"""Transport fault-tolerance unit coverage (ISSUE 9 tentpole):

- typed error taxonomy (BlockMissingError / BlockCorruptError /
  PeerUnreachableError) replacing string matching,
- per-frame CRC32 + the serializer envelope CRC (wire AND spill-read
  integrity),
- conf-driven connect/IO deadlines killing the hung-peer deadlock,
- NetInjector determinism and the net lint.

`pytest -m "net_inject and not slow"` is the tier-1 network robustness
job; see test_net_differential.py for the bench-shape differentials.
"""

import socket
import struct
import threading
import time

import numpy as np
import pytest

from spark_rapids_tpu.shuffle.netfault import (NetInjector, net_injection,
                                               net_injector)
from spark_rapids_tpu.shuffle.transport import (BlockCorruptError,
                                                BlockMissingError,
                                                LocalFsTransport,
                                                PeerUnreachableError,
                                                TcpTransport,
                                                TransportError,
                                                transport_metrics)

pytestmark = pytest.mark.net_inject


@pytest.fixture(autouse=True)
def _net_injection_off_after():
    """Injector state is process-wide: force it OFF after every test so
    a failure here cannot cascade synthetic faults into other suites."""
    yield
    net_injector().configure("")
    assert not net_injector().enabled


def _client(server, **kw):
    kw.setdefault("retries", 3)
    kw.setdefault("connect_timeout_s", 5.0)
    kw.setdefault("io_timeout_s", 5.0)
    kw.setdefault("backoff_base_ms", 1.0)
    return TcpTransport(peers={1: server.address}, **kw)


# ---------------------------------------------------------------------------
# typed taxonomy
# ---------------------------------------------------------------------------

def test_missing_block_is_typed_and_does_not_retry():
    server = TcpTransport()
    server.publish(1, 0, 0, b"present")
    client = _client(server)
    m0 = transport_metrics().snapshot()
    try:
        with pytest.raises(BlockMissingError, match="not found"):
            client.fetch(1, 9, 9)
        # a MISSING verdict fails over immediately: no same-peer retries
        assert transport_metrics().snapshot()["fetchRetryCount"] == \
            m0["fetchRetryCount"]
        assert client.fetch(1, 0, 0) == b"present"
    finally:
        client.close()
        server.close()


def test_unreachable_peer_is_typed():
    dead = TcpTransport()
    dead_addr = dead.address
    dead.close()
    client = TcpTransport(peers={1: dead_addr}, retries=2,
                          connect_timeout_s=2.0, io_timeout_s=2.0,
                          backoff_base_ms=1.0)
    try:
        with pytest.raises(PeerUnreachableError):
            client.fetch(3, 0, 0)
    finally:
        client.close()


def test_taxonomy_is_transport_error():
    # callers catching the base class keep working across the taxonomy
    for cls in (BlockMissingError, BlockCorruptError,
                PeerUnreachableError):
        assert issubclass(cls, TransportError)


# ---------------------------------------------------------------------------
# frame CRC (wire integrity)
# ---------------------------------------------------------------------------

def test_frame_crc_detects_wire_corruption():
    from spark_rapids_tpu.shuffle.transport import (_recv_frame,
                                                    _send_frame)
    a, b = socket.socketpair()
    try:
        _send_frame(a, 3, b"payload-bytes")
        op, payload = _recv_frame(b)
        assert (op, payload) == (3, b"payload-bytes")
        # corrupt one payload byte on the wire: receiver must reject
        frame = bytearray()
        import zlib
        body = b"payload-bytes"
        frame += b"RTPU" + struct.pack("<BII", 3, len(body),
                                       zlib.crc32(body) & 0xFFFFFFFF)
        frame += body
        frame[-3] ^= 0x10
        a.sendall(bytes(frame))
        c0 = transport_metrics().snapshot()["corruptFrameCount"]
        with pytest.raises(BlockCorruptError, match="checksum"):
            _recv_frame(b)
        assert transport_metrics().snapshot()["corruptFrameCount"] == c0 + 1
    finally:
        a.close()
        b.close()


def test_injected_corruption_retries_same_peer_and_recovers():
    server = TcpTransport()
    server.publish(2, 0, 0, b"x" * 4096)
    client = _client(server, retries=4)
    m0 = transport_metrics().snapshot()
    try:
        with net_injection("every-1", fault_kind="corrupt"):
            assert client.fetch(2, 0, 0) == b"x" * 4096
        m1 = transport_metrics().snapshot()
        assert m1["corruptFrameCount"] > m0["corruptFrameCount"]
        assert m1["fetchRetryCount"] > m0["fetchRetryCount"]
    finally:
        client.close()
        server.close()


# ---------------------------------------------------------------------------
# serializer envelope CRC (spill-read integrity)
# ---------------------------------------------------------------------------

def test_envelope_checksum_roundtrip_and_corruption():
    from spark_rapids_tpu.shuffle.serializer import (FrameChecksumError,
                                                     deserialize_host,
                                                     serialize_host)
    arrays = {"a": np.arange(100, dtype=np.int64),
              "b": np.linspace(0, 1, 100)}
    frame = serialize_host(arrays, 100)
    back, n = deserialize_host(frame)
    assert n == 100 and np.array_equal(back["a"], arrays["a"])
    bad = bytearray(frame)
    bad[len(bad) // 2] ^= 0x01      # body bit-flip
    with pytest.raises(FrameChecksumError):
        deserialize_host(bytes(bad))


def test_packed_frame_checksum_covers_spill_files(tmp_path):
    from spark_rapids_tpu.memory.packed import PackedTable
    from spark_rapids_tpu.shuffle.serializer import (FrameChecksumError,
                                                     deserialize_host,
                                                     frame_packed)
    pt = PackedTable.pack({"d0": np.arange(64, dtype=np.int32)}, 64)
    path = tmp_path / "buf-1.rtpu"
    path.write_bytes(frame_packed(pt))
    arrays, n = deserialize_host(path.read_bytes())   # clean spill read
    assert n == 64
    data = bytearray(path.read_bytes())
    data[-5] ^= 0x80                                  # disk corruption
    path.write_bytes(bytes(data))
    with pytest.raises(FrameChecksumError):
        deserialize_host(path.read_bytes())


# ---------------------------------------------------------------------------
# deadlines: the hung-peer deadlock (ISSUE 9 satellite)
# ---------------------------------------------------------------------------

def _silent_server():
    """A peer that accepts connections then never speaks again."""
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(8)
    stop = threading.Event()
    held = []

    def loop():
        srv.settimeout(0.2)
        while not stop.is_set():
            try:
                conn, _ = srv.accept()
                held.append(conn)     # accept, keep open, stay silent
            except socket.timeout:
                continue
            except OSError:
                break

    t = threading.Thread(target=loop, daemon=True)
    t.start()

    def close():
        stop.set()
        srv.close()
        for c in held:
            c.close()
        t.join(timeout=5)

    return srv.getsockname(), close


def test_hung_peer_times_out_instead_of_hanging():
    addr, close = _silent_server()
    client = TcpTransport(peers={1: addr}, retries=1,
                          connect_timeout_s=2.0, io_timeout_s=0.3,
                          backoff_base_ms=1.0)
    try:
        t0 = time.monotonic()
        with pytest.raises(PeerUnreachableError):
            client.fetch(1, 0, 0)
        assert time.monotonic() - t0 < 5.0
    finally:
        client.close()
        close()


def test_hung_peer_does_not_deadlock_concurrent_fetchers():
    """The regression this PR fixes: _transact used to hold the per-peer
    lock through an unbounded recv, so ONE hung peer wedged every
    fetching thread forever. With the I/O deadline both threads resolve
    within a bound."""
    addr, close = _silent_server()
    client = TcpTransport(peers={1: addr}, retries=1,
                          connect_timeout_s=2.0, io_timeout_s=0.3,
                          backoff_base_ms=1.0)
    errs = []

    def work():
        try:
            client.fetch(1, 0, 0)
        except TransportError as ex:
            errs.append(ex)

    threads = [threading.Thread(target=work) for _ in range(2)]
    try:
        t0 = time.monotonic()
        [t.start() for t in threads]
        [t.join(timeout=10) for t in threads]
        assert not any(t.is_alive() for t in threads), "fetcher deadlocked"
        assert time.monotonic() - t0 < 10.0
        assert len(errs) == 2
        assert all(isinstance(e, PeerUnreachableError) for e in errs)
    finally:
        client.close()
        close()


def test_io_timeout_is_conf_driven():
    from spark_rapids_tpu.config import (TRANSPORT_CONNECT_TIMEOUT_MS,
                                         TRANSPORT_IO_TIMEOUT_MS,
                                         RapidsTpuConf)
    conf = RapidsTpuConf({
        TRANSPORT_CONNECT_TIMEOUT_MS.key: "1500",
        TRANSPORT_IO_TIMEOUT_MS.key: "250"})
    assert conf.get(TRANSPORT_CONNECT_TIMEOUT_MS.key) == 1500
    assert conf.get(TRANSPORT_IO_TIMEOUT_MS.key) == 250


# ---------------------------------------------------------------------------
# suspects + heartbeat reporting
# ---------------------------------------------------------------------------

def test_unreachable_peer_is_deprioritized_for_later_fetches():
    dead = TcpTransport()
    dead_addr = dead.address
    dead.close()
    live = TcpTransport()
    live.publish(7, 0, 0, b"a")
    live.publish(7, 1, 0, b"b")
    client = TcpTransport(peers={1: dead_addr, 2: live.address},
                          retries=1, connect_timeout_s=2.0,
                          io_timeout_s=2.0, backoff_base_ms=1.0)
    try:
        t_first0 = time.monotonic()
        assert client.fetch(7, 0, 0) == b"a"    # pays the dead peer once
        first = time.monotonic() - t_first0
        # the dead peer is now a suspect: later fetches try the live
        # peer FIRST and never touch the dead one
        assert client._ordered_peers()[0][0] == 2
        t0 = time.monotonic()
        assert client.fetch(7, 1, 0) == b"b"
        assert time.monotonic() - t0 <= max(first, 0.5)
    finally:
        client.close()
        live.close()


def test_unreachable_reported_to_heartbeat_registry():
    from spark_rapids_tpu.plugin import init

    runtime = init()
    runtime.heartbeat("exec-gone")
    assert "exec-gone" in runtime.live_executors(timeout_s=60.0)
    dead = TcpTransport()
    dead_addr = dead.address
    dead.close()
    client = TcpTransport(peers={"exec-gone": dead_addr}, retries=1,
                          connect_timeout_s=2.0, io_timeout_s=2.0,
                          backoff_base_ms=1.0,
                          on_unreachable=runtime.mark_unreachable)
    try:
        with pytest.raises(PeerUnreachableError):
            client.fetch(9, 0, 0)
        # the fetch failure reported the peer: no longer listed live
        assert "exec-gone" not in runtime.live_executors(timeout_s=60.0)
    finally:
        client.close()


def test_persistently_corrupt_peer_stays_typed_corrupt():
    """A reachable peer that keeps serving CRC-failing bytes must
    surface as BlockCorruptError, not PeerUnreachableError — corruption
    on a live peer is a data-integrity problem (review finding)."""
    import zlib
    from spark_rapids_tpu.shuffle.transport import (_MAGIC, _VERSION,
                                                    _recv_frame)

    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(4)
    stop = threading.Event()

    def rogue():
        srv.settimeout(0.2)
        while not stop.is_set():
            try:
                conn, _ = srv.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            try:
                _recv_frame(conn)                       # client HELLO
                payload = struct.pack("<I", _VERSION)
                conn.sendall(_MAGIC + struct.pack(     # valid handshake
                    "<BII", 1, len(payload),
                    zlib.crc32(payload) & 0xFFFFFFFF) + payload)
                while True:
                    _recv_frame(conn)                   # any request
                    bad = b"\x00" * 8
                    conn.sendall(_MAGIC + struct.pack(  # WRONG crc
                        "<BII", 3, len(bad), 0xDEADBEEF) + bad)
            except (TransportError, OSError):
                conn.close()

    t = threading.Thread(target=rogue, daemon=True)
    t.start()
    client = TcpTransport(peers={1: srv.getsockname()}, retries=2,
                          connect_timeout_s=2.0, io_timeout_s=2.0,
                          backoff_base_ms=1.0)
    try:
        with pytest.raises(BlockCorruptError, match="corrupt"):
            client.fetch(1, 0, 0)
    finally:
        client.close()
        stop.set()
        srv.close()
        t.join(timeout=5)


def test_heartbeat_ids_are_type_agnostic():
    """The CACHED-registry path keys peers by INT executor id while
    in-process callers use strings — heartbeat/mark_unreachable/liveness
    must agree across both (review finding)."""
    from spark_rapids_tpu.plugin import init

    runtime = init()
    runtime.heartbeat(41)
    assert "41" in runtime.live_executors(timeout_s=60.0)
    runtime.mark_unreachable(41)
    assert "41" not in runtime.live_executors(timeout_s=60.0)
    # transport-side comparison normalizes too: an int-keyed peer table
    # filters against the string-keyed registry
    runtime.heartbeat(42)
    t = TcpTransport(peers={42: ("127.0.0.1", 1), 43: ("127.0.0.1", 2)},
                     liveness=runtime.live_executors)
    try:
        assert set(t._live_peers()) == {42}
    finally:
        t.close()


# ---------------------------------------------------------------------------
# LocalFsTransport strict filename parsing (ISSUE 9 satellite)
# ---------------------------------------------------------------------------

def test_localfs_malformed_block_file_raises(tmp_path):
    t = LocalFsTransport(str(tmp_path / "s"))
    t.publish(1, 2, 0, b"ok")
    (tmp_path / "s" / "s1-mbogus-r0.rtpu").write_bytes(b"junk")
    with pytest.raises(TransportError, match="malformed"):
        t.list_blocks(1, 0)


def test_localfs_ignores_tmp_staging_files(tmp_path):
    t = LocalFsTransport(str(tmp_path / "s"))
    t.publish(1, 2, 0, b"ok")
    # an in-flight publish from another process
    (tmp_path / "s" / "s1-m3-r0.rtpu.tmp").write_bytes(b"partial")
    assert t.list_blocks(1, 0) == [(1, 2, 0)]


def test_localfs_rejects_negative_ids(tmp_path):
    """A negative map id would embed an extra '-' and mis-parse (the old
    int(name.split('-')[1][1:]) bug class) — publish refuses it."""
    t = LocalFsTransport(str(tmp_path / "s"))
    with pytest.raises(TransportError, match="invalid block id"):
        t.publish(1, -3, 0, b"x")


# ---------------------------------------------------------------------------
# NetInjector semantics
# ---------------------------------------------------------------------------

def test_injector_every_n_schedule():
    inj = NetInjector()
    inj.configure("every-3", fault_kind="drop")
    hits = [inj.decide(f"s{i}") for i in range(9)]
    # fires on checks 3, 6, 9 — but each trigger grants the next check a
    # free pass, consuming one slot
    assert hits[2] == "drop"
    assert hits.count("drop") >= 2
    assert hits[0] is None and hits[1] is None


def test_injector_random_is_seed_deterministic():
    a, b = NetInjector(), NetInjector()
    a.configure("random-0.5", seed=7)
    b.configure("random-0.5", seed=7)
    seq_a = [a.decide("s") for _ in range(32)]
    seq_b = [b.decide("s") for _ in range(32)]
    assert seq_a == seq_b
    assert any(k is not None for k in seq_a)


def test_injector_suppressed_scope_blocks_new_triggers():
    inj = NetInjector()
    inj.configure("every-1", fault_kind="drop")
    assert inj.decide("s") == "drop"
    with inj.suppressed():
        assert all(inj.decide("s") is None for _ in range(8))


def test_injector_skip_count_aims_deep():
    inj = NetInjector()
    inj.configure("every-1", skip_count=4, fault_kind="delay")
    hits = [inj.decide("s") for i in range(6)]
    assert hits[:4] == [None] * 4
    assert hits[4] == "delay"


def test_injector_mix_cycles_kinds():
    inj = NetInjector()
    inj.configure("every-1", fault_kind="mix")
    kinds = []
    for _ in range(8):
        k = inj.decide("s")
        if k is not None:
            kinds.append(k)
    assert kinds[:4] == ["drop", "delay", "truncate", "corrupt"]


def test_injector_conf_plumbing():
    """The production surface: session conf → apply_session_conf →
    process-wide injector (same shape as injectOOM)."""
    from spark_rapids_tpu.config import RapidsTpuConf
    from spark_rapids_tpu.memory.retry import apply_session_conf
    conf = RapidsTpuConf({
        "spark.rapids.tpu.test.injectNet.mode": "every-2",
        "spark.rapids.tpu.test.injectNet.faultKind": "corrupt"})
    apply_session_conf(conf)
    try:
        assert net_injector().enabled
        assert net_injector().decide("s") is None
        assert net_injector().decide("s") == "corrupt"
    finally:
        apply_session_conf(RapidsTpuConf())
    assert not net_injector().enabled


# ---------------------------------------------------------------------------
# repo lint (ISSUE 9 satellite): sockets carry deadlines, faults are
# never silently swallowed — run in tier-1 like lint_retry
# ---------------------------------------------------------------------------

def _load_lint():
    import importlib
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    try:
        import lint_net
        importlib.reload(lint_net)
        return lint_net
    finally:
        sys.path.pop(0)


def test_lint_net_clean():
    """The tree itself passes the lint — this IS the tier-1 lint job."""
    assert _load_lint().lint() == []


def test_lint_net_catches_violations(tmp_path):
    lint_net = _load_lint()
    pkg = tmp_path / "pkg"
    (pkg / "shuffle").mkdir(parents=True)
    (pkg / "shuffle" / "bad.py").write_text(
        "import socket\n"
        "def connect(addr):\n"
        "    return socket.create_connection(addr)\n"     # no timeout
        "def pull(sock):\n"
        "    return sock.recv(1024)\n"                    # no settimeout
        "def swallow(sock):\n"
        "    try:\n"
        "        sock.sendall(b'x')\n"
        "    except OSError:\n"                           # swallowed
        "        pass\n")
    (pkg / "shuffle" / "good.py").write_text(
        "import socket\n"
        "def connect(addr, t):\n"
        "    s = socket.create_connection(addr, timeout=t)\n"
        "    s.settimeout(t)\n"
        "    return s\n"
        "def pull(sock):\n"
        "    return sock.recv(1024)\n"
        "def teardown(sock):\n"
        "    try:\n"
        "        sock.close()\n"
        "    except OSError:  # net-ok: teardown\n"
        "        pass\n")
    problems = lint_net.lint(str(pkg))
    assert len(problems) == 3
    assert any("create_connection" in p for p in problems)
    assert any(".recv()" in p for p in problems)
    assert any("swallows" in p for p in problems)
    assert all("bad.py" in p for p in problems)
