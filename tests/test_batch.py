"""Round-trip and invariants for the columnar data plane."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.batch import (
    Field, Schema, bucket_capacity, empty_batch, from_arrow, to_arrow,
)


def test_bucket_capacity():
    assert bucket_capacity(0) == 128
    assert bucket_capacity(128) == 128
    assert bucket_capacity(129) == 256
    assert bucket_capacity(1000) == 1024
    assert bucket_capacity(1 << 20) == 1 << 20


def _roundtrip(table: pa.Table, **kw) -> pa.Table:
    batch, schema = from_arrow(table, **kw)
    assert batch.capacity >= table.num_rows
    assert int(batch.num_rows) == table.num_rows
    # padding rows must be invalid
    for col in batch.columns:
        assert not np.asarray(col.validity[table.num_rows:]).any()
    return to_arrow(batch, schema)


def test_roundtrip_numeric_with_nulls():
    table = pa.table({
        "i32": pa.array([1, None, -3, 2**31 - 1], type=pa.int32()),
        "i64": pa.array([None, -(2**62), 7, 0], type=pa.int64()),
        "f64": pa.array([1.5, float("nan"), None, -0.0], type=pa.float64()),
        "b": pa.array([True, None, False, True], type=pa.bool_()),
    })
    out = _roundtrip(table)
    assert out.column("i32").to_pylist() == [1, None, -3, 2**31 - 1]
    assert out.column("i64").to_pylist() == [None, -(2**62), 7, 0]
    got = out.column("f64").to_pylist()
    assert got[0] == 1.5 and np.isnan(got[1]) and got[2] is None
    assert out.column("b").to_pylist() == [True, None, False, True]


def test_roundtrip_strings():
    table = pa.table({"s": pa.array(["hello", None, "", "héllo", "x" * 10])})
    out = _roundtrip(table)
    assert out.column("s").to_pylist() == ["hello", None, "", "héllo", "x" * 10]


def test_roundtrip_date_timestamp():
    import datetime as dt
    table = pa.table({
        "d": pa.array([dt.date(2020, 1, 1), None, dt.date(1969, 12, 31)]),
        "ts": pa.array([dt.datetime(2023, 5, 1, 12, 30, 0, 123456), None,
                        dt.datetime(1960, 1, 1)], type=pa.timestamp("us")),
    })
    out = _roundtrip(table)
    assert out.column("d").to_pylist() == [dt.date(2020, 1, 1), None,
                                           dt.date(1969, 12, 31)]
    got = out.column("ts").to_pylist()
    assert got[1] is None
    assert got[0].replace(tzinfo=None) == dt.datetime(2023, 5, 1, 12, 30, 0, 123456)


def test_roundtrip_decimal():
    import decimal as d
    table = pa.table({
        "dec": pa.array([d.Decimal("123.45"), None, d.Decimal("-0.01")],
                        type=pa.decimal128(9, 2))})
    out = _roundtrip(table)
    assert out.column("dec").to_pylist() == [d.Decimal("123.45"), None,
                                             d.Decimal("-0.01")]


def test_empty_batch():
    schema = Schema([Field("a", T.INT64), Field("s", T.string(8))])
    b = empty_batch(schema)
    assert int(b.num_rows) == 0
    out = to_arrow(b, schema)
    assert out.num_rows == 0


def test_typesig_gating():
    sig = T.numeric
    assert sig.supports(T.INT32) is None
    assert sig.supports(T.STRING) is not None
    assert sig.supports(T.decimal(38, 2)) is not None  # >18 digits unsupported


def test_batch_is_pytree():
    import jax
    table = pa.table({"a": pa.array([1, 2, 3], type=pa.int64())})
    batch, _ = from_arrow(table)
    leaves = jax.tree_util.tree_leaves(batch)
    assert len(leaves) == 3  # data, validity, num_rows

    @jax.jit
    def bump(b):
        col = b.columns[0]
        return b.replace(columns=(col.replace(data=col.data + 1),))

    out = bump(batch)
    assert np.asarray(out.columns[0].data[:3]).tolist() == [2, 3, 4]
