"""Sort / TopN differential tests. Oracle: Python sorted() with Spark key
semantics (asc nulls first / desc nulls last by default, NaN greatest)."""

import math

import pytest

from spark_rapids_tpu.exec import (InMemoryScanExec, SortExec,
                                   TakeOrderedAndProjectExec, collect)
from spark_rapids_tpu.exec.sort import SortOrder, asc, desc
from spark_rapids_tpu.expressions import col

from harness.asserts import assert_rows_equal, rows_of
from harness.data_gen import (DoubleGen, IntegerGen, LongGen, StringGen,
                              gen_table)


def scan(t, batch_rows=None):
    return InMemoryScanExec(t, batch_rows=batch_rows)


def spark_key(v, descending, nulls_first):
    # (null_rank, value_rank); NaN sorts greater than any double
    if v is None:
        return (0 if nulls_first else 2, 0)
    if isinstance(v, float):
        if math.isnan(v):
            r = (1, math.inf)
        else:
            r = (1, v)
        if descending:
            return (r[0], _neg(r[1]))
        return r
    if isinstance(v, str):
        b = v.encode("utf-8")
        key = tuple(b)
        return (1, tuple(-x for x in key) + (math.inf,)) if descending \
            else (1, key)
    return (1, -v if descending else v)


def _neg(x):
    return -x if x != math.inf else -math.inf


def oracle_sort(rows, specs):
    # specs: list of (col_idx, descending, nulls_first)
    def key(row):
        parts = []
        for i, d, nf in specs:
            parts.append(spark_key(row[i], d, nf))
        return tuple(parts)
    return sorted(rows, key=key)


@pytest.mark.parametrize("descending", [False, True])
def test_sort_ints(descending):
    t = gen_table([("a", IntegerGen()), ("b", LongGen())], n=900, seed=20)
    order = [SortOrder(col("a"), descending)]
    plan = SortExec(order, scan(t, batch_rows=200))
    got = rows_of(collect(plan))
    rows = list(zip(t.column("a").to_pylist(), t.column("b").to_pylist()))
    exp = oracle_sort(rows, [(0, descending, not descending)])
    # stable only per sort key; compare full rows but allow ties any order:
    assert [r[0] for r in got] == [r[0] for r in exp]
    assert_rows_equal(got, exp, ignore_order=True)


def test_sort_multi_key_with_doubles():
    t = gen_table([("a", IntegerGen(min_val=0, max_val=5)),
                   ("d", DoubleGen())], n=600, seed=21)
    plan = SortExec([asc(col("a")), desc(col("d"))], scan(t, batch_rows=128))
    got = rows_of(collect(plan))
    rows = list(zip(t.column("a").to_pylist(), t.column("d").to_pylist()))
    exp = oracle_sort(rows, [(0, False, True), (1, True, False)])
    for g, e in zip(got, exp):
        assert (g[0] is None) == (e[0] is None) and \
            (g[0] == e[0] or g[0] is None)
        ga, ea = g[1], e[1]
        if ea is None or ga is None:
            assert ga is None and ea is None
        elif math.isnan(ea):
            assert math.isnan(ga)
        else:
            assert ga == ea


def test_sort_strings():
    t = gen_table([("s", StringGen(max_len=10))], n=500, seed=22)
    plan = SortExec([asc(col("s"))], scan(t, batch_rows=100))
    got = [r[0] for r in rows_of(collect(plan))]
    vals = t.column("s").to_pylist()
    nones = [v for v in vals if v is None]
    rest = sorted([v for v in vals if v is not None],
                  key=lambda s: s.encode("utf-8"))
    assert got == [None] * len(nones) + rest


def test_top_n():
    t = gen_table([("a", IntegerGen()), ("b", IntegerGen())], n=2000, seed=23)
    plan = TakeOrderedAndProjectExec(25, [asc(col("a"))],
                                     [col("a"), col("b")],
                                     scan(t, batch_rows=256))
    got = rows_of(collect(plan))
    rows = list(zip(t.column("a").to_pylist(), t.column("b").to_pylist()))
    exp = oracle_sort(rows, [(0, False, True)])[:25]
    assert [r[0] for r in got] == [r[0] for r in exp]
    assert len(got) == 25
