"""Exact percentile tests (reference: GpuApproximatePercentile coverage —
ours is exact, so the oracle is the interpolated definition itself)."""

import pytest

from spark_rapids_tpu.expressions import col
from spark_rapids_tpu.expressions.aggregates import Count, Percentile, Sum
from spark_rapids_tpu.plan import Session, table

from harness.asserts import assert_tpu_and_cpu_are_equal_collect
from harness.data_gen import DoubleGen, IntegerGen, LongGen, gen_table

PT = gen_table([("k", IntegerGen(min_val=0, max_val=6)),
                ("v", LongGen(min_val=-1000, max_val=1000)),
                ("d", DoubleGen(no_nans=True))], n=700, seed=230)


@pytest.mark.parametrize("q", [0.0, 0.25, 0.5, 0.9, 1.0])
def test_percentile_groupby(q):
    assert_tpu_and_cpu_are_equal_collect(
        lambda: table(PT, num_slices=3).group_by("k")
        .agg(Percentile(col("v"), q).alias("p")))


def test_percentile_global():
    assert_tpu_and_cpu_are_equal_collect(
        lambda: table(PT).agg(Percentile(col("d"), 0.5).alias("med")))


def test_percentile_alongside_decomposable_aggs():
    assert_tpu_and_cpu_are_equal_collect(
        lambda: table(PT, num_slices=2).group_by("k")
        .agg(Percentile(col("v"), 0.5).alias("med"),
             Sum(col("v")).alias("s"), Count().alias("n")))


def test_approx_percentile_exact_answers():
    """approx_percentile is answered EXACTLY on the sorted-segment layout
    (an exact answer satisfies any accuracy contract)."""
    from spark_rapids_tpu.expressions.aggregates import ApproxPercentile
    from harness.asserts import assert_tpu_and_cpu_are_equal_collect
    import numpy as np
    import pyarrow as pa
    rng = np.random.default_rng(8)
    t = pa.table({"k": rng.integers(0, 4, 300).astype(np.int32),
                  "v": rng.integers(-50, 50, 300).astype(np.int64)})
    assert_tpu_and_cpu_are_equal_collect(
        lambda: table(t).group_by("k").agg(
            ApproxPercentile(col("v"), 0.5, 1000).alias("med")))
    assert_tpu_and_cpu_are_equal_collect(
        lambda: table(t).group_by("k").agg(
            ApproxPercentile(col("v"), 0.95).alias("p95")))
