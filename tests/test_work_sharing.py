"""Cross-query work sharing suite (ISSUE 18 acceptance).

Three granularities of "never compute the same thing twice", each
tested for both the speedup AND the correctness unwind:

  1. in-flight dedup — SingleFlight state machine (leader / waiter /
     promotion on leader failure / invalidation in both orderings),
     worker-session dedup, and router-tier dedup through a real
     2-worker fleet where N identical concurrent clients execute
     exactly once;
  2. subplan result cache — two queries sharing a scan+filter subtree
     under different aggregates execute the subtree once, bit-for-bit
     vs the sharing-off oracle;
  3. scan sharing — refcounted device-resident batches: hit counters
     move, pins drain to zero at close, invalidation stops handing
     entries out;

plus the satellite regressions: file-backed scans result-key on per-file
(path, mtime_ns, size) stats (a rewrite invalidates), and sharing OFF is
byte-identical to a build without the subsystem.
"""

import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.expressions import col, lit
from spark_rapids_tpu.expressions.aggregates import Count, Max, Sum
from spark_rapids_tpu.plan import plancache, table
from spark_rapids_tpu.plan import sharing
from spark_rapids_tpu.plan.session import Session
from spark_rapids_tpu.server import PlanClient, protocol
from spark_rapids_tpu.server.router import Router

pytestmark = [pytest.mark.serving, pytest.mark.sharing]

SHARING_ON = {"spark.rapids.tpu.server.sharing.enabled": "true"}
NO_CACHES = {
    "spark.rapids.tpu.server.planCache.enabled": "false",
    "spark.rapids.tpu.server.resultCache.enabled": "false",
}


@pytest.fixture(autouse=True)
def _fresh_sharing_state():
    """Process singletons must not leak state (or counters' baselines)
    across tests: every test starts with empty sharing structures."""
    with sharing._SINGLETON_LOCK:
        sharing._SINGLE_FLIGHT = sharing.SingleFlight()
        sharing._SUBPLAN_CACHE = sharing.SubplanCache()
        sharing._SCAN_SHARE = sharing.ScanShareRegistry()
        sharing._METRICS = sharing.SharingMetrics()
    yield


def _ints(n=4000, seed=7):
    rng = np.random.default_rng(seed)
    return pa.table({
        "k": rng.integers(0, 64, n).astype(np.int64),
        "v": rng.integers(-1000, 1000, n).astype(np.int64),
    })


def _sum_query(tab, v=10):
    return (table(tab).where(col("v") > lit(int(v)))
            .group_by("k").agg(Sum(col("v")).alias("s")))


# ---------------------------------------------------------------------------
# 1a. SingleFlight state machine (deterministic unit coverage)
# ---------------------------------------------------------------------------


class TestSingleFlight:
    def test_leader_waiter_result(self):
        sf = sharing.SingleFlight()
        role, f = sf.begin("k1", ("d1",))
        assert role == "leader"
        role2, f2 = sf.begin("k1", ("d1",))
        assert role2 == "wait" and f2 is f
        out = []
        th = threading.Thread(
            target=lambda: out.append(sf.wait(f2, 5.0)), daemon=True)
        th.start()
        time.sleep(0.05)
        assert sf.complete(f, b"bytes", {"rows": 3})
        th.join(timeout=5)
        assert out and out[0].state == "result"
        assert out[0].ipc == b"bytes" and out[0].payload["rows"] == 3
        # settled flights leave the live table: a NEW arrival leads
        role3, f3 = sf.begin("k1", ("d1",))
        assert role3 == "leader" and f3 is not f
        assert sf.stats() == {"inFlight": 1, "pendingDone": 0}

    def test_leader_failure_promotes_exactly_one(self):
        """Two waiters park; the leader fails; EXACTLY one waiter is
        promoted (re-executes), the other keeps waiting and is served
        the promoted leader's result — the error reaches nobody."""
        sf = sharing.SingleFlight()
        _, leader = sf.begin("k", ("d",))
        waits = [sf.begin("k", ("d",))[1] for _ in range(2)]
        outcomes = []
        lock = threading.Lock()

        def waiter(f):
            out = sf.wait(f, 10.0)
            if out.state == "promoted":
                # the promoted waiter IS the new leader: execute + publish
                time.sleep(0.05)
                sf.complete(f, b"good", {"rows": 1})
            with lock:
                outcomes.append(out)

        ths = [threading.Thread(target=waiter, args=(f,), daemon=True)
               for f in waits]
        for t in ths:
            t.start()
        time.sleep(0.05)
        sf.fail(leader, RuntimeError("leader died"))
        for t in ths:
            t.join(timeout=5)
        states = sorted(o.state for o in outcomes)
        assert states == ["promoted", "result"], states
        served = next(o for o in outcomes if o.state == "result")
        assert served.ipc == b"good"     # never the leader's error

    def test_invalidate_while_leader_running(self):
        """Ordering (a): drop_table lands while the leader executes —
        the parked waiter re-executes (against post-drop state) and the
        leader's eventual complete() publishes nothing."""
        sf = sharing.SingleFlight()
        _, leader = sf.begin("k", ("dig-a", "dig-b"))
        _, wf = sf.begin("k", ())
        out = []
        th = threading.Thread(target=lambda: out.append(sf.wait(wf, 5.0)),
                              daemon=True)
        th.start()
        time.sleep(0.05)
        assert sf.invalidate_digest("dig-b") == 1
        th.join(timeout=5)
        assert out[0].state == "invalidated"
        assert not sf.complete(leader, b"stale")    # nothing published
        # the key is free again
        assert sf.begin("k", ())[0] == "leader"

    def test_invalidate_after_complete_before_consume(self):
        """Ordering (b): the leader completed but the waiter has not
        consumed yet when the drop lands — the done-with-waiters flight
        is STILL invalidatable, and the waiter re-executes rather than
        consuming the pre-drop result."""
        sf = sharing.SingleFlight()
        _, leader = sf.begin("k", ("dig",))
        _, wf = sf.begin("k", ())
        assert sf.complete(leader, b"pre-drop", {})
        assert sf.stats()["pendingDone"] == 1
        # the drop beats the waiter's wakeup
        assert sf.invalidate_digest("dig") == 1
        out = sf.wait(wf, 5.0)
        assert out.state == "invalidated"
        assert sf.stats() == {"inFlight": 0, "pendingDone": 0}


# ---------------------------------------------------------------------------
# 1b. worker-session in-flight dedup (threads over process singletons)
# ---------------------------------------------------------------------------


class TestSessionInflight:
    def test_waiter_served_leader_bytes(self):
        tab = _ints()
        df = _sum_query(tab)
        conf = dict(NO_CACHES, **SHARING_ON)
        ses1, ses2 = Session(dict(conf)), Session(dict(conf))
        assert ses1.try_cached_result(df) is None     # leader
        got = []
        err = []

        def dup():
            try:
                t = ses2.try_cached_result(_sum_query(tab))
                got.append(t)
            except BaseException as e:   # surfaced below
                err.append(e)

        th = threading.Thread(target=dup, daemon=True)
        th.start()
        time.sleep(0.1)                               # B parks
        expected = ses1.collect(df)                   # leader executes
        th.join(timeout=10)
        assert not err and got and got[0] is not None
        assert got[0].equals(expected)
        assert ses2.last_cache["result"] == "inflight"
        snap = sharing.metrics().snapshot()
        assert snap["inflightLeaderCount"] >= 1
        assert snap["inflightServedCount"] == 1

    def test_leader_failure_promotes_waiter(self):
        """The leader aborts (exec failure / cancel): one parked
        duplicate is promoted and re-executes; every duplicate still
        gets the CORRECT result, never the leader's error."""
        tab = _ints()
        conf = dict(NO_CACHES, **SHARING_ON)
        ses1 = Session(dict(conf))
        assert ses1.try_cached_result(_sum_query(tab)) is None
        results, errs = [], []
        lock = threading.Lock()

        def dup():
            ses = Session(dict(conf))
            try:
                df = _sum_query(tab)
                t = ses.try_cached_result(df)
                if t is None:                # promoted to leader
                    t = ses.collect(df)
                with lock:
                    results.append(t)
            except BaseException as e:
                with lock:
                    errs.append(e)

        ths = [threading.Thread(target=dup, daemon=True)
               for _ in range(2)]
        for t in ths:
            t.start()
        time.sleep(0.15)                     # both park on the flight
        ses1.abort_inflight(RuntimeError("leader blew up"))
        for t in ths:
            t.join(timeout=30)
        assert errs == []
        oracle = Session(dict(NO_CACHES)).collect(_sum_query(tab))
        assert len(results) == 2
        for t in results:
            assert t.equals(oracle)
        snap = sharing.metrics().snapshot()
        assert snap["inflightPromotedCount"] == 1
        assert snap["inflightServedCount"] == 1

    def test_drop_while_waiter_parked_reexecutes(self):
        """Ordering (a) end-to-end at the session layer: the table is
        invalidated while a duplicate is parked — the waiter re-leads
        and re-executes instead of consuming a result the drop
        outdated."""
        tab = _ints()
        conf = dict(NO_CACHES, **SHARING_ON)
        ses1 = Session(dict(conf))
        assert ses1.try_cached_result(_sum_query(tab)) is None
        results, errs = [], []

        def dup():
            ses = Session(dict(conf))
            try:
                df = _sum_query(tab)
                t = ses.try_cached_result(df)
                if t is None:
                    t = ses.collect(df)
                results.append((t, dict(ses.last_cache)))
            except BaseException as e:
                errs.append(e)

        th = threading.Thread(target=dup, daemon=True)
        th.start()
        time.sleep(0.1)
        n = sharing.invalidate_digest(plancache.content_digest(tab))
        assert n >= 1
        th.join(timeout=30)
        assert errs == []
        oracle = Session(dict(NO_CACHES)).collect(_sum_query(tab))
        assert results and results[0][0].equals(oracle)
        # the waiter re-executed: it was NOT served the parked flight
        assert results[0][1].get("result") != "inflight"
        assert sharing.metrics().snapshot()[
            "inflightInvalidatedCount"] >= 1
        # the original leader's own collect still succeeds (its
        # complete() just publishes to nobody)
        assert ses1.collect(_sum_query(tab)).equals(oracle)


# ---------------------------------------------------------------------------
# 2. subplan result cache: shared scan+filter subtree, divergent aggs
# ---------------------------------------------------------------------------


class TestSubplanShare:
    def test_divergent_aggregates_share_subtree(self):
        tab = _ints()
        conf = dict(NO_CACHES, **SHARING_ON)

        def q_sum():
            return (table(tab).where(col("v") > lit(10))
                    .group_by("k").agg(Sum(col("v")).alias("s")))

        def q_max():
            return (table(tab).where(col("v") > lit(10))
                    .group_by("k").agg(Max(col("v")).alias("m"),
                                       Count().alias("n")))

        ses1 = Session(dict(conf))
        r_sum = ses1.collect(q_sum())
        assert ses1.last_cache.get("subplan") == "store"
        ses2 = Session(dict(conf))
        r_max = ses2.collect(q_max())
        assert ses2.last_cache.get("subplan") == "hit"
        snap = sharing.metrics().snapshot()
        assert snap["subplanStoreCount"] >= 1
        assert snap["subplanHitCount"] == 1
        # bit-for-bit against the sharing-off oracle for BOTH queries
        off = Session(dict(NO_CACHES))
        assert r_sum.equals(off.collect(q_sum()))
        assert r_max.equals(off.collect(q_max()))

    def test_float_subtrees_stay_unshared(self):
        """FLOAT64 columns in the subtree output are excluded (exact
        arithmetic is the bit-for-bit guarantee; float reductions may
        differ across padding shapes) — no store, no hit."""
        rng = np.random.default_rng(3)
        tab = pa.table({
            "k": rng.integers(0, 8, 500).astype(np.int64),
            "x": rng.uniform(0, 1, 500),
        })
        conf = dict(NO_CACHES, **SHARING_ON)
        ses = Session(dict(conf))
        ses.collect(table(tab).where(col("x") > lit(0.25))
                    .group_by("k").agg(Count().alias("n")))
        assert "subplan" not in ses.last_cache
        assert sharing.metrics().snapshot()["subplanStoreCount"] == 0

    def test_drop_invalidates_subplan_entries(self):
        tab = _ints()
        conf = dict(NO_CACHES, **SHARING_ON)
        ses = Session(dict(conf))
        ses.collect(_sum_query(tab))
        assert len(sharing.subplan_cache()) == 1
        assert sharing.invalidate_digest(
            plancache.content_digest(tab)) >= 1
        assert len(sharing.subplan_cache()) == 0


# ---------------------------------------------------------------------------
# 3. scan sharing: one upload, refcount hygiene, invalidation
# ---------------------------------------------------------------------------


class TestScanShare:
    def test_repeat_scan_rides_one_upload_and_unpins(self):
        tab = _ints()
        conf = dict(NO_CACHES, **SHARING_ON)
        ses1 = Session(dict(conf))
        r1 = ses1.collect(_sum_query(tab))
        snap = sharing.metrics().snapshot()
        assert snap["scanShareUploadCount"] >= 1
        st = sharing.scan_share().stats()
        assert st["entries"] >= 1 and st["usedBytes"] > 0
        # every pin released at close — the leak check
        assert st["pinnedRefs"] == 0, st
        uploads0 = snap["scanShareUploadCount"]
        ses2 = Session(dict(conf))
        r2 = ses2.collect(_sum_query(tab))
        snap2 = sharing.metrics().snapshot()
        assert snap2["scanShareHitCount"] >= 1
        assert snap2["scanShareUploadCount"] == uploads0  # no re-upload
        assert r2.equals(r1)
        assert sharing.scan_share().stats()["pinnedRefs"] == 0

    def test_invalidation_stops_handing_out_entries(self):
        tab = _ints()
        conf = dict(NO_CACHES, **SHARING_ON)
        Session(dict(conf)).collect(_sum_query(tab))
        assert sharing.scan_share().stats()["entries"] >= 1
        dig = plancache.content_digest(tab)
        assert sharing.invalidate_digest(dig) >= 1
        # no entry for the dropped table's content remains reachable
        # (subplan-materialized intermediates keyed on OTHER digests
        # may stay warm — they can only be hit by identical content)
        reg = sharing.scan_share()
        with reg._lock:
            assert all(e.digest != dig for e in reg._entries.values())
        # post-drop queries re-upload and still answer correctly
        ses = Session(dict(conf))
        got = ses.collect(_sum_query(tab))
        assert got.equals(Session(dict(NO_CACHES))
                          .collect(_sum_query(tab)))
        assert sharing.metrics().snapshot()[
            "scanShareInvalidationCount"] >= 1


# ---------------------------------------------------------------------------
# 4. satellite: file-backed scans are result-cacheable on file stats
# ---------------------------------------------------------------------------


class TestFileScanResultKey:
    def _write(self, path, seed):
        import pyarrow.parquet as pq
        rng = np.random.default_rng(seed)
        pq.write_table(pa.table({
            "k": rng.integers(0, 16, 1000).astype(np.int64),
            "v": rng.integers(0, 100, 1000).astype(np.int64),
        }), str(path))

    def test_stat_keyed_result_cache_and_rewrite_invalidation(
            self, tmp_path):
        import os
        from spark_rapids_tpu.io.scan import read_parquet
        p = tmp_path / "t.parquet"
        self._write(p, seed=1)
        conf = {"spark.rapids.tpu.server.resultCache.enabled": "true"}

        def q():
            return (read_parquet([str(p)])
                    .group_by("k").agg(Sum(col("v")).alias("s")))

        # the old behavior raised Uncacheable for ANY file scan; now
        # the key embeds per-file (path, mtime_ns, size)
        key1, digs = plancache.result_key(q().plan,
                                          Session(conf).conf)
        assert key1 and isinstance(digs, tuple)
        ses = Session(dict(conf))
        r1 = ses.collect(q())
        assert ses.try_cached_result(q()) is not None   # cache hit
        # rewrite with NEW data (and force an mtime step for coarse
        # filesystem clocks): the stat changes, so the key changes —
        # the stale entry is unreachable, the query recomputes
        self._write(p, seed=2)
        st = os.stat(str(p))
        os.utime(str(p), ns=(st.st_atime_ns, st.st_mtime_ns + 10**7))
        key2, _ = plancache.result_key(q().plan, Session(conf).conf)
        assert key2 != key1
        assert ses.try_cached_result(q()) is None       # miss
        r2 = ses.collect(q())
        assert not r2.equals(r1)       # really recomputed on new bytes

    def test_statless_source_stays_loudly_uncacheable(self):
        from spark_rapids_tpu.io.parquet import ParquetSource
        from spark_rapids_tpu.plan.logical import DataFrame, LogicalScan
        src = ParquetSource(["/nonexistent/never-there.parquet"])
        df = DataFrame(LogicalScan((), source=src, _schema=None))
        with pytest.raises(plancache.Uncacheable):
            plancache.result_key(df.plan, Session({}).conf)


# ---------------------------------------------------------------------------
# 5. sharing OFF is byte-identical (the conf-gate differential)
# ---------------------------------------------------------------------------


def test_sharing_off_is_byte_identical():
    tab = _ints()
    df_on = _sum_query(tab)
    on = Session(dict(NO_CACHES, **SHARING_ON))
    off = Session(dict(NO_CACHES))
    b_on = protocol.table_to_ipc(on.collect(df_on))
    before = sharing.metrics().snapshot()
    b_off = protocol.table_to_ipc(off.collect(_sum_query(tab)))
    after = sharing.metrics().snapshot()
    assert b_on == b_off
    # the off session never touched a sharing structure
    assert before == after
    assert len(sharing.subplan_cache()) >= 0  # structures exist, idle


# ---------------------------------------------------------------------------
# 6. fleet: N identical concurrent clients execute exactly once
# ---------------------------------------------------------------------------


N_DUP = 6


def test_fleet_inflight_dedup_executes_exactly_once():
    tab = _ints(seed=23)
    router = Router(
        workers=2,
        conf=dict(SHARING_ON),
        worker_conf={
            "spark.rapids.tpu.server.resultCache.enabled": "false",
            # holds the leader in its collect slot long enough that
            # every duplicate is provably parked, not racing
            "spark.rapids.tpu.server.test.collectDelayMs": "900",
        }).start()
    barrier = threading.Barrier(N_DUP)
    results, errors = [], []
    lock = threading.Lock()

    def client(ci):
        try:
            with PlanClient("127.0.0.1", router.port,
                            unavailable_retries=4) as c:
                barrier.wait(timeout=60)
                t = c.collect(_sum_query(tab))
                with lock:
                    results.append((t, c.last_sharing,
                                    dict(c.last_cache)))
        except Exception as e:
            barrier.abort()
            with lock:
                errors.append(f"client {ci}: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(N_DUP)]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert errors == [], errors
        assert len(results) == N_DUP
        oracle = Session(dict(NO_CACHES)).collect(_sum_query(tab))
        for t, _, _ in results:
            assert t.equals(oracle)
        # exactly ONE worker dispatch for N identical queries
        st = router.serving_stats()
        assert sum(st["routing"]["perWorkerPlans"].values()) == 1, st
        sh = st["sharing"]
        assert sh["inflightLeaderCount"] == 1, sh
        assert sh["inflightServedCount"] == N_DUP - 1, sh
        served = sum(1 for _, s, _ in results if s == "inflight")
        assert served == N_DUP - 1
    finally:
        router.stop(grace_s=5)


@pytest.mark.slow
def test_fleet_sharing_bit_for_bit_vs_oracle():
    """Threaded clients x the five bench shapes through a sharing-ON
    2-worker fleet: every result equals the sharing-off in-process
    oracle — dedup/subplan/scan sharing may change WHAT executes,
    never what is answered. Multi-minute: full (nightly) tier, like
    the adaptive differentials."""
    import pyarrow.parquet as pq
    rng = np.random.default_rng(11)
    n = 1500
    tabs = {
        "lineitem": pa.table({
            "k": rng.integers(0, 3, n).astype(np.int32),
            "l_quantity": rng.integers(1, 51, n).astype(np.int64),
            "l_extendedprice": rng.uniform(1.0, 1e5, n),
        }),
        "facts": pa.table({
            "k": rng.integers(0, 64, n).astype(np.int64),
            "v": rng.integers(-1000, 1000, n).astype(np.int64),
        }),
        "dims": pa.table({
            "k": np.arange(64, dtype=np.int64),
            "w": (np.arange(64) % 10).astype(np.int64),
        }),
    }

    def shapes(tmpdir):
        from spark_rapids_tpu.exec.sort import asc
        from spark_rapids_tpu.io.scan import read_parquet
        ppath = str(tmpdir / "ws.parquet")
        pq.write_table(tabs["facts"], ppath)

        def q1(v):
            return (table(tabs["lineitem"])
                    .where(col("l_quantity") > lit(int(v)))
                    .group_by("k")
                    .agg(Sum(col("l_extendedprice")).alias("rev"),
                         Count().alias("c")))

        def agg_sum(v):
            return _sum_query(tabs["facts"], v)

        def join_sort(v):
            return (table(tabs["facts"])
                    .where(col("v") > lit(int(v)))
                    .join(table(tabs["dims"]), ["k"], ["k"])
                    .group_by("w").agg(Sum(col("v")).alias("s"))
                    .order_by(asc(col("w"))))

        def parquet_scan(v):
            return (read_parquet([ppath])
                    .where(col("v") > lit(int(v)))
                    .group_by("k").agg(Count().alias("c")))

        def exchange(v):
            return (table(tabs["facts"], num_slices=4)
                    .where(col("v") > lit(int(v)))
                    .group_by("k").agg(Sum(col("v")).alias("s")))

        return [("q1", q1), ("agg", agg_sum), ("join_sort", join_sort),
                ("parquet", parquet_scan), ("exchange", exchange)]

    import tempfile
    with tempfile.TemporaryDirectory() as td:
        from pathlib import Path
        sh = shapes(Path(td))
        router = Router(workers=2, conf=dict(SHARING_ON)).start()
        results, errors = {}, []
        lock = threading.Lock()

        def client(ci):
            try:
                with PlanClient("127.0.0.1", router.port,
                                unavailable_retries=4) as c:
                    for r in range(2):
                        for name, build in sh:
                            t = c.collect(build(10 + r * 3))
                            with lock:
                                results[(ci, name, r)] = t
            except Exception as e:
                with lock:
                    errors.append(
                        f"client {ci}: {type(e).__name__}: {e}")

        try:
            threads = [threading.Thread(target=client, args=(i,),
                                        daemon=True) for i in range(3)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=300)
            assert errors == [], errors
            oracle = Session(dict(NO_CACHES))
            for r in range(2):
                for name, build in sh:
                    want = oracle.collect(build(10 + r * 3))
                    for ci in range(3):
                        got = results[(ci, name, r)]
                        assert got.equals(want), \
                            f"client {ci} {name} round {r} diverged " \
                            f"with sharing on"
        finally:
            router.stop(grace_s=5)


# ---------------------------------------------------------------------------
# 7. smoke-tier sharing loadbench job (~20s): rides the
#    `pytest -m "serving and smoke"` mini load gate
# ---------------------------------------------------------------------------


@pytest.mark.smoke
def test_sharing_loadbench_smoke():
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    try:
        import server_loadbench
    finally:
        sys.path.pop(0)
    book = {}
    rep = server_loadbench.run_fleet_load(
        clients=8, rounds=2, rows=1000, fleet=2, shapes_per_client=2,
        duplicate_fraction=0.5, sharing=True, digest_book=book)
    assert rep["errors"] == 0, rep["error_samples"]
    assert rep["queries"] == 8 * 2 * 2
    assert rep["leaked_sessions"] == 0
    assert rep["dup"]["n"] == 4 * 2 * 2       # 4 duplicator clients
    # duplicates were actually deduped in flight somewhere in the stack
    # (router tier and/or a worker), and the counters say so loudly
    r_sh = rep["sharing_counters"]["router"] or {}
    w_sh = rep["sharing_counters"]["workers"] or {}
    served = rep["dedup_served"] + \
        w_sh.get("inflightServedCount", 0)
    assert served >= 1, rep["sharing_counters"]
    assert r_sh.get("inflightLeaderCount", 0) >= 1
    # bit-for-bit book: every (shape, literal) answered identically
    assert len(book) >= 2
