"""Bounded producer/consumer pipeline (double-buffered prefetch).

Reference: the CUDA plugin hides host latency behind device compute with
pinned-memory prefetch — the multi-file reader decodes the NEXT batch on
its thread pool while the current one is in flight to the device
(GpuMultiFileReader.scala:441 readAsync over MultiFileReaderThreadPool,
staging through PinnedMemoryPool). JAX has no pinned-host allocator to
expose, but the overlap itself is a host-side structure: run the producer
(decode / D2H staging) one stage ahead of the consumer (`device_put` /
compute / framing) through a BOUNDED queue.

This module is that one structure, shared by the scan side
(io/source.py: decode batch N+1 while batch N is in device_put/compute)
and the exchange side (shuffle/exchange.py: D2H-stage partition P+1 while
partition P is framed/compressed). Contract:

- ``depth <= 0`` returns the source iterator unchanged — the synchronous
  path, bit for bit (``spark.rapids.tpu.prefetch.depth=0`` is the
  kill switch).
- Single-core hosts skip the thread handoff entirely (same policy as the
  single-core inline fast path in io/source.py: a thread cannot overlap
  CPU-bound work there, and the queue handoff taxes the hot loop).
- Producer exceptions are re-raised at the consumer, after all items
  produced before the failure have been consumed.
- Closing the iterator (consumer abort: limits, errors downstream)
  cancels the producer promptly and joins it — no leaked threads. The
  poison-pill DONE marker always lands, so the consumer never blocks on
  a dead producer.
- ``overlapTime`` metric: producer work hidden behind the consumer
  (busy time minus the time the consumer spent waiting on the queue) —
  the number that makes the overlap visible in metric roll-ups next to
  the xprof trace.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Iterable, Iterator, Optional

_ITEM, _ERR, _DONE = 0, 1, 2

#: joins/cancellation must complete well inside this (seconds); a producer
#: stuck past it indicates a hung decode, not a pipeline bug
_JOIN_TIMEOUT_S = 30.0


def prefetched(source: Iterable, depth: int, pool=None, metrics=None,
               name: str = "prefetch", force_thread: bool = False):
    """Wrap ``source`` so it is produced ``depth`` items ahead of the
    consumer on a background thread. Returns the plain iterator (no
    thread, no queue) when depth<=0 or on single-core hosts —
    ``force_thread`` overrides the single-core policy for I/O-bound
    producers (and tests)."""
    if depth is None or depth <= 0:
        return iter(source)
    if not force_thread and (os.cpu_count() or 1) <= 1:
        return iter(source)
    return PrefetchIterator(source, depth, pool=pool, metrics=metrics,
                            name=name)


class PrefetchIterator:
    """Iterator over ``source`` produced ahead through a bounded queue.

    ``pool`` runs the producer on an executor instead of a dedicated
    thread. NOTE for pool users: the producer OCCUPIES one worker for the
    iterator's whole lifetime — a pool whose every worker is a producer
    that submits work back into the same pool deadlocks, which is why the
    scan side uses a dedicated thread and lets the decode tasks have the
    shared reader pool to themselves."""

    def __init__(self, source: Iterable, depth: int, pool=None,
                 metrics=None, name: str = "prefetch"):
        self._source = source
        self._q: queue.Queue = queue.Queue(maxsize=max(int(depth), 1))
        self._cancel = threading.Event()
        self._metrics = metrics if metrics is not None else {}
        self._busy_ns = 0       # producer time spent inside next(source)
        self._wait_ns = 0       # consumer time spent blocked on the queue
        self._finished = False
        self._future = None
        self._thread: Optional[threading.Thread] = None
        if pool is not None:
            self._future = pool.submit(self._run)
        else:
            self._thread = threading.Thread(
                target=self._run, name=f"{name}-producer", daemon=True)
            self._thread.start()

    # ---- producer side ----
    def _put(self, item) -> bool:
        """Blocking put that observes cancellation; False = cancelled."""
        while not self._cancel.is_set():
            try:
                self._q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _run(self) -> None:
        it = iter(self._source)
        try:
            while not self._cancel.is_set():
                t0 = time.perf_counter_ns()
                try:
                    item = next(it)
                except StopIteration:
                    break
                except BaseException as e:   # re-raised at the consumer
                    self._busy_ns += time.perf_counter_ns() - t0
                    self._put((_ERR, e))
                    return
                self._busy_ns += time.perf_counter_ns() - t0
                if not self._put((_ITEM, item)):
                    break
        finally:
            if self._cancel.is_set():
                # consumer abort: release the source's resources (file
                # handles, nested pipelines) on the thread that drove it
                close = getattr(it, "close", None)
                if close is not None:
                    try:
                        close()
                    except Exception:
                        pass
            if not self._put((_DONE, None)):
                # cancelled with a full queue: make room so the marker
                # lands (close() is draining concurrently; benign race)
                try:
                    self._q.get_nowait()
                except queue.Empty:
                    pass
                try:
                    self._q.put_nowait((_DONE, None))
                except queue.Full:
                    pass

    def _producer_done(self) -> bool:
        if self._thread is not None:
            return not self._thread.is_alive()
        return self._future.done()

    def _join(self) -> None:
        if self._thread is not None:
            self._thread.join(timeout=_JOIN_TIMEOUT_S)
        else:
            try:
                self._future.result(timeout=_JOIN_TIMEOUT_S)
            except Exception:
                pass   # producer errors were already routed via _ERR

    # ---- consumer side ----
    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        if self._finished:
            raise StopIteration
        t0 = time.perf_counter_ns()
        tag, val = self._q.get()
        self._wait_ns += time.perf_counter_ns() - t0
        if tag == _ITEM:
            return val
        self._finish()
        if tag == _ERR:
            raise val
        raise StopIteration

    def _finish(self) -> None:
        if self._finished:
            return
        self._finished = True
        self._join()
        get = getattr(self._metrics, "get", None)
        if get is not None:
            m = get("overlapTime")
            if m is not None:
                m.add(max(self._busy_ns - self._wait_ns, 0))
            w = get("prefetchWaitTime")
            if w is not None:
                w.add(self._wait_ns)

    def close(self) -> None:
        """Consumer abort: cancel the producer, drain, join. Idempotent."""
        if self._finished:
            return
        self._cancel.set()
        # drain so a producer blocked on a full queue can observe the
        # cancel and exit; bounded in case the producer is hung mid-item
        deadline = time.monotonic() + _JOIN_TIMEOUT_S
        while not self._producer_done() and time.monotonic() < deadline:
            try:
                self._q.get(timeout=0.05)
            except queue.Empty:
                pass
        while True:   # leftover items + the DONE marker
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._finish()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        # abandoned mid-stream (consumer generator GC'd): stop the
        # producer rather than letting it fill the queue and park forever
        try:
            self.close()
        except Exception:
            pass


def close_iterator(it) -> None:
    """Close an iterator if it supports it (PrefetchIterator or
    generator) — the consumer-side finally-block helper."""
    close = getattr(it, "close", None)
    if close is not None:
        close()
