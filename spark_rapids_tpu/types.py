"""SQL type system for the TPU-native accelerator.

Mirrors the role of Spark's DataType lattice plus the reference's TypeSig gating
(reference: sql-plugin/.../TypeChecks.scala:171 `TypeSig`), re-designed for the
XLA compilation model: every SQL type maps to a fixed JAX storage dtype so that
columns are static-shaped, fixed-width device arrays.

Design notes (TPU-first):
- Nullability lives in a separate validity mask, never in the storage dtype.
- Strings are fixed-width padded UTF-8 byte matrices (``uint8[rows, max_len]``)
  plus a length vector — TPU vector units want rectangular data; cudf's
  offsets+chars layout (reference GpuColumnVector.java) would force dynamic
  shapes through XLA.
- Decimals with precision <= 18 are scaled int64 (DECIMAL64); wider decimals
  are deferred (tagged unsupported, CPU fallback — same policy the reference
  applies via TypeSig.DECIMAL_128 gating).
- Dates are days-since-epoch int32; timestamps are microseconds-since-epoch
  int64 (Spark's internal representation, which is also MXU/VPU friendly).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Optional, Tuple

import jax.numpy as jnp
import numpy as np


class TypeKind(enum.Enum):
    BOOLEAN = "boolean"
    INT8 = "tinyint"
    INT16 = "smallint"
    INT32 = "int"
    INT64 = "bigint"
    FLOAT32 = "float"
    FLOAT64 = "double"
    DECIMAL = "decimal"
    STRING = "string"
    DATE = "date"
    TIMESTAMP = "timestamp"
    NULL = "void"
    ARRAY = "array"
    STRUCT = "struct"
    MAP = "map"


_INTEGRALS = {TypeKind.INT8, TypeKind.INT16, TypeKind.INT32, TypeKind.INT64}
_FRACTIONALS = {TypeKind.FLOAT32, TypeKind.FLOAT64}

# JAX storage dtype per kind (strings/nested handled specially).
_STORAGE = {
    TypeKind.BOOLEAN: jnp.bool_,
    TypeKind.INT8: jnp.int8,
    TypeKind.INT16: jnp.int16,
    TypeKind.INT32: jnp.int32,
    TypeKind.INT64: jnp.int64,
    TypeKind.FLOAT32: jnp.float32,
    TypeKind.FLOAT64: jnp.float64,
    TypeKind.DECIMAL: jnp.int64,
    TypeKind.DATE: jnp.int32,
    TypeKind.TIMESTAMP: jnp.int64,
    TypeKind.NULL: jnp.int8,
}


@dataclass(frozen=True)
class SqlType:
    """A SQL data type. Hashable, usable as static (non-pytree) metadata."""

    kind: TypeKind
    # decimal parameters
    precision: int = 0
    scale: int = 0
    # string parameter: max encoded byte length (static per column)
    max_len: int = 0
    # nested element types (arrays/maps/structs)
    children: Tuple["SqlType", ...] = field(default_factory=tuple)
    # struct field names (parallel to children; empty for non-structs)
    names: Tuple[str, ...] = field(default_factory=tuple)

    # ---- predicates -------------------------------------------------
    @property
    def is_integral(self) -> bool:
        return self.kind in _INTEGRALS

    @property
    def is_fractional(self) -> bool:
        return self.kind in _FRACTIONALS

    @property
    def is_numeric(self) -> bool:
        return self.is_integral or self.is_fractional or self.kind is TypeKind.DECIMAL

    @property
    def is_string(self) -> bool:
        return self.kind is TypeKind.STRING

    @property
    def is_nested(self) -> bool:
        return self.kind in (TypeKind.ARRAY, TypeKind.STRUCT, TypeKind.MAP)

    @property
    def is_datetime(self) -> bool:
        return self.kind in (TypeKind.DATE, TypeKind.TIMESTAMP)

    # ---- storage ----------------------------------------------------
    @property
    def storage_dtype(self):
        """JAX dtype of the device storage array (payload for strings)."""
        if self.kind is TypeKind.STRING:
            return jnp.uint8
        if self.kind not in _STORAGE:
            raise TypeError(f"no device storage for {self}")
        return _STORAGE[self.kind]

    def __str__(self) -> str:
        if self.kind is TypeKind.DECIMAL:
            return f"decimal({self.precision},{self.scale})"
        if self.kind is TypeKind.STRING and self.max_len:
            return f"string[{self.max_len}]"
        if self.kind is TypeKind.ARRAY:
            return f"array<{self.children[0]}>"
        if self.kind is TypeKind.MAP:
            return f"map<{self.children[0]},{self.children[1]}>"
        if self.kind is TypeKind.STRUCT:
            names = self.names or tuple(
                f"f{i}" for i in range(len(self.children)))
            inner = ", ".join(f"{n}: {c}"
                              for n, c in zip(names, self.children))
            return f"struct<{inner}>"
        return self.kind.value


# Canonical singletons -----------------------------------------------
BOOLEAN = SqlType(TypeKind.BOOLEAN)
INT8 = SqlType(TypeKind.INT8)
INT16 = SqlType(TypeKind.INT16)
INT32 = SqlType(TypeKind.INT32)
INT64 = SqlType(TypeKind.INT64)
FLOAT32 = SqlType(TypeKind.FLOAT32)
FLOAT64 = SqlType(TypeKind.FLOAT64)
DATE = SqlType(TypeKind.DATE)
TIMESTAMP = SqlType(TypeKind.TIMESTAMP)
NULL = SqlType(TypeKind.NULL)


def decimal(precision: int, scale: int) -> SqlType:
    # precision > 18 (DECIMAL128) has no device storage yet; TypeSig's
    # max_decimal_precision gates it to CPU fallback at planning time.
    return SqlType(TypeKind.DECIMAL, precision=precision, scale=scale)


def string(max_len: int = 64) -> SqlType:
    return SqlType(TypeKind.STRING, max_len=max_len)


STRING = string()


def array(elem: SqlType, max_elems: int = 256) -> SqlType:
    """array<elem> with a static device element budget (max_len field),
    the same fixed-width strategy as strings."""
    return SqlType(TypeKind.ARRAY, max_len=max_elems, children=(elem,))


def struct(*fields: SqlType, names: Optional[Tuple[str, ...]] = None
           ) -> SqlType:
    """struct<name: type, ...> — stored on device as one lane-set per leaf
    field plus a struct-level validity lane (a null struct nulls every
    field; Spark's reverse inference does not apply)."""
    if names is None:
        names = tuple(f"f{i}" for i in range(len(fields)))
    if len(names) != len(fields):
        raise ValueError("struct names/fields length mismatch")
    return SqlType(TypeKind.STRUCT, children=tuple(fields),
                   names=tuple(names))


def map_(key: SqlType, value: SqlType, max_elems: int = 256) -> SqlType:
    """map<key,value> with a static entry budget — stored on device as two
    zipped fixed-budget matrices (keys, values) sharing one lengths vector."""
    return SqlType(TypeKind.MAP, max_len=max_elems, children=(key, value))


# ---- numeric promotion (Spark's findTightestCommonType subset) ------
_NUM_ORDER = [TypeKind.INT8, TypeKind.INT16, TypeKind.INT32, TypeKind.INT64,
              TypeKind.FLOAT32, TypeKind.FLOAT64]


def common_numeric_type(a: SqlType, b: SqlType) -> SqlType:
    """Tightest common numeric type for binary arithmetic (Spark promotion)."""
    if a.kind is TypeKind.DECIMAL or b.kind is TypeKind.DECIMAL:
        # Simplified decimal promotion; exact Spark rules in expressions/decimal.
        if a.kind is TypeKind.DECIMAL and b.kind is TypeKind.DECIMAL:
            scale = max(a.scale, b.scale)
            prec = max(a.precision - a.scale, b.precision - b.scale) + scale
            return decimal(min(prec, 38), scale)
        other = b if a.kind is TypeKind.DECIMAL else a
        dec = a if a.kind is TypeKind.DECIMAL else b
        if other.is_fractional:
            return FLOAT64
        if other.kind not in _INTEGRALS:
            raise TypeError(f"no common numeric type for {a}, {b}")
        # Spark DecimalType.forType: int8->3, int16->5, int32->10, int64->20 digits.
        digits = {TypeKind.INT8: 3, TypeKind.INT16: 5,
                  TypeKind.INT32: 10, TypeKind.INT64: 20}[other.kind]
        prec = max(dec.precision - dec.scale, digits) + dec.scale
        return decimal(min(prec, 38), dec.scale)
    if not (a.is_numeric and b.is_numeric):
        raise TypeError(f"no common numeric type for {a}, {b}")
    ia, ib = _NUM_ORDER.index(a.kind), _NUM_ORDER.index(b.kind)
    return SqlType(_NUM_ORDER[max(ia, ib)])


# ---- host<->device conversion helpers -------------------------------
def numpy_dtype(t: SqlType) -> np.dtype:
    return np.dtype(_STORAGE[t.kind]) if t.kind in _STORAGE else np.dtype(np.uint8)


def from_arrow(arrow_type: Any, max_len: int = 64) -> SqlType:
    """Map a pyarrow DataType to a SqlType."""
    import pyarrow as pa

    if pa.types.is_boolean(arrow_type):
        return BOOLEAN
    if pa.types.is_int8(arrow_type):
        return INT8
    if pa.types.is_int16(arrow_type):
        return INT16
    if pa.types.is_int32(arrow_type):
        return INT32
    if pa.types.is_int64(arrow_type):
        return INT64
    if pa.types.is_float32(arrow_type):
        return FLOAT32
    if pa.types.is_float64(arrow_type):
        return FLOAT64
    if pa.types.is_decimal(arrow_type):
        return decimal(arrow_type.precision, arrow_type.scale)
    if pa.types.is_string(arrow_type) or pa.types.is_large_string(arrow_type):
        return string(max_len)
    if pa.types.is_dictionary(arrow_type):
        # dictionary encoding is a COLUMN property (dictenc.py), not a
        # type: dictionary<string> scans type as plain string
        return from_arrow(arrow_type.value_type, max_len)
    if pa.types.is_date32(arrow_type):
        return DATE
    if pa.types.is_timestamp(arrow_type):
        return TIMESTAMP
    if pa.types.is_map(arrow_type):
        return map_(from_arrow(arrow_type.key_type, max_len),
                    from_arrow(arrow_type.item_type, max_len))
    if pa.types.is_list(arrow_type):
        return array(from_arrow(arrow_type.value_type, max_len))
    if pa.types.is_struct(arrow_type):
        return struct(*(from_arrow(f.type, max_len) for f in arrow_type),
                      names=tuple(f.name for f in arrow_type))
    if pa.types.is_null(arrow_type):
        return NULL
    raise TypeError(f"unsupported arrow type {arrow_type}")


def to_arrow(t: SqlType):
    import pyarrow as pa

    m = {
        TypeKind.BOOLEAN: pa.bool_(),
        TypeKind.INT8: pa.int8(),
        TypeKind.INT16: pa.int16(),
        TypeKind.INT32: pa.int32(),
        TypeKind.INT64: pa.int64(),
        TypeKind.FLOAT32: pa.float32(),
        TypeKind.FLOAT64: pa.float64(),
        TypeKind.STRING: pa.string(),
        TypeKind.DATE: pa.date32(),
        TypeKind.TIMESTAMP: pa.timestamp("us", tz="UTC"),
        TypeKind.NULL: pa.null(),
    }
    if t.kind is TypeKind.DECIMAL:
        return pa.decimal128(t.precision, t.scale)
    if t.kind is TypeKind.ARRAY:
        return pa.list_(to_arrow(t.children[0]))
    if t.kind is TypeKind.MAP:
        return pa.map_(to_arrow(t.children[0]), to_arrow(t.children[1]))
    if t.kind is TypeKind.STRUCT:
        names = t.names or tuple(f"f{i}" for i in range(len(t.children)))
        return pa.struct([pa.field(n, to_arrow(c), nullable=True)
                          for n, c in zip(names, t.children)])
    return m[t.kind]


# ---- TypeSig: per-operator supported-type signatures ----------------
class TypeSig:
    """Set-algebra over TypeKind used to gate operator placement.

    Reference: TypeChecks.scala `TypeSig` — drives both CPU-fallback decisions
    and the generated supported-ops documentation.
    """

    def __init__(self, kinds: frozenset, note: str = "",
                 max_decimal_precision: int = 18):
        self.kinds = frozenset(kinds)
        self.note = note
        self.max_decimal_precision = max_decimal_precision

    def __add__(self, other: "TypeSig") -> "TypeSig":
        return TypeSig(self.kinds | other.kinds,
                       max_decimal_precision=max(self.max_decimal_precision,
                                                 other.max_decimal_precision))

    def supports(self, t: SqlType) -> Optional[str]:
        """None if supported, else a human-readable fallback reason."""
        if t.kind not in self.kinds:
            return f"{t} is not supported"
        if t.kind is TypeKind.DECIMAL and t.precision > self.max_decimal_precision:
            return (f"decimal precision {t.precision} exceeds supported "
                    f"maximum {self.max_decimal_precision}")
        if t.is_nested:
            for c in t.children:
                r = self.supports(c)
                if r is not None:
                    return f"nested: {r}"
        return None

    @staticmethod
    def of(*kinds: TypeKind) -> "TypeSig":
        return TypeSig(frozenset(kinds))


integral = TypeSig.of(TypeKind.INT8, TypeKind.INT16, TypeKind.INT32, TypeKind.INT64)
fp = TypeSig.of(TypeKind.FLOAT32, TypeKind.FLOAT64)
numeric = integral + fp + TypeSig.of(TypeKind.DECIMAL)
comparable = numeric + TypeSig.of(TypeKind.BOOLEAN, TypeKind.STRING, TypeKind.DATE,
                                  TypeKind.TIMESTAMP)
all_basic = comparable + TypeSig.of(TypeKind.NULL)
orderable = comparable
