"""Columnar data plane: device columns and batches.

TPU-native analogue of the reference's column bridge
(reference: sql-plugin/src/main/java/com/nvidia/spark/rapids/GpuColumnVector.java —
Spark ColumnVector over cudf columns) re-designed for XLA:

- A ``DeviceColumn`` is a fixed-capacity JAX array plus a validity mask. The
  capacity is **static** (bucketed to powers of two) so that every operator
  compiles once per bucket instead of once per row count — cudf kernels accept
  any shape, XLA wants static shapes; this bucketed-padding scheme is the
  central architectural divergence called out in SURVEY.md §7.
- ``num_rows`` is a traced scalar: rows in ``[num_rows, capacity)`` are padding
  and always invalid. Filters clear validity instead of compacting, so a whole
  scan→project→filter→aggregate stage fuses into one XLA computation with no
  host round-trips; compaction happens only at exchange boundaries.
- Strings are fixed-width padded UTF-8 byte matrices ``uint8[cap, max_len]``
  with a separate length vector (rectangular data for the VPU; see types.py).

Host interchange is Arrow (pyarrow) — the same interchange layer the
reference uses between the JVM and Python workers (GpuArrowEvalPythonExec).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

import flax.struct

from . import types as T
from .types import SqlType, TypeKind

MIN_CAPACITY = 128  # one TPU lane row


class StringOverflowError(ValueError):
    """A string exceeded its column's device max_len byte budget."""


class CapacityError(ValueError):
    """A fixed device budget (array max_elems, …) was exceeded; the result
    would be silently truncated, so the host boundary fails loud instead."""


def bucket_capacity(n: int, minimum: int = MIN_CAPACITY) -> int:
    """Round a row count up to the compile-cache bucket (next power of two)."""
    if n <= minimum:
        return minimum
    return 1 << (n - 1).bit_length()


@dataclass(frozen=True)
class Field:
    name: str
    dtype: SqlType
    nullable: bool = True


@dataclass(frozen=True)
class Schema:
    fields: Tuple[Field, ...]

    def __init__(self, fields: Sequence[Field]):
        object.__setattr__(self, "fields", tuple(fields))

    def __len__(self):
        return len(self.fields)

    def __iter__(self):
        return iter(self.fields)

    def __getitem__(self, i):
        return self.fields[i]

    @property
    def names(self) -> List[str]:
        return [f.name for f in self.fields]

    def index_of(self, name: str) -> int:
        for i, f in enumerate(self.fields):
            if f.name == name:
                return i
        raise KeyError(f"column {name!r} not in schema {self.names}")

    def field(self, name: str) -> Field:
        return self.fields[self.index_of(name)]

    def __str__(self):
        inner = ", ".join(f"{f.name}: {f.dtype}" for f in self.fields)
        return f"Schema({inner})"


@flax.struct.dataclass
class DeviceColumn:
    """One column resident in HBM: payload + validity (+ lengths for strings).

    STRUCT columns (reference carries structs through every operator —
    GpuColumnVector.java struct paths, complexTypeExtractors.scala:355)
    hold a TUPLE of child DeviceColumns in ``data`` — one lane-set per leaf
    field — plus the struct-level validity lane. The tuple is a pytree
    node, so struct columns trace through jit like any other column;
    generic primitives (gather/compact/concat) recurse into the children.
    """

    data: jax.Array                 # [cap] | [cap, max_len] uint8 strings
    #                               | int32[cap] codes (dict strings)
    #                               | tuple[DeviceColumn, ...] for structs
    validity: jax.Array             # bool[cap]; False beyond num_rows
    lengths: Optional[jax.Array] = None   # int32[cap], strings/arrays/maps
    dtype: SqlType = flax.struct.field(pytree_node=False, default=T.INT32)
    # maps only: the VALUES matrix [cap, max_elems] (``data`` holds keys).
    # A map column is two zipped fixed-budget arrays sharing one lengths
    # vector — the TPU answer to cudf's LIST<STRUCT<K,V>> layout.
    data2: Optional[jax.Array] = None
    # dictionary-encoded STRING columns only (dictenc.py): sorted-distinct
    # padded entries + per-entry byte lengths; ``data`` holds the codes
    # and ``lengths`` is None (rematerialized at decode). Invariants —
    # including why code order == string order — live in dictenc.py.
    dict_data: Optional[jax.Array] = None     # uint8[card, max_len]
    dict_lengths: Optional[jax.Array] = None  # int32[card]

    @property
    def capacity(self) -> int:
        # validity is always a flat [cap] lane, even for structs where
        # ``data`` is a tuple of child columns
        return self.validity.shape[0]

    @property
    def is_struct(self) -> bool:
        return isinstance(self.data, tuple)

    @property
    def is_dict(self) -> bool:
        return self.dict_data is not None

    @property
    def struct_fields(self) -> Tuple["DeviceColumn", ...]:
        return self.data

    def with_validity(self, validity: jax.Array) -> "DeviceColumn":
        return self.replace(validity=validity)

    def size_bytes(self) -> int:
        if self.is_struct:
            return (sum(c.size_bytes() for c in self.data)
                    + self.validity.size)
        n = self.data.size * self.data.dtype.itemsize + self.validity.size
        if self.lengths is not None:
            n += self.lengths.size * 4
        if self.data2 is not None:
            n += self.data2.size * self.data2.dtype.itemsize
        if self.dict_data is not None:
            n += self.dict_data.size + self.dict_lengths.size * 4
        return n


@flax.struct.dataclass
class ColumnarBatch:
    """A batch of columns with a traced row count and static capacity."""

    columns: Tuple[DeviceColumn, ...]
    num_rows: jax.Array  # int32 scalar

    @property
    def capacity(self) -> int:
        return self.columns[0].capacity if self.columns else 0

    @property
    def num_columns(self) -> int:
        return len(self.columns)

    def column(self, i: int) -> DeviceColumn:
        return self.columns[i]

    def row_mask(self) -> jax.Array:
        """bool[cap] — True for live (within num_rows) positions."""
        cap = self.capacity
        return jnp.arange(cap, dtype=jnp.int32) < self.num_rows

    def size_bytes(self) -> int:
        return sum(c.size_bytes() for c in self.columns)


# ---------------------------------------------------------------------------
# Construction helpers
# ---------------------------------------------------------------------------

def make_column(values: np.ndarray, validity: np.ndarray, dtype: SqlType,
                capacity: int, lengths: Optional[np.ndarray] = None,
                values2: Optional[np.ndarray] = None) -> DeviceColumn:
    """Pad host arrays to capacity and move to device.

    For strings, pass the exact byte ``lengths``; deriving them from the
    zero-padded matrix would drop trailing NUL bytes.
    """
    n = values.shape[0]
    if n > capacity:
        raise ValueError(f"{n} rows exceed capacity {capacity}")
    if dtype.kind is TypeKind.STRING:
        ml = dtype.max_len
        padded = np.zeros((capacity, ml), dtype=np.uint8)
        padded[:n] = values
        plen = np.zeros(capacity, dtype=np.int32)
        plen[:n] = values_lengths(values) if lengths is None else lengths
        val = np.zeros(capacity, dtype=bool)
        val[:n] = validity
        return DeviceColumn(jnp.asarray(padded), jnp.asarray(val),
                            jnp.asarray(plen), dtype)
    if dtype.kind in (TypeKind.ARRAY, TypeKind.MAP):
        padded = np.zeros((capacity,) + values.shape[1:], dtype=values.dtype)
        padded[:n] = values
        plen = np.zeros(capacity, dtype=np.int32)
        plen[:n] = lengths
        val = np.zeros(capacity, dtype=bool)
        val[:n] = validity
        p2 = None
        if values2 is not None:
            p2 = np.zeros((capacity,) + values2.shape[1:],
                          dtype=values2.dtype)
            p2[:n] = values2
            p2 = jnp.asarray(p2)
        return DeviceColumn(jnp.asarray(padded), jnp.asarray(val),
                            jnp.asarray(plen), dtype, p2)
    if values.ndim > 1:     # decimal128 limb matrices
        padded = np.zeros((capacity,) + values.shape[1:], dtype=values.dtype)
        padded[:n] = values
        val = np.zeros(capacity, dtype=bool)
        val[:n] = validity
        return DeviceColumn(jnp.asarray(padded), jnp.asarray(val), None,
                            dtype)
    padded = np.zeros(capacity, dtype=T.numpy_dtype(dtype))
    padded[:n] = values
    val = np.zeros(capacity, dtype=bool)
    val[:n] = validity
    return DeviceColumn(jnp.asarray(padded), jnp.asarray(val), None, dtype)


def values_lengths(byte_matrix: np.ndarray) -> np.ndarray:
    """Recover string byte lengths from a zero-padded byte matrix."""
    nz = byte_matrix != 0
    return (byte_matrix.shape[1] - np.argmax(nz[:, ::-1], axis=1)) * nz.any(axis=1)


def _strings_to_matrix(arr: pa.Array, max_len: int,
                       truncate: bool = False) -> Tuple[np.ndarray, np.ndarray]:
    """Encode an arrow string array into (byte_matrix, lengths).

    Vectorized over the arrow offsets/data buffers (no per-row Python on the
    scan hot path). Raises on strings longer than ``max_len`` unless
    ``truncate`` — silent truncation is data corruption; the planner
    re-buckets max_len or falls back to CPU instead (config.STRING_MAX_BYTES).
    """
    n = len(arr)
    if n == 0:
        return np.zeros((0, max_len), np.uint8), np.zeros(0, np.int32)
    if arr.type == pa.large_string():
        arr = arr.cast(pa.string())
    bufs = arr.buffers()
    offsets = np.frombuffer(bufs[1], dtype=np.int32, count=n + 1,
                            offset=arr.offset * 4).astype(np.int64)
    data = (np.frombuffer(bufs[2], dtype=np.uint8)
            if bufs[2] is not None else np.zeros(0, np.uint8))
    lengths = np.diff(offsets).astype(np.int32)
    if arr.null_count:
        valid = np.asarray(arr.is_valid())
        lengths = np.where(valid, lengths, 0)
    over = lengths > max_len
    if over.any():
        if not truncate:
            raise StringOverflowError(
                f"string of {int(lengths.max())} bytes exceeds device "
                f"max_len {max_len}; re-bucket the column or fall back to CPU")
        lengths = np.minimum(lengths, max_len)
    col_idx = np.arange(max_len, dtype=np.int64)[None, :]
    mask = col_idx < lengths[:, None]
    if data.size:
        gather = np.minimum(offsets[:-1, None] + col_idx, data.size - 1)
        out = np.where(mask, data[gather], 0).astype(np.uint8)
    else:
        out = np.zeros((n, max_len), np.uint8)
    if over.any():
        # repair rows whose truncation split a multi-byte codepoint: find the
        # start of the trailing char; drop it only if its sequence is cut
        for i in np.nonzero(over)[0]:
            row = out[i]
            ln = int(lengths[i])
            p = ln - 1
            while p >= 0 and (row[p] & 0xC0) == 0x80:
                p -= 1
            if p >= 0:
                lead = int(row[p])
                char_len = 1 if lead < 0x80 else \
                    2 if lead < 0xE0 else 3 if lead < 0xF0 else 4
                if p + char_len > ln:  # incomplete sequence — drop it
                    out[i, p:] = 0
                    lengths[i] = p
    return out, lengths


def _scalar_storage(arr: pa.Array, dtype: SqlType,
                    validity: np.ndarray) -> np.ndarray:
    """Arrow scalar array → numpy storage values (the device encoding):
    decimal → unscaled int64, date → epoch days, timestamp → epoch micros,
    numerics/bools pass through. Shared by top-level columns and
    array/map ELEMENT buffers so nested data gets identical encoding."""
    n = len(arr)
    if dtype.kind is TypeKind.DECIMAL:
        import decimal as pydec
        # the default decimal context (28 digits) ROUNDS scaleb on wide
        # values — widen it for the exact unscaled-int conversion
        with pydec.localcontext() as lctx:
            lctx.prec = 60
            ints = [int(v.scaleb(dtype.scale)) if v is not None else 0
                    for v in arr.to_pylist()]
        if dtype.precision > 18:
            # DECIMAL128: 4×32-bit limbs in int64 lanes (decimal128.py)
            from .expressions.decimal128 import to_limbs_np
            return to_limbs_np(ints)
        return np.array(ints, dtype=np.int64)
    if dtype.kind is TypeKind.TIMESTAMP:
        np_vals = np.zeros(n, dtype=np.int64)
        tmp = arr.cast(pa.timestamp("us")).to_numpy(zero_copy_only=False)
        np_vals[validity] = tmp[validity].astype(
            "datetime64[us]").astype(np.int64)
        return np_vals
    if dtype.kind is TypeKind.DATE:
        np_vals = np.zeros(n, dtype=np.int32)
        tmp = arr.to_numpy(zero_copy_only=False)
        np_vals[validity] = np.asarray(
            tmp[validity], dtype="datetime64[D]").astype(np.int32)
        return np_vals
    # Null slots become 0 in the payload (validity carries nullness);
    # keeps integer dtypes intact and avoids NaN poisoning reductions.
    filled = arr.fill_null(False) if dtype.kind is TypeKind.BOOLEAN \
        else arr.fill_null(0) if arr.null_count else arr
    return np.asarray(filled.to_numpy(zero_copy_only=False),
                      dtype=T.numpy_dtype(dtype))


def column_from_arrow(arr: pa.Array, dtype: SqlType, capacity: int,
                      truncate_strings: bool = False,
                      name: str = "",
                      allow_dict: bool = True,
                      dict_conf: Optional[tuple] = None) -> DeviceColumn:
    arr = arr.combine_chunks() if isinstance(arr, pa.ChunkedArray) else arr

    if pa.types.is_dictionary(arr.type):
        # RLE_DICTIONARY scan hand-off: keep the codes, build the byte
        # matrix once per DISTINCT value (dictenc.py). Nested positions
        # and over-threshold cardinalities decode to the padded path.
        if dtype.kind is TypeKind.STRING and allow_dict:
            from .dictenc import column_from_arrow_dictionary
            col = column_from_arrow_dictionary(arr, dtype, capacity,
                                               truncate_strings, name,
                                               dict_conf)
            if col is not None:
                return col
        arr = arr.cast(arr.type.value_type)

    n = len(arr)
    if arr.null_count:
        validity = np.asarray(arr.is_valid())
    else:
        validity = np.ones(n, dtype=bool)

    if dtype.kind is TypeKind.STRUCT:
        # one lane-set per leaf field + struct-level validity; a field of
        # a null struct is null (validity AND), struct-of-struct recurses
        pval = np.zeros(capacity, dtype=bool)
        pval[:n] = validity
        pval_dev = jnp.asarray(pval)
        kids = []
        for i, ct in enumerate(dtype.children):
            # struct leaf lanes stay plain: generic struct recursion
            # (gather/concat/serialize) does not carry dictionaries
            kid = column_from_arrow(arr.field(i), ct, capacity,
                                    truncate_strings, allow_dict=False)
            kids.append(kid.with_validity(kid.validity & pval_dev))
        return DeviceColumn(tuple(kids), pval_dev, None, dtype)

    if dtype.kind is TypeKind.STRING:
        mat, lengths = _strings_to_matrix(arr, dtype.max_len, truncate_strings)
        return make_column(mat, validity, dtype, capacity, lengths)

    if dtype.kind is TypeKind.ARRAY:
        # list column → fixed-budget matrix data[cap, max_elems] + lengths,
        # the same layout collect_list produces on-device (docstring at top).
        # String elements use a 3D byte tensor with per-element byte lengths
        # in data2 (split()'s output layout).
        elem_t = dtype.children[0]
        if elem_t.kind in (TypeKind.ARRAY, TypeKind.STRUCT, TypeKind.MAP):
            raise TypeError(
                f"array<{elem_t}> nested elements have no device layout; "
                f"the planner must fall back to CPU")
        me = dtype.max_len
        offsets = np.asarray(arr.offsets)
        counts = np.diff(offsets).astype(np.int32)
        counts = np.where(validity, counts, 0)
        if counts.size and int(counts.max()) > me:
            raise CapacityError(
                f"list of {int(counts.max())} elements exceeds the device "
                f"array budget of {me}; raise max_elems in the scan schema "
                f"or fall back to CPU")
        values = arr.values
        if values.null_count:
            raise TypeError(
                "arrays with null elements are outside the device subset "
                "(fixed-budget arrays hold non-null elements; CPU fallback)")
        col_idx = np.arange(me)[None, :]
        mask = col_idx < counts[:, None]
        start = offsets[:-1]
        src_idx = (start[:, None] + col_idx)[mask]
        if elem_t.kind is TypeKind.STRING:
            smat, slens = _strings_to_matrix(values, elem_t.max_len,
                                             truncate_strings)
            mat = np.zeros((n, me, elem_t.max_len), np.uint8)
            el_lens = np.zeros((n, me), np.int32)
            mat[mask] = smat[src_idx]
            el_lens[mask] = slens[src_idx]
            return make_column(mat, validity, dtype, capacity,
                               counts.astype(np.int32), values2=el_lens)
        flat = _scalar_storage(values, elem_t,
                               np.ones(len(values), dtype=bool))
        mat = np.zeros((n, me), dtype=flat.dtype)
        # rows are laid out consecutively in the flat values buffer; the
        # masked scatter below is the inverse of to_arrow's masked gather
        mat[mask] = flat[src_idx]
        return make_column(mat, validity, dtype, capacity,
                           counts.astype(np.int32))

    if dtype.kind is TypeKind.MAP:
        key_t, val_t = dtype.children
        for t in (key_t, val_t):
            if t.kind in (TypeKind.STRING, TypeKind.ARRAY, TypeKind.STRUCT,
                          TypeKind.MAP):
                raise TypeError(
                    f"map<{key_t},{val_t}> device layout is fixed-width "
                    f"scalars only; the planner must fall back to CPU")
        me = dtype.max_len
        offsets = np.asarray(arr.offsets)
        counts = np.diff(offsets).astype(np.int32)
        counts = np.where(validity, counts, 0)
        if counts.size and int(counts.max()) > me:
            raise CapacityError(
                f"map of {int(counts.max())} entries exceeds the device "
                f"budget of {me}")
        if arr.keys.null_count or arr.items.null_count:
            raise TypeError(
                "maps with null keys/values are outside the device subset "
                "(fixed-budget matrices hold non-null entries; CPU fallback)")
        keys = _scalar_storage(arr.keys, key_t,
                               np.ones(len(arr.keys), dtype=bool))
        items = _scalar_storage(arr.items, val_t,
                                np.ones(len(arr.items), dtype=bool))
        kmat = np.zeros((n, me), dtype=keys.dtype)
        vmat = np.zeros((n, me), dtype=items.dtype)
        col_idx = np.arange(me)[None, :]
        mask = col_idx < counts[:, None]
        src_idx = (offsets[:-1][:, None] + col_idx)[mask]
        kmat[mask] = keys[src_idx]
        vmat[mask] = items[src_idx]
        return make_column(kmat, validity, dtype, capacity,
                           counts.astype(np.int32), values2=vmat)

    return make_column(_scalar_storage(arr, dtype, validity), validity,
                       dtype, capacity)


def schema_from_arrow(schema: pa.Schema, string_max_len: int = 64) -> Schema:
    return Schema([Field(f.name, T.from_arrow(f.type, string_max_len), f.nullable)
                   for f in schema])


def from_arrow(table: pa.Table, capacity: Optional[int] = None,
               schema: Optional[Schema] = None,
               string_max_len: int = 64,
               truncate_strings: bool = False,
               dict_conf: Optional[tuple] = None
               ) -> Tuple[ColumnarBatch, Schema]:
    """Build a device batch from an Arrow table (the scan H2D boundary).

    Nullability is tightened from the DATA (null_count metadata, free in
    Arrow): a null-free column becomes non-nullable, which lets the
    aggregation fast path skip its validity payload lane and share one
    count lane across aggregates (the reference's readers track per-batch
    null counts the same way)."""
    if schema is None:
        schema = schema_from_arrow(table.schema, string_max_len)
        tight = []
        for i, f in enumerate(schema):
            nullable = f.nullable and table.column(i).null_count > 0
            tight.append(Field(f.name, f.dtype, nullable))
        schema = Schema(tight)
    n = table.num_rows
    cap = capacity or bucket_capacity(n)
    cols = [column_from_arrow(table.column(i), f.dtype, cap, truncate_strings,
                              name=f.name, dict_conf=dict_conf)
            for i, f in enumerate(schema)]
    return ColumnarBatch(tuple(cols), jnp.asarray(n, jnp.int32)), schema


def empty_column(dtype: SqlType, capacity: int = MIN_CAPACITY
                 ) -> DeviceColumn:
    validity = jnp.zeros(capacity, bool)
    if dtype.kind is TypeKind.STRUCT:
        kids = tuple(empty_column(c, capacity) for c in dtype.children)
        return DeviceColumn(kids, validity, None, dtype)
    if dtype.kind is TypeKind.STRING:
        return DeviceColumn(jnp.zeros((capacity, dtype.max_len), jnp.uint8),
                            validity, jnp.zeros(capacity, jnp.int32), dtype)
    return DeviceColumn(jnp.zeros(capacity, dtype.storage_dtype),
                        validity, None, dtype)


def empty_batch(schema: Schema, capacity: int = MIN_CAPACITY) -> ColumnarBatch:
    cols = [empty_column(f.dtype, capacity) for f in schema]
    return ColumnarBatch(tuple(cols), jnp.asarray(0, jnp.int32))


# ---------------------------------------------------------------------------
# Device -> host (the C2R / collect boundary)
# ---------------------------------------------------------------------------

def _storage_to_arrow(flat: np.ndarray, dtype: SqlType) -> pa.Array:
    """Inverse of _scalar_storage for non-null element buffers."""
    import decimal as pydec
    if dtype.kind is TypeKind.DECIMAL:
        return pa.array([pydec.Decimal(int(v)).scaleb(-dtype.scale)
                         for v in flat], type=T.to_arrow(dtype))
    if dtype.kind is TypeKind.TIMESTAMP:
        return pa.array(flat.astype("datetime64[us]"),
                        type=T.to_arrow(dtype))
    if dtype.kind is TypeKind.DATE:
        return pa.array(flat.astype("datetime64[D]"),
                        type=T.to_arrow(dtype))
    return pa.array(flat, type=T.to_arrow(dtype))


def to_arrow(batch: ColumnarBatch, schema: Schema) -> pa.Table:
    n = int(batch.num_rows)
    arrays = [_col_to_arrow(col, f.dtype, f.name, n)
              for col, f in zip(batch.columns, schema)]
    return pa.table(arrays, names=schema.names)


def _col_to_arrow(col: DeviceColumn, dtype: SqlType, name: str,
                  n: int) -> pa.Array:
    """One device column → one arrow array (recursive for structs)."""
    validity = np.asarray(col.validity[:n])
    if dtype.kind is TypeKind.NULL:
        return pa.nulls(n)
    if dtype.kind is TypeKind.STRUCT:
        names = dtype.names or tuple(
            f"f{i}" for i in range(len(dtype.children)))
        kids = [_col_to_arrow(c, ct, f"{name}.{nm}", n)
                for c, ct, nm in zip(col.struct_fields,
                                     dtype.children, names)]
        return pa.StructArray.from_arrays(
            kids, names=list(names),
            mask=pa.array(~validity) if not validity.all() else None)
    if dtype.kind is TypeKind.STRING:
        if col.is_dict:
            # lazy decode at the collect boundary: gather the dictionary
            # on HOST (codes + small dict came down; bytes never lived
            # per-row on device)
            dmat = np.asarray(col.dict_data)
            dlens = np.asarray(col.dict_lengths)
            codes = np.clip(np.asarray(col.data[:n]), 0,
                            max(dmat.shape[0] - 1, 0))
            mat = dmat[codes] if dmat.shape[0] else \
                np.zeros((n, dtype.max_len), np.uint8)
            lens = np.where(validity,
                            dlens[codes] if dlens.shape[0]
                            else 0, 0).astype(np.int32)
        else:
            mat = np.asarray(col.data[:n])
            lens = np.where(validity, np.asarray(col.lengths[:n]), 0)
        # vectorized: row-major masked bytes ARE the arrow data buffer
        mask = np.arange(mat.shape[1])[None, :] < lens[:, None]
        flat = np.ascontiguousarray(mat)[mask]
        offsets = np.zeros(n + 1, np.int32)
        np.cumsum(lens, out=offsets[1:])
        return pa.StringArray.from_buffers(
            n, pa.py_buffer(offsets.tobytes()),
            pa.py_buffer(flat.tobytes()),
            pa.py_buffer(np.packbits(validity, bitorder="little").tobytes())
            if not validity.all() else None)
    if dtype.kind is TypeKind.ARRAY:
        mat = np.asarray(col.data[:n])
        counts = np.where(validity, np.asarray(col.lengths[:n]), 0)
        if counts.size and int(counts.max()) > mat.shape[1]:
            raise CapacityError(
                f"array column '{name}' holds a list of "
                f"{int(counts.max())} elements but the device budget is "
                f"{mat.shape[1]}; raise max_elems (collect_list/set) or "
                f"fall back to CPU")
        mask2 = np.arange(mat.shape[1])[None, :] < counts[:, None]
        offsets = np.zeros(n + 1, np.int32)
        np.cumsum(counts, out=offsets[1:])
        elem_t = T.to_arrow(dtype.children[0])
        if dtype.children[0].kind is TypeKind.STRING:
            # 3D byte tensor [n, me, max_len]; per-element byte lengths
            # ride in data2
            el_lens = np.asarray(col.data2[:n])
            live_el = mat[mask2]                     # [k, max_len]
            live_lens = el_lens[mask2]
            bmask = np.arange(mat.shape[2])[None, :] < live_lens[:, None]
            str_offsets = np.zeros(len(live_lens) + 1, np.int32)
            np.cumsum(live_lens, out=str_offsets[1:])
            values = pa.StringArray.from_buffers(
                len(live_lens),
                pa.py_buffer(str_offsets.tobytes()),
                pa.py_buffer(np.ascontiguousarray(live_el)[bmask]
                             .tobytes()))
        else:
            values = _storage_to_arrow(mat[mask2],
                                       dtype.children[0])
        la = pa.ListArray.from_arrays(pa.array(offsets, pa.int32()),
                                      values)
        if not validity.all():
            # rebuild with a null mask (from_arrays has no mask param
            # for offsets-based construction)
            la = pa.ListArray.from_arrays(
                pa.array(offsets, pa.int32()), values)
            pl = la.to_pylist()
            la = pa.array([v if ok else None
                           for v, ok in zip(pl, validity)],
                          type=pa.list_(elem_t))
        return la
    if dtype.kind is TypeKind.MAP:
        kmat = np.asarray(col.data[:n])
        vmat = np.asarray(col.data2[:n])
        counts = np.where(validity, np.asarray(col.lengths[:n]), 0)
        mask2 = np.arange(kmat.shape[1])[None, :] < counts[:, None]
        offsets = np.zeros(n + 1, np.int32)
        np.cumsum(counts, out=offsets[1:])
        key_t, val_t = dtype.children
        ma = pa.MapArray.from_arrays(
            pa.array(offsets, pa.int32()),
            _storage_to_arrow(kmat[mask2], key_t),
            _storage_to_arrow(vmat[mask2], val_t))
        if not validity.all():
            pl = ma.to_pylist()
            ma = pa.array([v if ok else None
                           for v, ok in zip(pl, validity)],
                          type=pa.map_(T.to_arrow(key_t),
                                       T.to_arrow(val_t)))
        return ma
    data = np.asarray(col.data[:n])
    if dtype.kind is TypeKind.DECIMAL:
        import decimal as pydec
        with pydec.localcontext() as lctx:
            lctx.prec = 60       # exact: default context rounds at 28
            if dtype.precision > 18:
                from .expressions.decimal128 import from_limbs_np
                ints = from_limbs_np(data)
                vals = [pydec.Decimal(v).scaleb(-dtype.scale)
                        if ok else None
                        for v, ok in zip(ints, validity)]
            else:
                vals = [pydec.Decimal(int(v)).scaleb(-dtype.scale)
                        if ok else None
                        for v, ok in zip(data, validity)]
        return pa.array(vals, type=T.to_arrow(dtype))
    if dtype.kind is TypeKind.TIMESTAMP:
        return pa.array(data.astype("datetime64[us]"),
                        type=T.to_arrow(dtype), mask=~validity)
    if dtype.kind is TypeKind.DATE:
        return pa.array(data.astype("datetime64[D]"),
                        type=T.to_arrow(dtype), mask=~validity)
    return pa.array(data, type=T.to_arrow(dtype), mask=~validity)


def to_pandas(batch: ColumnarBatch, schema: Schema):
    return to_arrow(batch, schema).to_pandas()
