"""Runtime bootstrap: the plugin/executor lifecycle.

Reference: SURVEY.md §2.1/§3.1 — SQLPlugin → RapidsDriverPlugin (conf
fixup, heartbeat host) and RapidsExecutorPlugin (device acquire, RMM init,
version handshake, semaphore init, fatal-error exit policy,
Plugin.scala:215-393). The standalone TPU engine folds both roles into one
process; multi-host deployments run one `ExecutorRuntime` per host with
`jax.distributed` supplying the DCN control plane.

Failure policy mirrors the reference (SURVEY.md §5): a fatal device error
marks the runtime unusable and (optionally) exits with a dedicated code so
a scheduler reschedules the executor — the plugin adds fast failure, the
cluster manager supplies recovery (Spark task-retry in the reference).
"""

from __future__ import annotations

import atexit
import logging
import os
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .config import (CONCURRENT_TPU_TASKS, HBM_POOL_FRACTION, HBM_RESERVE,
                     HOST_SPILL_LIMIT, RapidsTpuConf, SPILL_DIR)

log = logging.getLogger("spark_rapids_tpu")

FATAL_EXIT_CODE = 20     # reference: executor exits 20 on fatal CUDA error

_MIN_JAX = (0, 4, 30)


@dataclass
class DeviceInfo:
    platform: str
    device_kind: str
    num_local: int
    num_global: int
    hbm_bytes: Optional[int] = None


class ExecutorRuntime:
    """Per-process device runtime (reference: RapidsExecutorPlugin.init)."""

    _instance: Optional["ExecutorRuntime"] = None
    _lock = threading.Lock()

    def __init__(self, conf: Optional[RapidsTpuConf] = None,
                 exit_on_fatal: bool = False):
        self.conf = conf or RapidsTpuConf()
        self.exit_on_fatal = exit_on_fatal
        self.fatal_error: Optional[BaseException] = None
        self.started_at = time.time()
        self._heartbeats: Dict[str, float] = {}
        #: executors a transport PROVED unreachable: they need a fresh
        #: register() handshake to count as live again — a stray late
        #: heartbeat must not resurrect a dead block server
        self._dead_executors: set = set()
        #: guards _heartbeats + _dead_executors together: the dead check
        #: and the stamp must be one atomic step, or a concurrent
        #: mark_unreachable between them gets silently undone
        self._hb_lock = threading.Lock()
        self._hb_senders: List[tuple] = []      # (thread, stop event)

        self._version_handshake()
        self.device = self._acquire_device()
        self.semaphore = self._init_semaphore()
        self.catalog = self._init_memory()
        atexit.register(self.shutdown)
        log.info("ExecutorRuntime up: %s", self.device)

    # ------------------------------------------------------------------

    @classmethod
    def get(cls, conf: Optional[RapidsTpuConf] = None) -> "ExecutorRuntime":
        with cls._lock:
            if cls._instance is None:
                cls._instance = ExecutorRuntime(conf)
            return cls._instance

    def _version_handshake(self) -> None:
        """Reference: cudf/JNI version checks (Plugin.scala:300-324)."""
        import jax
        ver = tuple(int(x) for x in jax.__version__.split(".")[:3])
        if ver < _MIN_JAX:
            raise RuntimeError(
                f"jax {jax.__version__} is older than the minimum supported "
                f"{'.'.join(map(str, _MIN_JAX))}")
        if not jax.config.jax_enable_x64:
            raise RuntimeError(
                "x64 mode is off — int64/float64 SQL semantics require it "
                "(spark_rapids_tpu enables it at import; something reset it)")

    def _acquire_device(self) -> DeviceInfo:
        """Reference: one GPU per executor (GpuDeviceManager.scala:93-114) —
        one TPU chip per executor process here."""
        import jax
        local = jax.local_devices()
        dev = local[0]
        hbm = None
        try:
            stats = dev.memory_stats()
            if stats:
                hbm = stats.get("bytes_limit")
        except Exception:
            pass
        return DeviceInfo(platform=dev.platform,
                          device_kind=getattr(dev, "device_kind", "?"),
                          num_local=len(local),
                          num_global=jax.device_count(), hbm_bytes=hbm)

    def _init_semaphore(self):
        from .memory.semaphore import TpuSemaphore
        return TpuSemaphore(self.conf.get(CONCURRENT_TPU_TASKS.key))

    def _init_memory(self):
        """Reference: initializeRmm pool sizing (GpuDeviceManager:192-317) —
        here the reservation budget is sized from real HBM when known."""
        from .config import LEAK_DETECTION
        from .memory.catalog import BufferCatalog
        frac = self.conf.get(HBM_POOL_FRACTION.key)
        reserve = self.conf.get(HBM_RESERVE.key)
        hbm = self.device.hbm_bytes or (16 << 30)
        limit = max(int(hbm * frac) - reserve, 1 << 30)
        return BufferCatalog(device_limit=limit,
                             host_limit=self.conf.get(HOST_SPILL_LIMIT.key),
                             spill_dir=self.conf.get(SPILL_DIR.key),
                             track_leaks=self.conf.get(LEAK_DETECTION.key))

    # ------------------------------------------------------------------
    # failure handling (reference: Plugin.scala:370-392 onTaskFailed)
    # ------------------------------------------------------------------

    FATAL_MARKERS = ("DEADLINE_EXCEEDED", "device is in an invalid state",
                     "halted")

    def classify_failure(self, exc: BaseException) -> bool:
        """True if fatal for the device (executor must be replaced).

        The device-OOM family (RESOURCE_EXHAUSTED / HBM OOM — memory/
        retry.py RETRYABLE_OOM_MARKERS, one list so classification and
        retry can never disagree) belongs to the retry state machine:
        release pins, spill, re-run, split — only a post-retry
        FinalOOMError fails the query, and even that leaves the executor
        healthy (the reference's task-level GpuOOM vs executor-fatal
        CUDA errors). An explicit fatal marker wins over an OOM marker
        in the same message: a halted device is gone no matter what
        exhausted it."""
        from .memory.retry import FinalOOMError
        if isinstance(exc, FinalOOMError):
            # the retry framework already released pins and spilled the
            # store; the query died but the device is in a clean state
            return False
        msg = str(exc)
        if any(m in msg for m in self.FATAL_MARKERS):
            return True
        # everything else — including the retryable OOM family
        # (is_retryable_oom) — leaves the device usable
        return False

    def on_task_failed(self, exc: BaseException) -> None:
        if not self.classify_failure(exc):
            return
        self.fatal_error = exc
        log.error("fatal device error; executor unusable: %s", exc)
        self._dump_device_state()
        if self.exit_on_fatal:
            sys.exit(FATAL_EXIT_CODE)

    def _dump_device_state(self) -> None:
        """Reference: nvidia-smi capture on death (Plugin.scala:341-361)."""
        try:
            import jax
            for d in jax.local_devices():
                stats = d.memory_stats() or {}
                log.error("device %s stats: %s", d, stats)
            log.error("catalog:\n%s", self.catalog.dump_state())
        except Exception:
            pass

    def ensure_healthy(self) -> None:
        if self.fatal_error is not None:
            raise RuntimeError(
                f"executor poisoned by earlier fatal error: "
                f"{self.fatal_error}")

    # ------------------------------------------------------------------
    # liveness (reference: RapidsShuffleHeartbeatManager — driver-side
    # registry of executor heartbeats for shuffle peer discovery)
    # ------------------------------------------------------------------

    def register(self, executor_id) -> None:
        """The explicit liveness handshake: clears a dead promotion and
        stamps the executor live. mark_unreachable + register is the
        full suspect→dead→rehabilitated cycle; a bare heartbeat only
        covers the live legs."""
        eid = str(executor_id)
        with self._hb_lock:
            self._dead_executors.discard(eid)
            self._heartbeats[eid] = time.time()

    def heartbeat(self, executor_id) -> bool:
        """Stamp liveness unless the executor was promoted dead; returns
        False (refused) for a dead one — it must register() afresh. The
        dead check and the stamp are ONE atomic step under the lock, so
        a concurrent mark_unreachable cannot be silently undone by a
        heartbeat that already passed the check."""
        # keys normalize to str: the CACHED-shuffle registry path hands
        # the transport INT executor ids (spark.rapids.tpu.executorId)
        # while in-process callers use strings — one table serves both
        eid = str(executor_id)
        with self._hb_lock:
            if eid in self._dead_executors:
                # a transport PROVED this executor's block server dead;
                # a stray late heartbeat must not silently resurrect it
                # into every reader's fetch ordering — rehabilitation
                # requires the explicit register() handshake
                return False
            self._heartbeats[eid] = time.time()
        return True

    def start_heartbeat(self, executor_id: str,
                        interval_s: Optional[float] = None
                        ) -> threading.Event:
        """Background sender: stamp this executor's liveness every
        interval (default: shuffle.cached.heartbeatIntervalMs conf;
        reference: RapidsShuffleHeartbeatEndpoint's executor →
        driver ping loop). Returns the stop event; shutdown() sets it."""
        stop = threading.Event()
        if interval_s is None:
            from .config import CACHED_HEARTBEAT_INTERVAL_MS
            interval_s = self.conf.get(
                CACHED_HEARTBEAT_INTERVAL_MS.key) / 1000.0

        def loop():
            # a FRESH sender is the registration handshake (the executor
            # restating itself); subsequent stamps are plain heartbeats.
            # A refused beat means this executor was promoted dead while
            # its sender is demonstrably alive (transient partition) —
            # perform the explicit re-register handshake, the same
            # rehabilitation RegistryClient._beat does on the wire. A
            # truly dead executor has no sender, so stray late beats
            # from other callers still cannot resurrect it. Re-registers
            # BACK OFF exponentially while refusals keep recurring: a
            # HALF-dead executor (heartbeat thread alive, block server
            # wedged) would otherwise undo its promotion every interval
            # and re-tax every reader's fetch with the very timeouts the
            # promotion exists to remove; the backoff resets only after
            # a sustained healthy stretch.
            self.register(executor_id)
            rereg_backoff = interval_s
            last_rereg = time.time()
            healthy = 0
            while not stop.is_set():
                if self.heartbeat(executor_id):
                    healthy += 1
                    if healthy >= 10:
                        rereg_backoff = interval_s
                else:
                    healthy = 0
                    now = time.time()
                    if now - last_rereg >= rereg_backoff:
                        self.register(executor_id)
                        last_rereg = now
                        rereg_backoff = min(rereg_backoff * 2,
                                            max(60.0, interval_s))
                stop.wait(interval_s)

        t = threading.Thread(target=loop, daemon=True,
                             name=f"heartbeat-{executor_id}")
        with self._lock:
            self._hb_senders.append((t, stop))
        t.start()
        return stop

    def mark_unreachable(self, executor_id) -> None:
        """Transport-report hook (TcpTransport.on_unreachable): a peer
        that exhausted its fetch retry budget stops counting as live
        immediately instead of coasting until its heartbeat ages out —
        subsequent list_blocks calls skip it without paying a socket
        timeout (reference: transport errors feeding the
        RapidsShuffleHeartbeatManager's executor-death bookkeeping).
        The removal is a PROMOTION to dead, not mere staleness: only an
        explicit register() brings the executor back."""
        eid = str(executor_id)
        with self._hb_lock:
            self._dead_executors.add(eid)
            self._heartbeats.pop(eid, None)

    def live_executors(self, timeout_s: Optional[float] = None
                       ) -> List[str]:
        if timeout_s is None:
            from .config import CACHED_HEARTBEAT_TIMEOUT_MS
            timeout_s = self.conf.get(
                CACHED_HEARTBEAT_TIMEOUT_MS.key) / 1000.0
        now = time.time()
        with self._hb_lock:
            # snapshot under the same lock the sender threads stamp
            # under — iterating a dict a register() is inserting into
            # raises "dictionary changed size during iteration"
            return [e for e, t in self._heartbeats.items()
                    if now - t <= timeout_s]

    def shutdown(self) -> None:
        # deterministic teardown: stop AND join the senders so no stamp
        # can land after shutdown returns
        for t, stop in list(getattr(self, "_hb_senders", [])):
            stop.set()
        for t, stop in list(getattr(self, "_hb_senders", [])):
            t.join(timeout=10)
        # the MemoryCleaner-at-shutdown analogue (reference:
        # Plugin.scala:283-298 shutdown-hook ordering): surviving catalog
        # handles at engine shutdown are leaks — log them loudly
        leaks = self.catalog.leak_check()
        if leaks:
            log.error("catalog leak check: %d handle(s) still registered "
                      "at shutdown:\n  %s", len(leaks), "\n  ".join(leaks))


def init(conf_dict: Optional[Dict] = None) -> ExecutorRuntime:
    """Engine entry point (the `spark.plugins=com.nvidia.spark.SQLPlugin`
    moment). Idempotent."""
    conf = RapidsTpuConf(conf_dict) if conf_dict else None
    return ExecutorRuntime.get(conf)
