"""UDF compiler: Python bytecode -> TPU-plannable expression trees.

Reference: udf-compiler/ (SURVEY.md §2.13) — the reference reflects a Scala
UDF's JVM bytecode (LambdaReflection.scala), walks a CFG (CFG.scala),
abstract-interprets the instructions (Instruction.scala, 980 LoC) and emits
equivalent Catalyst expressions so the UDF body becomes GPU-plannable.
Identical idea here, against CPython bytecode: `dis` is the reflection
layer, a fork-on-branch symbolic interpreter is the CFG walk, and the
output is this engine's Expression tree. Gated by
spark.rapids.tpu.sql.udfCompiler.enabled, falling back to the row
interpreter (the reference falls back to the JVM row UDF the same way).
"""

from .compiler import CompileError, compile_udf, udf

__all__ = ["compile_udf", "udf", "CompileError"]
