"""CPython-bytecode -> Expression abstract interpreter.

Mirrors the reference's three stages (udf-compiler/, SURVEY.md §2.13):
  LambdaReflection  -> `dis.get_instructions` + closure/global resolution
  CFG + Instruction -> `_Simulator`: a stack machine over Expression values
                       that FORKS at conditional jumps and joins the arms
                       with If(cond, then, else) — loops are rejected
                       (same restriction as the reference's CFG, which only
                       accepts reducible acyclic flow for expressions)
  CatalystExpressionBuilder -> the Expression constructors themselves

Supported surface: arithmetic/comparison/boolean operators, ternaries,
`is None` checks, abs/min/max, math.* calls, str methods
(upper/lower/strip/startswith/endswith/replace/ljust/rjust…), len,
constants, tuple/list/dict locals with constant subscripts, counted
range() for-loops (statically unrolled, incl. for-in-for and
for-inside-while), `while` loops compiled to ONE jax.lax.while_loop over
per-row carry slots — trip counts up to MAX_WHILE_ITERS at RUNTIME (no
unrolling), with `break`/`return` inside the body via path-composed exit
conditions and a loud per-row budget error past the cap — and nested
calls of compilable Python functions. A while nested inside another
loop is outside the subset (the mixed exit-to-outer-loop/return shape;
same reducible-CFG restriction the reference applies) and, like
everything else unsupported, raises CompileError so the planner leaves
the UDF on the CPU row path.
"""

from __future__ import annotations

import dis
import math
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from ..expressions import base as EB
from ..expressions import comparison as EC
from ..expressions import boolean as EBOOL
from ..expressions import arithmetic as EA
from ..expressions import conditional as ECOND
from ..expressions import math as EM
from ..expressions import strings as ES
from ..expressions.base import Expression, Literal, lit


class CompileError(Exception):
    pass


#: unroll budget for counted range() loops (each iteration inlines the
#: body's expression tree; beyond this the tree blows up the trace)
MAX_LOOP_TRIP = 64

#: RUNTIME iteration budget for while loops: they compile to ONE traced
#: body under jax.lax.while_loop (no unrolling — the tree and the XLA
#: program stay small no matter the trip count), so the budget is a
#: device-side counter; rows still running at the cap fail loudly
MAX_WHILE_ITERS = 65536


class _RangeIter:
    """A concrete range(...) iterator discovered at compile time."""

    def __init__(self, values):
        self.values = list(values)


class _State:
    """Mid-loop machine state returned when execution reaches a loop's
    back-edge (JUMP_BACKWARD to a FOR_ITER or while head)."""

    def __init__(self, stack, locals_, head=None):
        self.stack = stack
        self.locals = locals_
        self.head = head          # back-edge target offset


class _Partial:
    """A fork whose arms mix 'function returned' with 'loop continues':
    rows where ``exit_cond`` holds leave the loop with ``value``; the rest
    carry ``state`` into the next iteration. This is how while-loop exits
    and `return` inside loop bodies compile."""

    def __init__(self, exit_cond, value, state: "_State"):
        self.exit_cond = exit_cond
        self.value = value
        self.state = state


_SLOT_ENV: list = []


class _SlotRef(Expression):
    """Placeholder for a while-loop carry slot: the loop body/condition
    are compiled ONCE over these, and eval reads the current carry arrays
    published by _WhileLoop.run for the body trace. ``token`` scopes the
    lookup to the OWNING loop so nested loops don't collide."""

    def __init__(self, idx, dtype, nullable, token=None):
        object.__setattr__(self, "idx", idx)
        object.__setattr__(self, "_dtype", dtype)
        object.__setattr__(self, "_nullable", nullable)
        object.__setattr__(self, "token", token)

    @property
    def children(self):
        return ()

    def with_children(self, c):
        return self

    @property
    def dtype(self):
        return self._dtype

    @property
    def nullable(self):
        return self._nullable

    def eval(self, batch, ctx=EB.EvalContext()):
        for token, slots in reversed(_SLOT_ENV):
            if token is self.token:
                data, validity = slots[self.idx]
                return EB.DeviceColumn(data, validity, None, self._dtype)
        raise CompileError("slot reference outside its while-loop body")


class _WhileLoop:
    """Compile artifact: a while loop as ONE lax.while_loop over per-row
    carry slots (reference compiles loops via CFG reconstruction —
    CFG.scala; the TPU-native form keeps the trace size independent of
    the trip count). ``ret`` is the optional (exit_cond, value) pair for
    `return`/`break` inside the body."""

    def __init__(self, init_exprs, cond, body_exprs, slot_types,
                 token, ret=None):
        self.init = init_exprs          # per-slot initial Expressions
        self.cond = cond                # continue condition over _SlotRefs
        self.body = body_exprs          # per-slot next values over refs
        self.slot_types = slot_types    # [(dtype, nullable)]
        self.token = token              # slot-env scope key
        self.ret = ret                  # None | (exit_cond, value_expr)

    def run(self, batch, ctx):
        """Returns (slot_cols, returned_mask, ret_col|None); memoized per
        (loop, batch) on the context so every _WhileOut shares one
        execution."""
        import jax
        import jax.numpy as jnp
        memo = getattr(ctx, "_udf_memo", None)
        if memo is None:
            memo = {}
            object.__setattr__(ctx, "_udf_memo", memo)
        key = ("while", id(self), id(batch))
        hit = memo.get(key)
        if hit is not None and hit[0] is batch:
            return hit[1]
        init_cols = [e.eval(batch, ctx) for e in self.init]
        datas = tuple(
            c.data.astype(t.storage_dtype)
            for c, (t, _) in zip(init_cols, self.slot_types))
        valids = tuple(c.validity for c in init_cols)
        active0 = batch.row_mask()
        if self.ret is not None:
            rt = self.ret[1].dtype
            ret0 = (jnp.zeros(batch.capacity, rt.storage_dtype),
                    jnp.zeros(batch.capacity, bool))
        else:
            ret0 = (jnp.zeros(batch.capacity, jnp.int8),
                    jnp.zeros(batch.capacity, bool))
        returned0 = jnp.zeros(batch.capacity, bool)

        def cond_fn(carry):
            _, _, active, _, _, it = carry
            return jnp.any(active) & (it < MAX_WHILE_ITERS)

        def body_fn(carry):
            # DO-WHILE order: CPython places the loop test at the bottom
            # (a duplicated top guard gates ENTRY, which the simulator
            # resolved as an ordinary fork before building this loop), so
            # extraction composes both the continue condition and any
            # early-exit condition over PRE-body slot values — apply the
            # body to every active row, then test
            datas, valids, active, returned, ret, it = carry
            _SLOT_ENV.append((self.token, list(zip(datas, valids))))
            try:
                bctx = EB.EvalContext(False, None)
                upd = active
                if self.ret is not None:
                    ec = self.ret[0].eval(batch, bctx)
                    rv = self.ret[1].eval(batch, bctx)
                    hit = active & ec.data & ec.validity
                    ret = (jnp.where(hit, rv.data, ret[0]),
                           jnp.where(hit, rv.validity, ret[1]))
                    returned = returned | hit
                    upd = active & ~hit
                new = [e.eval(batch, bctx) for e in self.body]
                c = self.cond.eval(batch, bctx)
            finally:
                _SLOT_ENV.pop()
            nd = tuple(jnp.where(upd, n.data.astype(d.dtype), d)
                       for n, d in zip(new, datas))
            nv = tuple(jnp.where(upd, n.validity, v)
                       for n, v in zip(new, valids))
            nxt = upd & c.data & c.validity
            return nd, nv, nxt, returned, ret, it + 1

        datas, valids, active, returned, ret, it = jax.lax.while_loop(
            cond_fn, body_fn,
            (datas, valids, active0, returned0, ret0, jnp.int32(0)))
        # rows still wanting another iteration at the cap fail loudly
        ctx.report(active, "CAPACITY_udf_while_budget", always=True)
        out = ([EB.DeviceColumn(d, v, None, t)
                for d, v, (t, _) in zip(datas, valids, self.slot_types)],
               returned,
               EB.DeviceColumn(ret[0], ret[1], None, self.ret[1].dtype)
               if self.ret is not None else None)
        if len(memo) > 128:
            memo.clear()
        memo[key] = (batch, out)
        return out


class _WhileOut(Expression):
    """Slot i of a finished _WhileLoop (or its return value/flag)."""

    def __init__(self, loop, kind, idx, dtype, nullable):
        object.__setattr__(self, "loop", loop)
        object.__setattr__(self, "kind", kind)   # slot | ret | returned
        object.__setattr__(self, "idx", idx)
        object.__setattr__(self, "_dtype", dtype)
        object.__setattr__(self, "_nullable", nullable)

    @property
    def children(self):
        # the loop's init expressions ARE the dependency edge (binding
        # rewrites etc. never descend into loop internals — compiled
        # trees are already bound)
        return ()

    def with_children(self, c):
        return self

    @property
    def dtype(self):
        return self._dtype

    @property
    def nullable(self):
        return self._nullable

    def eval(self, batch, ctx=EB.EvalContext()):
        import jax.numpy as jnp
        slots, returned, ret = self.loop.run(batch, ctx)
        if self.kind == "slot":
            return slots[self.idx]
        if self.kind == "returned":
            return EB.DeviceColumn(returned,
                                   jnp.ones(returned.shape[0], bool),
                                   None, self._dtype)
        return ret


class _Memo(Expression):
    """Trace-time memoization wrapper. Loop unrolling produces DAGs (each
    pass's condition, value and next-state all reference the previous
    state); Expression.eval walks trees, so shared nodes would re-trace
    exponentially. One eval per (node, batch) per trace through the
    context's memo dict."""

    def __init__(self, child):
        object.__setattr__(self, "child", child)

    @property
    def children(self):
        return (self.child,)

    def with_children(self, c):
        return _Memo(c[0])

    @property
    def dtype(self):
        return self.child.dtype

    @property
    def nullable(self):
        return self.child.nullable

    def eval(self, batch, ctx=EB.EvalContext()):
        memo = getattr(ctx, "_udf_memo", None)
        if memo is None:
            memo = {}
            object.__setattr__(ctx, "_udf_memo", memo)
        key = (id(self), id(batch))
        hit = memo.get(key)
        # entries carry the batch to defeat id() reuse: a freed batch's
        # address can be recycled by a DIFFERENT batch, and returning the
        # stale column would silently corrupt results
        if hit is not None and hit[0] is batch:
            return hit[1]
        if len(memo) > 128:          # bound the default-context cache
            memo.clear()             # (entries pin their batches)
        out = self.child.eval(batch, ctx)
        memo[key] = (batch, out)
        return out


def _memo(v):
    if isinstance(v, Expression) and not isinstance(v, (Literal, _Memo)):
        return _Memo(v)
    if isinstance(v, tuple):
        return tuple(_memo(x) for x in v)
    if isinstance(v, dict):
        return {k: _memo(x) for k, x in v.items()}
    return v


class _LoopBudgetCheck(Expression):
    """Wraps a while-loop result: rows whose loop condition STILL holds
    after the unroll budget fail the query through the engine's error
    channel (never a silently wrong value)."""

    def __init__(self, still_running, value):
        object.__setattr__(self, "still", still_running)
        object.__setattr__(self, "value", value)

    @property
    def children(self):
        return (self.still, self.value)

    def with_children(self, c):
        return _LoopBudgetCheck(c[0], c[1])

    @property
    def dtype(self):
        return self.value.dtype

    @property
    def nullable(self):
        return self.value.nullable

    def eval(self, batch, ctx=EB.EvalContext()):
        cond = self.still.eval(batch, ctx)
        ctx.report(cond.data & cond.validity & batch.row_mask(),
                   "CAPACITY_udf_while_budget", always=True)
        return self.value.eval(batch, ctx)


def _py_mod(l, r):
    """Python %: floor-mod (sign of divisor). SQL Remainder is Java %
    (sign of dividend); ((a % b) + b) % b converts exactly."""
    return EA.Remainder(EA.Add(EA.Remainder(l, r), r), r)


def _py_floordiv(l, r):
    """Python //: floor division; SQL IntegralDivide truncates toward zero.
    floor = trunc - 1 when the remainder is nonzero and signs differ."""
    import copy
    trunc = EA.IntegralDivide(l, r)
    rem_nz = EC.Not(EC.EqualTo(EA.Remainder(l, r), lit(0)))
    sign_mix = EC.LessThan(EA.Multiply(l, r), lit(0))
    from ..expressions.boolean import And
    return ECOND.If(And(rem_nz, sign_mix),
                    EA.Subtract(trunc, lit(1)), trunc)


_BINARY_OPS = {
    0: lambda l, r: EA.Add(l, r),            # +
    5: lambda l, r: EA.Multiply(l, r),       # *
    10: lambda l, r: EA.Subtract(l, r),      # -
    11: lambda l, r: EA.Divide(l, r),        # /
    2: _py_floordiv,                         # //
    6: _py_mod,                              # %
    8: lambda l, r: EM.Pow(l, r),            # **
    1: lambda l, r: EA.BitwiseOp(l, r, "and"),
    7: lambda l, r: EA.BitwiseOp(l, r, "or"),
    12: lambda l, r: EA.BitwiseOp(l, r, "xor"),
    # in-place variants (x += 1 inside a lambda body via aug-assign)
    13: lambda l, r: EA.Add(l, r),
    18: lambda l, r: EA.Multiply(l, r),
    23: lambda l, r: EA.Subtract(l, r),
    24: lambda l, r: EA.Divide(l, r),
    15: _py_floordiv,
    19: _py_mod,
}

_COMPARE_OPS = {
    "<": EC.LessThan, "<=": EC.LessThanOrEqual, ">": EC.GreaterThan,
    ">=": EC.GreaterThanOrEqual, "==": EC.EqualTo,
}

_MATH_FNS = {"sqrt": "sqrt", "exp": "exp", "log": "log", "sin": "sin",
             "cos": "cos", "tan": "tan", "asin": "asin", "acos": "acos",
             "atan": "atan", "sinh": "sinh", "cosh": "cosh", "tanh": "tanh",
             "log10": "log10", "log2": "log2", "log1p": "log1p",
             "expm1": "expm1", "degrees": "degrees", "radians": "radians"}


@dataclass
class _Method:
    """A bound-method placeholder on the stack (LOAD_ATTR on a value)."""

    obj: Expression
    name: str


class _Simulator:
    def __init__(self, code, arg_exprs: List[Expression],
                 globals_: Dict[str, Any], closure: Dict[str, Any]):
        self.instructions = list(dis.get_instructions(code))
        self.by_offset = {i.offset: idx
                          for idx, i in enumerate(self.instructions)}
        # while-loop heads: JUMP_BACKWARD targets that are NOT FOR_ITER
        self.while_heads = set()
        for i in self.instructions:
            if i.opname == "JUMP_BACKWARD":
                tgt = self.by_offset.get(i.argval)
                if tgt is not None and \
                        self.instructions[tgt].opname != "FOR_ITER":
                    self.while_heads.add(i.argval)
        self.code = code
        self.globals = globals_
        self.closure = closure
        self.arg_exprs = arg_exprs
        self.nargs = len(arg_exprs)
        #: while-extraction table: head offset -> (continue_cond, exit_idx)
        self._wx = {}

    def run(self) -> Expression:
        locals_: Dict[int, Any] = dict(enumerate(self.arg_exprs))
        out = self._exec(0, [], locals_, depth=0)
        if not isinstance(out, Expression):
            raise CompileError("dangling loop state (malformed CFG)")
        return out

    def _merge_val(self, cond, x, y):
        if x is y:
            return x
        if isinstance(x, tuple) and isinstance(y, tuple) and \
                len(x) == len(y):
            return tuple(self._merge_val(cond, a, b) for a, b in zip(x, y))
        if isinstance(x, dict) and isinstance(y, dict) and \
                set(x) == set(y):
            return {k: self._merge_val(cond, x[k], y[k]) for k in x}
        return ECOND.If(cond, self._expr(x), self._expr(y))

    def _merge_states(self, cond, a: "_State", b: "_State") -> "_State":
        """Join two loop-body arms: per-slot If() where they diverge."""
        if len(a.stack) != len(b.stack):
            raise CompileError("loop arms leave different stack depths")
        if a.head != b.head:
            raise CompileError("unstructured control flow across loops")
        stack = [self._merge_val(cond, x, y)
                 for x, y in zip(a.stack, b.stack)]
        locals_ = {k: self._merge_val(cond, a.locals[k], b.locals[k])
                   for k in set(a.locals) & set(b.locals)}
        return _State(stack, locals_, a.head)

    # ------------------------------------------------------------------

    def _exec(self, idx: int, stack: List[Any], locals_: Dict[int, Any],
              depth: int, loop_heads: Tuple[int, ...] = (),
              extract: Optional[int] = None):
        if depth > 60:
            raise CompileError("branch nesting too deep")
        stack = list(stack)
        locals_ = dict(locals_)
        n = len(self.instructions)
        while idx < n:
            ins = self.instructions[idx]
            op = ins.opname
            if ins.offset in self.while_heads and \
                    ins.offset not in loop_heads:
                return self._run_while(idx, stack, locals_, depth,
                                       loop_heads)
            if op in ("RESUME", "NOP", "CACHE", "PRECALL", "PUSH_NULL",
                      "COPY_FREE_VARS", "MAKE_CELL"):
                idx += 1
            elif op == "LOAD_FAST":
                if ins.arg not in locals_:
                    raise CompileError(f"unbound local {ins.argval}")
                stack.append(locals_[ins.arg])
                idx += 1
            elif op == "STORE_FAST":
                locals_[ins.arg] = stack.pop()
                idx += 1
            elif op == "LOAD_CONST":
                v = ins.argval
                try:
                    if isinstance(v, tuple):
                        stack.append(tuple(lit(x) for x in v))
                    else:
                        stack.append(lit(v))
                except TypeError as ex:
                    raise CompileError(str(ex))
                idx += 1
            elif op == "RETURN_CONST":
                try:
                    return lit(ins.argval)
                except TypeError as ex:
                    raise CompileError(str(ex))
            elif op == "LOAD_GLOBAL":
                import builtins
                name = ins.argval
                if name in self.globals:
                    val = self.globals[name]
                else:
                    val = getattr(builtins, name, None)
                if val is None:
                    raise CompileError(f"unresolvable global {name}")
                stack.append(val)
                idx += 1
            elif op == "LOAD_DEREF":
                if ins.argval not in self.closure:
                    raise CompileError(f"unresolvable closure {ins.argval}")
                stack.append(self.closure[ins.argval])
                idx += 1
            elif op in ("LOAD_ATTR", "LOAD_METHOD"):
                obj = stack.pop()
                if isinstance(obj, Expression):
                    stack.append(_Method(obj, ins.argval))
                elif obj is math:
                    stack.append(getattr(math, ins.argval))
                else:
                    raise CompileError(f"attr {ins.argval} on {obj!r}")
                idx += 1
            elif op == "UNARY_NEGATIVE":
                stack.append(EA.UnaryMinus(self._expr(stack.pop())))
                idx += 1
            elif op == "UNARY_NOT":
                stack.append(EC.Not(self._expr(stack.pop())))
                idx += 1
            elif op == "BINARY_OP":
                r = self._expr(stack.pop())
                l = self._expr(stack.pop())
                # args >= 13 are the NB_INPLACE_* variants; on immutable
                # values they reduce to the plain operator
                fn = _BINARY_OPS.get(ins.arg if ins.arg < 13
                                     else ins.arg - 13)
                if fn is None:
                    raise CompileError(f"binary op {ins.argrepr}")
                stack.append(fn(l, r))
                idx += 1
            elif op == "COMPARE_OP":
                r = self._expr(stack.pop())
                l = self._expr(stack.pop())
                sym = ins.argval
                if sym == "!=":
                    stack.append(EC.Not(EC.EqualTo(l, r)))
                elif sym in _COMPARE_OPS:
                    stack.append(_COMPARE_OPS[sym](l, r))
                else:
                    raise CompileError(f"compare {sym}")
                idx += 1
            elif op == "IS_OP":
                r = stack.pop()
                l = self._expr(stack.pop())
                if not (isinstance(r, Literal) and r.value is None):
                    raise CompileError("`is` supported only against None")
                e = EC.IsNull(l)
                stack.append(EC.Not(e) if ins.arg == 1 else e)
                idx += 1
            elif op == "CALL":
                args = [stack.pop() for _ in range(ins.arg)][::-1]
                fn = stack.pop()
                stack.append(self._call(fn, args))
                idx += 1
            elif op in ("POP_JUMP_IF_FALSE", "POP_JUMP_IF_TRUE",
                        "POP_JUMP_IF_NONE", "POP_JUMP_IF_NOT_NONE"):
                tos = stack.pop()
                if op == "POP_JUMP_IF_FALSE":
                    cond = self._expr(tos)
                elif op == "POP_JUMP_IF_TRUE":
                    cond = EC.Not(self._expr(tos))
                elif op == "POP_JUMP_IF_NONE":
                    cond = EC.IsNotNull(self._expr(tos))
                else:
                    cond = EC.IsNull(self._expr(tos))
                then_e = self._exec(idx + 1, stack, locals_, depth + 1,
                                    loop_heads, extract)
                else_e = self._exec(self.by_offset[ins.argval], stack,
                                    locals_, depth + 1, loop_heads, extract)
                return self._join_fork(cond, then_e, else_e, loop_heads)
            elif op in ("JUMP_FORWARD", "JUMP_BACKWARD_NO_INTERRUPT"):
                tgt = self.by_offset.get(ins.argval)
                if tgt is None or tgt <= idx and op != "JUMP_FORWARD":
                    raise CompileError("backward jump (loop) unsupported")
                idx = tgt
            elif op == "JUMP_BACKWARD":
                if ins.argval in loop_heads:
                    return _State(stack, locals_, head=ins.argval)
                raise CompileError(
                    "backward jump outside any active loop (generators "
                    "and unstructured flow stay on the CPU path)")
            elif op == "GET_ITER":
                tos = stack.pop()
                if not isinstance(tos, _RangeIter):
                    raise CompileError(
                        "only range() objects are iterable here")
                stack.append(tos)
                idx += 1
            elif op == "FOR_ITER":
                it = stack[-1]
                if not isinstance(it, _RangeIter):
                    raise CompileError("FOR_ITER over a non-range value")
                # unroll: run the body once per concrete value; each
                # iteration's arms rejoin at the back-edge (reference
                # compiles loops via CFG reconstruction — CFG.scala; here
                # the trip count is static so unrolling is exact)
                cur = _State(list(stack), dict(locals_))
                for v in it.values:
                    cur = _State([_memo(x) for x in cur.stack],
                                 {k: _memo(x)
                                  for k, x in cur.locals.items()},
                                 cur.head)
                    body_stack = list(cur.stack) + [lit(v)]
                    r = self._exec(idx + 1, body_stack, cur.locals,
                                   depth + 1,
                                   loop_heads=loop_heads + (ins.offset,))
                    if not isinstance(r, _State):
                        raise CompileError(
                            "return inside a for-loop body is not "
                            "compilable (use while)")
                    cur = r
                # exhausted: fall to the loop exit (END_FOR pops the iter)
                idx = self.by_offset[ins.argval]
                stack = list(cur.stack)
                locals_ = dict(cur.locals)
            elif op == "END_FOR":
                stack.pop()
                idx += 1
            elif op == "RETURN_VALUE":
                return self._expr(stack.pop())
            elif op == "POP_TOP":
                stack.pop()
                idx += 1
            elif op in ("COPY",):
                stack.append(stack[-ins.arg])
                idx += 1
            elif op in ("SWAP",):
                stack[-1], stack[-ins.arg] = stack[-ins.arg], stack[-1]
                idx += 1
            elif op == "TO_BOOL":
                idx += 1
            elif op in ("BUILD_TUPLE", "BUILD_LIST"):
                vals = [stack.pop() for _ in range(ins.arg)][::-1]
                stack.append(tuple(vals))
                idx += 1
            elif op == "BUILD_MAP":
                pairs = [stack.pop() for _ in range(2 * ins.arg)][::-1]
                d = {}
                for k, v in zip(pairs[0::2], pairs[1::2]):
                    if not isinstance(k, Literal):
                        raise CompileError("dict keys must be constants")
                    d[k.value] = v
                stack.append(d)
                idx += 1
            elif op == "BUILD_CONST_KEY_MAP":
                keys = stack.pop()
                vals = [stack.pop() for _ in range(ins.arg)][::-1]
                kt = [k.value if isinstance(k, Literal) else k
                      for k in (keys.value if isinstance(keys, Literal)
                                else keys)]
                stack.append(dict(zip(kt, vals)))
                idx += 1
            elif op == "UNPACK_SEQUENCE":
                seq = stack.pop()
                if not isinstance(seq, tuple) or len(seq) != ins.arg:
                    raise CompileError("unpack of a non-tuple value")
                for v in reversed(seq):
                    stack.append(v)
                idx += 1
            elif op == "BINARY_SUBSCR":
                key = stack.pop()
                cont = stack.pop()
                if not isinstance(key, Literal):
                    raise CompileError("subscripts must be constants")
                if isinstance(cont, tuple):
                    try:
                        stack.append(cont[key.value])
                    except (IndexError, TypeError) as ex:
                        raise CompileError(f"tuple index: {ex}")
                elif isinstance(cont, dict):
                    if key.value not in cont:
                        raise CompileError(f"missing dict key {key.value!r}")
                    stack.append(cont[key.value])
                else:
                    raise CompileError("subscript of a non-container")
                idx += 1
            elif op == "STORE_SUBSCR":
                key = stack.pop()
                cont = stack.pop()
                val = stack.pop()
                if not (isinstance(cont, dict) and isinstance(key, Literal)):
                    raise CompileError(
                        "item assignment needs a dict local and a "
                        "constant key")
                new = dict(cont)
                new[key.value] = val
                # containers are immutable values here: rebind every
                # alias so forked arms never share mutated state
                for slot, lv in list(locals_.items()):
                    if lv is cont:
                        locals_[slot] = new
                for i2, sv in enumerate(stack):
                    if sv is cont:
                        stack[i2] = new
                idx += 1
            else:
                raise CompileError(f"unsupported opcode {op}")
        raise CompileError("fell off the end of the bytecode")

    def _join_fork(self, cond, a, b, loop_heads):
        """Join the two arms of a conditional. cond = 'arm a taken'.
        Arms may be final Expressions, continuing _States of the
        INNERMOST active loop, exit _States of an outer loop, or
        _Partials — any combination joins into the weakest common
        shape."""
        if isinstance(a, Expression) and isinstance(b, Expression):
            return ECOND.If(cond, a, b)
        cur = loop_heads[-1] if loop_heads else None

        def is_cont(x):
            return isinstance(x, _State) and x.head == cur

        if isinstance(a, _State) and isinstance(b, _State) and \
                a.head == b.head:
            return self._merge_states(cond, a, b)

        def as_partial(x, other_state, other_value):
            if isinstance(x, _Partial):
                return x
            if is_cont(x):
                return _Partial(lit(False), other_value, x)
            # exit payload: a returned Expression or an outer-loop state
            return _Partial(lit(True), x, other_state)

        val = next((x.value if isinstance(x, _Partial) else x
                    for x in (a, b)
                    if isinstance(x, _Partial) or not is_cont(x)), None)
        st = next((x.state if isinstance(x, _Partial) else x
                   for x in (a, b)
                   if isinstance(x, _Partial) or is_cont(x)), None)
        if val is None or st is None:
            raise CompileError(
                "mixed function-return and outer-loop exits (a while "
                "nested in another loop) are outside the compilable "
                "subset")
        pa = as_partial(a, st, val)
        pb = as_partial(b, st, val)
        if pa.state.head != pb.state.head:
            raise CompileError("unstructured control flow across loops")
        if pa.value is pb.value:
            value = pa.value
        elif isinstance(pa.value, _State) and isinstance(pb.value, _State):
            if pa.value.head != pb.value.head:
                raise CompileError("exits target different loops")
            value = self._merge_states(cond, pa.value, pb.value)
        elif isinstance(pa.value, _State) or isinstance(pb.value, _State):
            # one arm exits to an outer loop, the other arm's exit payload
            # is a masked dummy: keep the real state payload
            value = pa.value if isinstance(pa.value, _State) else pb.value
        else:
            value = ECOND.If(cond, self._expr(pa.value),
                             self._expr(pb.value))
        return _Partial(
            ECOND.If(cond, self._expr(pa.exit_cond),
                     self._expr(pb.exit_cond)),
            value,
            self._merge_states(cond, pa.state, pb.state))

    def _run_while(self, head_idx: int, stack, locals_, depth: int,
                   loop_heads):
        """Bounded while-loop unrolling. Each pass symbolically executes
        from the condition head: rows that exit carry their final value
        (the REST of the program evaluated at exit state); the rest loop.
        After MAX_LOOP_TRIP passes, still-running rows fail loudly via
        _LoopBudgetCheck (reference: CFG.scala loop support; the trip
        budget mirrors the for-loop unroll budget)."""
        head_off = self.instructions[head_idx].offset
        slot = self._try_slot_mode(head_idx, head_off, stack, locals_,
                                   depth, loop_heads)
        if slot is not None:
            return slot
        state = _State(list(stack), dict(locals_), head=head_off)
        exits = []                # (exit_cond, payload) per pass

        def memo_state(st):
            return _State([_memo(v) for v in st.stack],
                          {k: _memo(v) for k, v in st.locals.items()},
                          head=head_off)

        def fold(last):
            """Fold accumulated exits over the final payload."""
            out = last
            for c, v in reversed(exits):
                if isinstance(v, _State) or isinstance(out, _State):
                    if not (isinstance(v, _State) and
                            isinstance(out, _State) and
                            v.head == out.head):
                        raise CompileError(
                            "mixed return/continue exits from one loop")
                    out = self._merge_states(self._expr(c), v, out)
                else:
                    out = ECOND.If(self._expr(c), self._expr(v), out)
            return out

        for _ in range(MAX_LOOP_TRIP):
            r = self._exec(head_idx, state.stack, state.locals, depth + 1,
                           loop_heads + (head_off,))
            if isinstance(r, Expression) or (isinstance(r, _State)
                                             and r.head != head_off):
                # no continuing rows are possible: fold accumulated exits
                return fold(r)
            if isinstance(r, _State):
                # body made no exit this pass (e.g. `while True` prefix)
                state = memo_state(r)
                continue
            state = memo_state(r.state)
            exits.append((_memo(self._expr(r.exit_cond)), r.value))
        # budget exhausted: one more pass determines the residual rows
        r = self._exec(head_idx, state.stack, state.locals, depth + 1,
                       loop_heads + (head_off,))
        if isinstance(r, Expression) or (isinstance(r, _State)
                                         and r.head != head_off):
            return fold(r)
        if isinstance(r, _Partial) and not isinstance(r.value, _State):
            return fold(_LoopBudgetCheck(EC.Not(self._expr(r.exit_cond)),
                                         self._expr(r.value)))
        raise CompileError(
            f"while loop never exits within the unroll budget "
            f"({MAX_LOOP_TRIP})")

    def _try_slot_mode(self, head_idx, head_off, stack, locals_, depth,
                       loop_heads):
        """Compile the while loop as ONE lax.while_loop over carry slots
        (trace size independent of the trip count; runtime budget
        MAX_WHILE_ITERS). One symbolic pass from the head yields a
        _Partial whose PATH-COMPOSED exit condition covers every way out
        (the loop test, `break`, `return`) and whose value is the rest of
        the function over the loop state — so the runtime is uniform:
        test the exit first, apply the body to survivors. None = shape
        outside slot mode; the caller falls back to bounded unrolling."""
        from .. import types as TT
        if stack:
            return None
        flat = (TT.TypeKind.INT8, TT.TypeKind.INT16, TT.TypeKind.INT32,
                TT.TypeKind.INT64, TT.TypeKind.FLOAT32, TT.TypeKind.FLOAT64,
                TT.TypeKind.BOOLEAN, TT.TypeKind.DATE, TT.TypeKind.TIMESTAMP)
        slot_ids = []
        for k, v in locals_.items():
            if isinstance(v, Expression):
                try:
                    if v.dtype.kind not in flat:
                        return None
                except Exception:       # noqa: BLE001
                    return None
                slot_ids.append(k)
        slot_ids.sort()
        types = [(locals_[k].dtype, locals_[k].nullable) for k in slot_ids]
        token = object()
        for _ in range(3):              # dtype fixed point (int -> float)
            refs = {k: _SlotRef(i, t, nl, token)
                    for i, (k, (t, nl)) in enumerate(zip(slot_ids, types))}
            ref_locals = dict(locals_)
            ref_locals.update(refs)
            try:
                r = self._exec(head_idx, [], ref_locals, depth + 1,
                               loop_heads + (head_off,))
            except CompileError:
                return None
            if not isinstance(r, _Partial) or r.state.head != head_off \
                    or not isinstance(r.value, Expression):
                return None
            st = r.state
            body_vals = []
            new_types = []
            ok = True
            for k, (t, nl) in zip(slot_ids, types):
                v = st.locals.get(k)
                if not isinstance(v, Expression):
                    ok = False
                    break
                try:
                    vt, vn = v.dtype, v.nullable or nl
                except Exception:       # noqa: BLE001
                    ok = False
                    break
                if vt.kind not in flat:
                    ok = False
                    break
                body_vals.append(v)
                new_types.append((vt, vn))
            if not ok:
                return None
            # containers must come through the body UNCHANGED (they are
            # loop constants in slot mode)
            for k, v in locals_.items():
                if not isinstance(v, Expression) and \
                        st.locals.get(k) is not v:
                    return None
            if new_types == types:
                break
            types = new_types
        else:
            return None                 # dtypes never stabilized
        from ..expressions.cast import Cast
        init = []
        for k, (t, _) in zip(slot_ids, types):
            e = locals_[k]
            init.append(e if e.dtype == t else Cast(e, t))
        ret = (self._expr(r.exit_cond), self._expr(r.value))
        loop = _WhileLoop(init, lit(True), body_vals, types, token, ret)
        # every row exits through ret (the loop test is one of its
        # paths); the loop's return value IS the function's remainder
        return _WhileOut(loop, "ret", 0, ret[1].dtype, True)

    # ------------------------------------------------------------------

    def _expr(self, v) -> Expression:
        if isinstance(v, Expression):
            return v
        if isinstance(v, (int, float, str, bool)) or v is None:
            return lit(v)
        if isinstance(v, (tuple, dict)):
            raise CompileError(
                "tuple/dict values may be stored and indexed but not "
                "returned or used as scalars")
        raise CompileError(f"non-expression on stack: {v!r}")

    def _call(self, fn, args):
        import builtins
        if fn is builtins.range:
            vals = []
            for a in args:
                if not (isinstance(a, Literal)
                        and isinstance(a.value, int)):
                    raise CompileError(
                        "range() bounds must be compile-time constants")
                vals.append(a.value)
            r = range(*vals)
            if len(r) > MAX_LOOP_TRIP:
                raise CompileError(
                    f"loop trip count {len(r)} exceeds the unroll budget "
                    f"({MAX_LOOP_TRIP})")
            return _RangeIter(r)
        if isinstance(fn, _Method):
            return self._str_method(fn, args)
        if fn is builtins.abs:
            return EA.Abs(self._expr(args[0]))
        if fn is builtins.min:
            return ECOND.LeastGreatest(
                tuple(self._expr(a) for a in args), greatest=False)
        if fn is builtins.max:
            return ECOND.LeastGreatest(
                tuple(self._expr(a) for a in args), greatest=True)
        if fn is builtins.len:
            return ES.Length(self._expr(args[0]))
        if fn is builtins.float:
            from .. import types as T
            from ..expressions.cast import Cast
            return Cast(self._expr(args[0]), T.FLOAT64)
        if fn is builtins.int:
            from .. import types as T
            from ..expressions.cast import Cast
            return Cast(self._expr(args[0]), T.INT64)
        if fn is builtins.round and len(args) <= 2:
            scale = 0
            if len(args) == 2:
                s = args[1]
                if not isinstance(s, Literal):
                    raise CompileError("round() scale must be constant")
                scale = s.value
            return EM.Round(self._expr(args[0]), scale, half_even=True)
        if callable(fn) and getattr(fn, "__module__", "") == "math":
            name = fn.__name__
            if name in _MATH_FNS:
                return EM.UnaryMath(self._expr(args[0]), _MATH_FNS[name])
            if name == "pow":
                return EM.Pow(self._expr(args[0]), self._expr(args[1]))
            if name == "atan2":
                return EM.Atan2(self._expr(args[0]), self._expr(args[1]))
            if name == "floor":
                return EM.FloorCeil(self._expr(args[0]), is_ceil=False)
            if name == "ceil":
                return EM.FloorCeil(self._expr(args[0]), is_ceil=True)
        if callable(fn) and hasattr(fn, "__code__"):
            # nested Python function: inline-compile it (reference: the udf
            # compiler recurses into called methods the same way)
            inner_args = [self._expr(a) for a in args]
            return _compile_code(fn, inner_args)
        raise CompileError(f"uncompilable call target {fn!r}")

    def _str_method(self, m: _Method, args) -> Expression:
        name = m.name
        obj = m.obj
        if name == "upper":
            return ES.Upper(obj)
        if name == "lower":
            return ES.Lower(obj)
        if name == "strip":
            return ES.StringTrim(obj, "both")
        if name == "lstrip":
            return ES.StringTrim(obj, "leading")
        if name == "rstrip":
            return ES.StringTrim(obj, "trailing")
        if name == "startswith":
            return ES.StringPredicate(obj, self._expr(args[0]), "startswith")
        if name == "endswith":
            return ES.StringPredicate(obj, self._expr(args[0]), "endswith")
        if name == "replace":
            return ES.StringReplace(obj, self._expr(args[0]),
                                    self._expr(args[1]))
        if name == "find":
            return EA.Subtract(ES.StringLocate(obj, self._expr(args[0])),
                               lit(1))
        if name in ("ljust", "rjust"):
            pad = self._expr(args[1]) if len(args) > 1 else lit(" ")
            return ES.StringPad(obj, self._expr(args[0]), pad,
                                left=(name == "rjust"))
        raise CompileError(f"string method {name}")


def _compile_code(fn, arg_exprs: List[Expression]) -> Expression:
    code = fn.__code__
    if code.co_argcount != len(arg_exprs):
        raise CompileError(
            f"UDF takes {code.co_argcount} args, got {len(arg_exprs)}")
    closure = {}
    if fn.__closure__:
        for name, cell in zip(code.co_freevars, fn.__closure__):
            closure[name] = cell.cell_contents
    sim = _Simulator(code, arg_exprs, fn.__globals__, closure)
    return sim.run()


def compile_udf(fn, arg_exprs: List[Expression]) -> Expression:
    """Compile a Python function of N args applied to N column expressions
    into an equivalent Expression tree. Raises CompileError if any construct
    falls outside the supported surface."""
    return _compile_code(fn, list(arg_exprs))


def udf(fn):
    """Decorator: returns a callable that builds compiled expressions —
    `my_udf(col("x"))` yields the translated tree (or raises CompileError,
    which the planner turns into a CPU fallback)."""

    def apply(*cols):
        return compile_udf(fn, list(cols))

    apply.__wrapped__ = fn
    return apply
