"""CPython-bytecode -> Expression abstract interpreter.

Mirrors the reference's three stages (udf-compiler/, SURVEY.md §2.13):
  LambdaReflection  -> `dis.get_instructions` + closure/global resolution
  CFG + Instruction -> `_Simulator`: a stack machine over Expression values
                       that FORKS at conditional jumps and joins the arms
                       with If(cond, then, else) — loops are rejected
                       (same restriction as the reference's CFG, which only
                       accepts reducible acyclic flow for expressions)
  CatalystExpressionBuilder -> the Expression constructors themselves

Supported surface: arithmetic/comparison/boolean operators, ternaries,
`is None` checks, abs/min/max, math.* calls, str methods
(upper/lower/strip/startswith/endswith/replace…), len, constants, nested
calls of already-compiled UDFs. Anything else raises CompileError and the
planner leaves the UDF on the CPU row path.
"""

from __future__ import annotations

import dis
import math
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from ..expressions import base as EB
from ..expressions import comparison as EC
from ..expressions import boolean as EBOOL
from ..expressions import arithmetic as EA
from ..expressions import conditional as ECOND
from ..expressions import math as EM
from ..expressions import strings as ES
from ..expressions.base import Expression, Literal, lit


class CompileError(Exception):
    pass


#: unroll budget for counted range() loops (each iteration inlines the
#: body's expression tree; beyond this the tree blows up the trace)
MAX_LOOP_TRIP = 64


class _RangeIter:
    """A concrete range(...) iterator discovered at compile time."""

    def __init__(self, values):
        self.values = list(values)


class _State:
    """Mid-loop machine state returned when execution reaches the loop's
    back-edge (JUMP_BACKWARD to the FOR_ITER head)."""

    def __init__(self, stack, locals_):
        self.stack = stack
        self.locals = locals_


def _py_mod(l, r):
    """Python %: floor-mod (sign of divisor). SQL Remainder is Java %
    (sign of dividend); ((a % b) + b) % b converts exactly."""
    return EA.Remainder(EA.Add(EA.Remainder(l, r), r), r)


def _py_floordiv(l, r):
    """Python //: floor division; SQL IntegralDivide truncates toward zero.
    floor = trunc - 1 when the remainder is nonzero and signs differ."""
    import copy
    trunc = EA.IntegralDivide(l, r)
    rem_nz = EC.Not(EC.EqualTo(EA.Remainder(l, r), lit(0)))
    sign_mix = EC.LessThan(EA.Multiply(l, r), lit(0))
    from ..expressions.boolean import And
    return ECOND.If(And(rem_nz, sign_mix),
                    EA.Subtract(trunc, lit(1)), trunc)


_BINARY_OPS = {
    0: lambda l, r: EA.Add(l, r),            # +
    5: lambda l, r: EA.Multiply(l, r),       # *
    10: lambda l, r: EA.Subtract(l, r),      # -
    11: lambda l, r: EA.Divide(l, r),        # /
    2: _py_floordiv,                         # //
    6: _py_mod,                              # %
    8: lambda l, r: EM.Pow(l, r),            # **
    1: lambda l, r: EA.BitwiseOp(l, r, "and"),
    7: lambda l, r: EA.BitwiseOp(l, r, "or"),
    12: lambda l, r: EA.BitwiseOp(l, r, "xor"),
    # in-place variants (x += 1 inside a lambda body via aug-assign)
    13: lambda l, r: EA.Add(l, r),
    18: lambda l, r: EA.Multiply(l, r),
    23: lambda l, r: EA.Subtract(l, r),
    24: lambda l, r: EA.Divide(l, r),
    15: _py_floordiv,
    19: _py_mod,
}

_COMPARE_OPS = {
    "<": EC.LessThan, "<=": EC.LessThanOrEqual, ">": EC.GreaterThan,
    ">=": EC.GreaterThanOrEqual, "==": EC.EqualTo,
}

_MATH_FNS = {"sqrt": "sqrt", "exp": "exp", "log": "log", "sin": "sin",
             "cos": "cos", "tan": "tan", "asin": "asin", "acos": "acos",
             "atan": "atan", "sinh": "sinh", "cosh": "cosh", "tanh": "tanh",
             "log10": "log10", "log2": "log2", "log1p": "log1p",
             "expm1": "expm1", "degrees": "degrees", "radians": "radians"}


@dataclass
class _Method:
    """A bound-method placeholder on the stack (LOAD_ATTR on a value)."""

    obj: Expression
    name: str


class _Simulator:
    def __init__(self, code, arg_exprs: List[Expression],
                 globals_: Dict[str, Any], closure: Dict[str, Any]):
        self.instructions = list(dis.get_instructions(code))
        self.by_offset = {i.offset: idx
                          for idx, i in enumerate(self.instructions)}
        self.code = code
        self.globals = globals_
        self.closure = closure
        self.arg_exprs = arg_exprs
        self.nargs = len(arg_exprs)

    def run(self) -> Expression:
        locals_: Dict[int, Any] = dict(enumerate(self.arg_exprs))
        out = self._exec(0, [], locals_, depth=0)
        if isinstance(out, _State):
            raise CompileError("dangling loop state (malformed CFG)")
        return out

    def _merge_states(self, cond, a: "_State", b: "_State") -> "_State":
        """Join two loop-body arms: per-slot If() where they diverge."""
        if len(a.stack) != len(b.stack):
            raise CompileError("loop arms leave different stack depths")
        stack = []
        for x, y in zip(a.stack, b.stack):
            stack.append(x if x is y
                         else ECOND.If(cond, self._expr(x), self._expr(y)))
        locals_ = {}
        for k in set(a.locals) & set(b.locals):
            x, y = a.locals[k], b.locals[k]
            if x is y:
                locals_[k] = x
            else:
                locals_[k] = ECOND.If(cond, self._expr(x), self._expr(y))
        return _State(stack, locals_)

    # ------------------------------------------------------------------

    def _exec(self, idx: int, stack: List[Any], locals_: Dict[int, Any],
              depth: int, loop_head: Optional[int] = None):
        if depth > 40:
            raise CompileError("branch nesting too deep")
        stack = list(stack)
        locals_ = dict(locals_)
        n = len(self.instructions)
        while idx < n:
            ins = self.instructions[idx]
            op = ins.opname
            if op in ("RESUME", "NOP", "CACHE", "PRECALL", "PUSH_NULL",
                      "COPY_FREE_VARS", "MAKE_CELL"):
                idx += 1
            elif op == "LOAD_FAST":
                if ins.arg not in locals_:
                    raise CompileError(f"unbound local {ins.argval}")
                stack.append(locals_[ins.arg])
                idx += 1
            elif op == "STORE_FAST":
                locals_[ins.arg] = stack.pop()
                idx += 1
            elif op == "LOAD_CONST":
                try:
                    stack.append(lit(ins.argval))
                except TypeError as ex:
                    raise CompileError(str(ex))
                idx += 1
            elif op == "RETURN_CONST":
                try:
                    return lit(ins.argval)
                except TypeError as ex:
                    raise CompileError(str(ex))
            elif op == "LOAD_GLOBAL":
                import builtins
                name = ins.argval
                if name in self.globals:
                    val = self.globals[name]
                else:
                    val = getattr(builtins, name, None)
                if val is None:
                    raise CompileError(f"unresolvable global {name}")
                stack.append(val)
                idx += 1
            elif op == "LOAD_DEREF":
                if ins.argval not in self.closure:
                    raise CompileError(f"unresolvable closure {ins.argval}")
                stack.append(self.closure[ins.argval])
                idx += 1
            elif op in ("LOAD_ATTR", "LOAD_METHOD"):
                obj = stack.pop()
                if isinstance(obj, Expression):
                    stack.append(_Method(obj, ins.argval))
                elif obj is math:
                    stack.append(getattr(math, ins.argval))
                else:
                    raise CompileError(f"attr {ins.argval} on {obj!r}")
                idx += 1
            elif op == "UNARY_NEGATIVE":
                stack.append(EA.UnaryMinus(self._expr(stack.pop())))
                idx += 1
            elif op == "UNARY_NOT":
                stack.append(EC.Not(self._expr(stack.pop())))
                idx += 1
            elif op == "BINARY_OP":
                r = self._expr(stack.pop())
                l = self._expr(stack.pop())
                # args >= 13 are the NB_INPLACE_* variants; on immutable
                # values they reduce to the plain operator
                fn = _BINARY_OPS.get(ins.arg if ins.arg < 13
                                     else ins.arg - 13)
                if fn is None:
                    raise CompileError(f"binary op {ins.argrepr}")
                stack.append(fn(l, r))
                idx += 1
            elif op == "COMPARE_OP":
                r = self._expr(stack.pop())
                l = self._expr(stack.pop())
                sym = ins.argval
                if sym == "!=":
                    stack.append(EC.Not(EC.EqualTo(l, r)))
                elif sym in _COMPARE_OPS:
                    stack.append(_COMPARE_OPS[sym](l, r))
                else:
                    raise CompileError(f"compare {sym}")
                idx += 1
            elif op == "IS_OP":
                r = stack.pop()
                l = self._expr(stack.pop())
                if not (isinstance(r, Literal) and r.value is None):
                    raise CompileError("`is` supported only against None")
                e = EC.IsNull(l)
                stack.append(EC.Not(e) if ins.arg == 1 else e)
                idx += 1
            elif op == "CALL":
                args = [stack.pop() for _ in range(ins.arg)][::-1]
                fn = stack.pop()
                stack.append(self._call(fn, args))
                idx += 1
            elif op in ("POP_JUMP_IF_FALSE", "POP_JUMP_IF_TRUE",
                        "POP_JUMP_IF_NONE", "POP_JUMP_IF_NOT_NONE"):
                tos = stack.pop()
                if op == "POP_JUMP_IF_FALSE":
                    cond = self._expr(tos)
                elif op == "POP_JUMP_IF_TRUE":
                    cond = EC.Not(self._expr(tos))
                elif op == "POP_JUMP_IF_NONE":
                    cond = EC.IsNotNull(self._expr(tos))
                else:
                    cond = EC.IsNull(self._expr(tos))
                then_e = self._exec(idx + 1, stack, locals_, depth + 1,
                                    loop_head)
                else_e = self._exec(self.by_offset[ins.argval], stack,
                                    locals_, depth + 1, loop_head)
                if isinstance(then_e, _State) and isinstance(else_e, _State):
                    return self._merge_states(cond, then_e, else_e)
                if isinstance(then_e, _State) or isinstance(else_e, _State):
                    raise CompileError(
                        "return inside a loop body is not compilable")
                return ECOND.If(cond, then_e, else_e)
            elif op in ("JUMP_FORWARD", "JUMP_BACKWARD_NO_INTERRUPT"):
                tgt = self.by_offset.get(ins.argval)
                if tgt is None or tgt <= idx and op != "JUMP_FORWARD":
                    raise CompileError("backward jump (loop) unsupported")
                idx = tgt
            elif op == "JUMP_BACKWARD":
                if loop_head is not None and ins.argval == loop_head:
                    return _State(stack, locals_)
                raise CompileError(
                    "only counted range() for-loops are compilable "
                    "(while loops and generators stay on the CPU path)")
            elif op == "GET_ITER":
                tos = stack.pop()
                if not isinstance(tos, _RangeIter):
                    raise CompileError(
                        "only range() objects are iterable here")
                stack.append(tos)
                idx += 1
            elif op == "FOR_ITER":
                it = stack[-1]
                if not isinstance(it, _RangeIter):
                    raise CompileError("FOR_ITER over a non-range value")
                # unroll: run the body once per concrete value; each
                # iteration's arms rejoin at the back-edge (reference
                # compiles loops via CFG reconstruction — CFG.scala; here
                # the trip count is static so unrolling is exact)
                cur = _State(list(stack), dict(locals_))
                for v in it.values:
                    body_stack = list(cur.stack) + [lit(v)]
                    r = self._exec(idx + 1, body_stack, cur.locals,
                                   depth + 1, loop_head=ins.offset)
                    if not isinstance(r, _State):
                        raise CompileError(
                            "return inside a loop body is not compilable")
                    cur = r
                # exhausted: fall to the loop exit (END_FOR pops the iter)
                idx = self.by_offset[ins.argval]
                stack = list(cur.stack)
                locals_ = dict(cur.locals)
            elif op == "END_FOR":
                stack.pop()
                idx += 1
            elif op == "RETURN_VALUE":
                return self._expr(stack.pop())
            elif op == "POP_TOP":
                stack.pop()
                idx += 1
            elif op in ("COPY",):
                stack.append(stack[-ins.arg])
                idx += 1
            elif op in ("SWAP",):
                stack[-1], stack[-ins.arg] = stack[-ins.arg], stack[-1]
                idx += 1
            elif op == "TO_BOOL":
                idx += 1
            else:
                raise CompileError(f"unsupported opcode {op}")
        raise CompileError("fell off the end of the bytecode")

    # ------------------------------------------------------------------

    def _expr(self, v) -> Expression:
        if isinstance(v, Expression):
            return v
        if isinstance(v, (int, float, str, bool)) or v is None:
            return lit(v)
        raise CompileError(f"non-expression on stack: {v!r}")

    def _call(self, fn, args):
        import builtins
        if fn is builtins.range:
            vals = []
            for a in args:
                if not (isinstance(a, Literal)
                        and isinstance(a.value, int)):
                    raise CompileError(
                        "range() bounds must be compile-time constants")
                vals.append(a.value)
            r = range(*vals)
            if len(r) > MAX_LOOP_TRIP:
                raise CompileError(
                    f"loop trip count {len(r)} exceeds the unroll budget "
                    f"({MAX_LOOP_TRIP})")
            return _RangeIter(r)
        if isinstance(fn, _Method):
            return self._str_method(fn, args)
        if fn is builtins.abs:
            return EA.Abs(self._expr(args[0]))
        if fn is builtins.min:
            return ECOND.LeastGreatest(
                tuple(self._expr(a) for a in args), greatest=False)
        if fn is builtins.max:
            return ECOND.LeastGreatest(
                tuple(self._expr(a) for a in args), greatest=True)
        if fn is builtins.len:
            return ES.Length(self._expr(args[0]))
        if fn is builtins.float:
            from .. import types as T
            from ..expressions.cast import Cast
            return Cast(self._expr(args[0]), T.FLOAT64)
        if fn is builtins.int:
            from .. import types as T
            from ..expressions.cast import Cast
            return Cast(self._expr(args[0]), T.INT64)
        if fn is builtins.round and len(args) <= 2:
            scale = 0
            if len(args) == 2:
                s = args[1]
                if not isinstance(s, Literal):
                    raise CompileError("round() scale must be constant")
                scale = s.value
            return EM.Round(self._expr(args[0]), scale, half_even=True)
        if callable(fn) and getattr(fn, "__module__", "") == "math":
            name = fn.__name__
            if name in _MATH_FNS:
                return EM.UnaryMath(self._expr(args[0]), _MATH_FNS[name])
            if name == "pow":
                return EM.Pow(self._expr(args[0]), self._expr(args[1]))
            if name == "atan2":
                return EM.Atan2(self._expr(args[0]), self._expr(args[1]))
            if name == "floor":
                return EM.FloorCeil(self._expr(args[0]), is_ceil=False)
            if name == "ceil":
                return EM.FloorCeil(self._expr(args[0]), is_ceil=True)
        if callable(fn) and hasattr(fn, "__code__"):
            # nested Python function: inline-compile it (reference: the udf
            # compiler recurses into called methods the same way)
            inner_args = [self._expr(a) for a in args]
            return _compile_code(fn, inner_args)
        raise CompileError(f"uncompilable call target {fn!r}")

    def _str_method(self, m: _Method, args) -> Expression:
        name = m.name
        obj = m.obj
        if name == "upper":
            return ES.Upper(obj)
        if name == "lower":
            return ES.Lower(obj)
        if name == "strip":
            return ES.StringTrim(obj, "both")
        if name == "lstrip":
            return ES.StringTrim(obj, "leading")
        if name == "rstrip":
            return ES.StringTrim(obj, "trailing")
        if name == "startswith":
            return ES.StringPredicate(obj, self._expr(args[0]), "startswith")
        if name == "endswith":
            return ES.StringPredicate(obj, self._expr(args[0]), "endswith")
        if name == "replace":
            return ES.StringReplace(obj, self._expr(args[0]),
                                    self._expr(args[1]))
        if name == "find":
            return EA.Subtract(ES.StringLocate(obj, self._expr(args[0])),
                               lit(1))
        raise CompileError(f"string method {name}")


def _compile_code(fn, arg_exprs: List[Expression]) -> Expression:
    code = fn.__code__
    if code.co_argcount != len(arg_exprs):
        raise CompileError(
            f"UDF takes {code.co_argcount} args, got {len(arg_exprs)}")
    closure = {}
    if fn.__closure__:
        for name, cell in zip(code.co_freevars, fn.__closure__):
            closure[name] = cell.cell_contents
    sim = _Simulator(code, arg_exprs, fn.__globals__, closure)
    return sim.run()


def compile_udf(fn, arg_exprs: List[Expression]) -> Expression:
    """Compile a Python function of N args applied to N column expressions
    into an equivalent Expression tree. Raises CompileError if any construct
    falls outside the supported surface."""
    return _compile_code(fn, list(arg_exprs))


def udf(fn):
    """Decorator: returns a callable that builds compiled expressions —
    `my_udf(col("x"))` yields the translated tree (or raises CompileError,
    which the planner turns into a CPU fallback)."""

    def apply(*cols):
        return compile_udf(fn, list(cols))

    apply.__wrapped__ = fn
    return apply
