"""Deterministic network fault injection at the transport frame seam.

Reference: RapidsShuffleClientSuite / RapidsShuffleTestHelper — the UCX
shuffle's retry/transaction story is tested against mocked transports
that drop, corrupt, and stall transactions on a schedule. The TPU twin
is modeled on the OOM injector (memory/retry.py `OomInjector`,
RmmSpark's forceRetryOOM shape): a process-wide injector configured by
``spark.rapids.tpu.test.injectNet.{mode,seed,skipCount,faultKind,
delayMs}`` whose hooks sit inside `transport._send_frame` /
`transport._recv_frame`, so every fault lands exactly where a real
network would deliver it — AFTER checksums are computed on the send
side, BEFORE they are verified on the receive side.

Fault kinds (``faultKind``):

- ``drop``      — the connection closes mid-transaction (peer crash /
                  RST); the client's retry loop must reconnect.
- ``delay``     — the frame stalls ``delayMs`` (congestion); nothing
                  fails, deadlines and pipelining absorb it.
- ``truncate``  — the frame is cut short and the connection closes
                  (peer died mid-send); the receiver sees a mid-frame
                  EOF.
- ``corrupt``   — one payload bit flips after the CRC was computed;
                  the RECEIVER's checksum verification must catch it
                  and classify the fetch BlockCorruptError.
- ``mix``       — cycles drop → delay → truncate → corrupt per trigger.

Scheduling mirrors the OOM injector exactly: ``every-N`` fires on every
Nth eligible frame, ``random[-P]`` with seeded probability; re-attempts
inside a transport retry scope are ``suppressed()`` (no NEW triggers, so
recovery terminates), and the first check after a trigger is an
uncounted free pass so even ``every-1`` converges.
"""

from __future__ import annotations

import random
import threading
import time
from contextlib import contextmanager
from typing import Optional

FAULT_KINDS = ("drop", "delay", "truncate", "corrupt")


class InjectedNetError(ConnectionError):
    """Synthetic transport fault from the injection layer (test-only).
    A ConnectionError so production classification (retry + reconnect)
    is exercised end to end, like InjectedOOMError rides
    OutOfBudgetError."""


class NetInjector:
    """Decides, per transport frame, whether (and how) it faults."""

    def __init__(self):
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._gen = 0
        self.configure("")

    def configure(self, mode: str, seed: int = 0, skip_count: int = 0,
                  fault_kind: str = "drop", delay_ms: int = 20) -> None:
        with self._lock:
            mode = (mode or "").strip().lower()
            self._mode = mode
            self._every = 0
            self._p = 0.0
            if mode.startswith("every-"):
                self._every = max(int(mode.split("-", 1)[1]), 1)
            elif mode.startswith("random"):
                self._p = float(mode.split("-", 1)[1]) \
                    if "-" in mode else 0.2
            elif mode not in ("", "off"):
                raise ValueError(f"unknown injectNet.mode {mode!r}")
            fault_kind = (fault_kind or "drop").strip().lower()
            if fault_kind not in FAULT_KINDS + ("mix",):
                raise ValueError(f"unknown injectNet.faultKind "
                                 f"{fault_kind!r}")
            self._kind = fault_kind
            self._delay_s = max(int(delay_ms), 0) / 1000.0
            self._rng = random.Random(seed)
            self._skip_left = max(int(skip_count), 0)
            self._checks = 0
            self.injected = 0
            # invalidate thread-local free-pass state WITHOUT replacing
            # self._tls — another thread may be inside suppressed() right
            # now (same hazard the OOM injector documents)
            self._gen += 1

    @property
    def enabled(self) -> bool:
        return bool(self._every or self._p)

    @contextmanager
    def suppressed(self):
        """Scope for transport retry re-attempts: no NEW faults fire
        inside, so recovery terminates under every-1 schedules."""
        self._tls.suppress = getattr(self._tls, "suppress", 0) + 1
        try:
            yield
        finally:
            self._tls.suppress = max(
                getattr(self._tls, "suppress", 1) - 1, 0)

    def decide(self, site: str) -> Optional[str]:
        """Returns the fault kind this frame suffers, or None. The
        transport seam applies the kind's mechanics (close/sleep/flip)."""
        if not self.enabled:
            return None
        if getattr(self._tls, "gen", -1) != self._gen:
            self._tls.gen = self._gen
            self._tls.free = False
        if getattr(self._tls, "free", False):
            # post-trigger free pass: the retry that follows a fault must
            # be able to make progress even outside a suppressed() scope
            self._tls.free = False
            return None
        if getattr(self._tls, "suppress", 0) > 0:
            return None
        with self._lock:
            if self._skip_left > 0:
                self._skip_left -= 1
                return None
            self._checks += 1
            n = self._checks
            fire = (self._every and n % self._every == 0) or \
                (self._p and self._rng.random() < self._p)
            if not fire:
                return None
            self.injected += 1
            kind = self._kind
            if kind == "mix":
                kind = FAULT_KINDS[(self.injected - 1) % len(FAULT_KINDS)]
        self._tls.free = True
        return kind

    @property
    def delay_s(self) -> float:
        return self._delay_s


_INJECTOR = NetInjector()


def net_injector() -> NetInjector:
    return _INJECTOR


def apply_session_conf(conf) -> None:
    """Install a session's injectNet settings process-wide (the same
    executor-singleton shape as the OOM injector: the last session to
    run configures it)."""
    from ..config import (INJECT_NET_DELAY_MS, INJECT_NET_FAULT_KIND,
                          INJECT_NET_MODE, INJECT_NET_SEED,
                          INJECT_NET_SKIP_COUNT)
    _INJECTOR.configure(str(conf.get(INJECT_NET_MODE.key)),
                        int(conf.get(INJECT_NET_SEED.key)),
                        int(conf.get(INJECT_NET_SKIP_COUNT.key)),
                        str(conf.get(INJECT_NET_FAULT_KIND.key)),
                        int(conf.get(INJECT_NET_DELAY_MS.key)))


@contextmanager
def net_injection(mode: str, seed: int = 0, skip_count: int = 0,
                  fault_kind: str = "drop", delay_ms: int = 20):
    """Test helper: enable injection inside the block, restore off after."""
    _INJECTOR.configure(mode, seed, skip_count, fault_kind, delay_ms)
    try:
        yield _INJECTOR
    finally:
        _INJECTOR.configure("")


def _flip_bit(payload: bytes) -> bytes:
    """Deterministic single-bit corruption of a frame payload."""
    if not payload:
        return payload
    buf = bytearray(payload)
    buf[len(buf) // 2] ^= 0x40
    return bytes(buf)


def fault_send(sock, frame: bytes, site: str) -> bytes:
    """Send-side seam: returns the (possibly corrupted) frame to put on
    the wire, or raises/closes per the scheduled fault. ``frame`` is the
    complete encoded frame INCLUDING its checksum."""
    kind = _INJECTOR.decide(site)
    if kind is None:
        return frame
    if kind == "delay":
        time.sleep(_INJECTOR.delay_s)
        return frame
    if kind == "corrupt":
        # flip a payload bit past the frame header: the header's CRC was
        # computed over the clean payload, so the receiver must reject it
        head = min(13, len(frame) - 1)
        return frame[:head] + _flip_bit(frame[head:])
    if kind == "truncate":
        try:
            sock.sendall(frame[: max(len(frame) // 2, 1)])
        except OSError:  # net-ok: the injected close is the fault itself
            pass
        _close(sock)
        raise InjectedNetError(f"injected truncate at {site}")
    _close(sock)                                   # kind == "drop"
    raise InjectedNetError(f"injected connection drop at {site}")


def fault_recv(sock, payload: bytes, site: str) -> bytes:
    """Receive-side seam: returns the (possibly corrupted) payload, or
    raises per the scheduled fault — BEFORE checksum verification."""
    kind = _INJECTOR.decide(site)
    if kind is None:
        return payload
    if kind == "delay":
        time.sleep(_INJECTOR.delay_s)
        return payload
    if kind == "corrupt":
        return _flip_bit(payload)
    _close(sock)                  # truncate/drop: mid-frame peer death
    raise InjectedNetError(f"injected {kind} at {site}")


def _close(sock) -> None:
    try:
        sock.close()
    except OSError:  # net-ok: injected teardown, best-effort close
        pass
