"""Shuffle lineage registry: deterministic lost-partition recompute.

Reference: Spark's MapOutputTracker + stage-resubmission story, compressed
to the fragment level (SURVEY.md §5 names executor death as the one
failure the plugin delegates to Spark's task retry; a standalone engine
has to supply that recovery itself). Theseus (PAPERS.md) frames the same
requirement as treating executor loss as a data-movement event, not a
query abort.

Every published map output records its LINEAGE: the producing plan
fragment (a deterministic recompute closure over the exchange's child
partition stream), a digest of its input splits (the PR-10 fingerprint
machinery), and a content digest per published block. When a reduce-side
fetch exhausts failover — ``BlockMissingError`` with no serving peer, or
``PeerUnreachableError`` on a dead executor — the registry re-runs
exactly the lost map partition:

- the re-run rides the PR-7 ``with_retry`` state machine, so a recompute
  that lands on a memory-pressured host survives OOM like any task;
- partitioning is hash-deterministic and serialization is canonical, so
  the recomputed block is BIT-FOR-BIT the lost one — and the recorded
  content digest is verified to prove it (a nondeterministic fragment
  fails loudly instead of resuming with silently-different rows);
- recovered blocks are republished to the local transport so sibling
  reads (and peers) fetch them without recomputing again.

Replication (``spark.rapids.tpu.shuffle.replicas``) makes recompute the
FALLBACK rather than the only path: map outputs written to K peers at
publish time are served from a replica after the primary dies, and the
``replicaBytes``/``recomputeCount`` counters make the difference
observable in ``Session.metrics()`` and ``serving_stats()``.
"""

from __future__ import annotations

import hashlib
import threading
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional, Tuple

from .transport import BlockMissingError, PeerUnreachableError, TransportError


class LineageMissError(TransportError):
    """The lost block has no recorded lineage — nothing can recompute it
    (a foreign shuffle, lineage disabled, or the fragment was already
    cleaned up). The fetch failure that triggered recovery propagates as
    this error's ``__cause__``."""


class LineageVerificationError(TransportError):
    """The recomputed block does not match the content digest recorded at
    publish time — the producing fragment is NOT deterministic (or its
    inputs changed underneath it). Failing loudly here is the contract:
    recovery must resume bit-for-bit or not at all."""


class RecomputeCancelledError(RuntimeError):
    """The server cancelled the query (stop()/watchdog) while its
    recompute loop was running; the loop observed the flag and unwound."""


def _digest(payload: bytes) -> str:
    """Content digest of one serialized block (the PR-10 digest shape —
    blake2b-128, same as plancache.digest_ipc)."""
    return hashlib.blake2b(payload, digest_size=16).hexdigest()


# ---------------------------------------------------------------------------
# metrics (rolled into Session.metrics() as `lineage.*` deltas and into
# PlanServer.serving_stats(), like the retry/net counter groups)
# ---------------------------------------------------------------------------

class LineageMetrics:
    """Process-wide recovery counters; sessions report deltas."""

    def __init__(self):
        self._lock = threading.Lock()
        self.recompute_count = 0
        self.recomputed_partitions = 0     # monotonic distinct-block count
        self.replica_bytes = 0
        self.lineage_miss_count = 0
        #: distinct block ids currently deduping recomputedPartitions —
        #: purged per shuffle at cleanup (forget_shuffle), so a
        #: long-running serving process does not accumulate ids forever;
        #: the counter above stays monotonic for delta reporting
        self._recomputed_blocks = set()

    def note_recompute(self, block_id: Tuple[int, int, int]) -> None:
        with self._lock:
            self.recompute_count += 1
            if block_id not in self._recomputed_blocks:
                self._recomputed_blocks.add(block_id)
                self.recomputed_partitions += 1

    def forget_shuffle(self, shuffle_id: int) -> None:
        """Drop the dedup entries of one finished shuffle (its blocks
        can never be recomputed again — the lineage is gone too)."""
        with self._lock:
            self._recomputed_blocks = {
                b for b in self._recomputed_blocks if b[0] != shuffle_id}

    def note_replica(self, nbytes: int) -> None:
        with self._lock:
            self.replica_bytes += int(nbytes)

    def note_miss(self) -> None:
        with self._lock:
            self.lineage_miss_count += 1

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {
                "recomputeCount": self.recompute_count,
                "recomputedPartitions": self.recomputed_partitions,
                "replicaBytes": self.replica_bytes,
                "lineageMissCount": self.lineage_miss_count,
            }


_METRICS = LineageMetrics()


def metrics() -> LineageMetrics:
    return _METRICS


# ---------------------------------------------------------------------------
# query-cancellation plumbing (the plan server installs its cancel flag
# around collect; the recompute loop polls it between recoveries so
# stop()/watchdog cancellation lands instead of riding out the recovery)
# ---------------------------------------------------------------------------

_TLS = threading.local()


@contextmanager
def cancel_scope(cancelled: Callable[[], bool], exc: type = None):
    """Install ``cancelled`` as the calling thread's recompute-cancel
    hook; ``exc`` (default RecomputeCancelledError) is raised when it
    fires. The exchange read captures the hook via ``current_cancel()``
    on the query thread and carries it into the recovery pool."""
    prev = getattr(_TLS, "cancel", None)
    _TLS.cancel = (cancelled, exc or RecomputeCancelledError)
    try:
        yield
    finally:
        _TLS.cancel = prev


def current_cancel() -> Optional[Tuple[Callable[[], bool], type]]:
    """The (cancelled, exc) hook installed on THIS thread, if any."""
    return getattr(_TLS, "cancel", None)


def in_active_recovery() -> bool:
    """True while THIS thread is inside a recompute re-run — reads made
    by the re-executed fragment are nested recoveries and must not wait
    on the recover lock their outer recovery already holds."""
    return bool(getattr(_TLS, "in_recovery", False))


def _check_cancel(cancel) -> None:
    if cancel is not None and cancel[0]():
        raise cancel[1](
            "recompute cancelled by the server (stop()/watchdog)")


# ---------------------------------------------------------------------------
# the registry
# ---------------------------------------------------------------------------

class FragmentLineage:
    """Lineage of ONE map output: the producing fragment's recompute
    closure, its input-split digest, and the content digest of every
    block it published."""

    __slots__ = ("shuffle_id", "map_id", "recompute", "input_digest",
                 "blocks", "recovered")

    def __init__(self, shuffle_id: int, map_id: int,
                 recompute: Callable[..., Dict[int, Optional[bytes]]],
                 input_digest: str):
        self.shuffle_id = shuffle_id
        self.map_id = map_id
        #: recompute(reduce_ids) -> {reduce_id: serialized block bytes}
        #: for EVERY asked partition in one re-execution of the fragment
        #: — a dead peer usually loses a whole map output, and one
        #: child re-run must not be paid once per lost reducer
        self.recompute = recompute
        self.input_digest = input_digest
        #: reduce_id -> content digest recorded at publish time
        self.blocks: Dict[int, str] = {}
        #: verified sibling blocks stashed by a recovery run, served to
        #: later recover() calls without re-running the fragment
        self.recovered: Dict[int, bytes] = {}


class LineageRegistry:
    """Map-output lineage of every shuffle this process produced.

    Registration happens on the map side (one fragment per input batch,
    one block note per published piece); ``recover`` is the reduce-side
    entry point once transport failover is exhausted. Recoveries
    serialize on one lock — a recompute re-executes a plan fragment on
    the device, and racing several per lost host would multiply peak
    memory exactly when a failure already has the fleet degraded."""

    def __init__(self):
        self._lock = threading.Lock()
        self._recover_lock = threading.Lock()
        #: shuffle_id -> {map_id: FragmentLineage}: listings and cleanup
        #: touch ONLY their own shuffle — a flat (s, m)-keyed dict would
        #: make every read partition of every query scan every live
        #: fragment in the process under one lock (the serving tier
        #: holds many concurrent queries' fragments at once)
        self._shuffles: Dict[int, Dict[int, FragmentLineage]] = {}

    # ---- map side -----------------------------------------------------

    def register_shuffle(self, shuffle_id: int) -> None:
        """Mark a shuffle as lineage-tracked even before (or without)
        any fragment: a child that yields ZERO batches still needs
        ``knows_shuffle`` true, or an empty shuffle behind a dead peer
        would fail its listing instead of reading as provably empty."""
        with self._lock:
            self._shuffles.setdefault(shuffle_id, {})

    def register_fragment(self, shuffle_id: int, map_id: int,
                          recompute: Callable[..., Dict[int,
                                                        Optional[bytes]]],
                          input_digest: str) -> None:
        with self._lock:
            self._shuffles.setdefault(shuffle_id, {})[map_id] = \
                FragmentLineage(shuffle_id, map_id, recompute,
                                input_digest)

    def note_block(self, shuffle_id: int, map_id: int, reduce_id: int,
                   payload: bytes) -> None:
        """Record a published block's content digest (the bit-for-bit
        verification target for its eventual recompute)."""
        # hash OUTSIDE the lock: every shuffle writer thread of every
        # concurrent query funnels through here, and a multi-MB blake2b
        # under the registry lock would serialize them all on it
        digest = _digest(payload)
        with self._lock:
            ent = self._shuffles.get(shuffle_id, {}).get(map_id)
            if ent is not None:
                ent.blocks[reduce_id] = digest

    # ---- reduce side --------------------------------------------------

    def knows_shuffle(self, shuffle_id: int) -> bool:
        """True when this process registered lineage for the shuffle —
        the listing in ``blocks`` is then AUTHORITATIVE, including an
        empty one (a reducer no map output produced rows for), so the
        read side can survive a dead peer's failed listing outright."""
        with self._lock:
            return shuffle_id in self._shuffles

    def blocks(self, shuffle_id: int, reduce_id: int
               ) -> List[Tuple[int, int, int]]:
        """Every block lineage knows for one reducer — the AUTHORITATIVE
        listing the read side unions with the transport's: a dead peer
        excluded from live listing must surface its blocks here (and be
        recomputed), never silently drop their rows."""
        with self._lock:
            return sorted(
                (shuffle_id, m, reduce_id)
                for m, ent in self._shuffles.get(shuffle_id, {}).items()
                if reduce_id in ent.blocks)

    def recover(self, shuffle_id: int, map_id: int, reduce_id: int, *,
                catalog=None, cancel=None,
                cause: Optional[BaseException] = None,
                nested: Optional[bool] = None) -> bytes:
        """Deterministically recompute one lost block and verify it
        against the digest recorded at publish. Raises LineageMissError
        (chaining ``cause``) when the block has no lineage, and the
        cancel-scope exception when the server cancelled the query.

        Serialization vs deadlock: top-level recoveries take the
        recover lock (racing several fragment re-runs would multiply
        peak memory exactly when a failure has the fleet degraded), but
        a NESTED recovery — the recompute of shuffle B re-executes a
        child whose own exchange-A read needs recovery — must NOT wait
        on it: the outer recompute holds the lock while blocking on the
        inner fetch, a permanent circular wait. recover() marks its
        recompute's thread ``in_active_recovery``; the nested fetcher
        (created inside that re-execution) carries the flag to its pool
        threads via ``nested=`` and skips the lock. The lock acquire
        itself polls the cancel flag, so stop()/watchdog can always
        unwind a recovery stuck waiting its turn."""
        with self._lock:
            ent = self._shuffles.get(shuffle_id, {}).get(map_id)
            expect = ent.blocks.get(reduce_id) if ent is not None else None
        if expect is None:
            _METRICS.note_miss()
            raise LineageMissError(
                f"block s{shuffle_id}-m{map_id}-r{reduce_id} has no "
                f"recorded lineage — cannot recompute the lost "
                f"partition") from cause
        _check_cancel(cancel)
        if nested is None:
            nested = in_active_recovery()
        stashed = self._serve_stash(ent, reduce_id)
        if stashed is not None:
            return stashed
        if not nested:
            if cancel is None:
                # no cancel hook to poll — a plain blocking acquire
                # instead of a 20 Hz spin on an already-degraded host
                self._recover_lock.acquire()  # retry-ok: threading lock, not a catalog pin
            else:
                while not self._recover_lock.acquire(timeout=0.05):
                    _check_cancel(cancel)
        try:
            # the flag may have fired while this recovery waited behind
            # another — observe it before starting device work, and let
            # the retry loop observe it between OOM re-attempts too
            _check_cancel(cancel)
            # a racing recovery of a SIBLING block may have filled the
            # stash while this one waited its turn for the lock
            stashed = self._serve_stash(ent, reduce_id)
            if stashed is not None:
                return stashed
            from ..memory.retry import RetryCancelledError, \
                with_retry_no_split
            # ONE fragment re-run recovers every block this map output
            # published: a dead peer usually loses the whole output, and
            # re-executing the child once per lost reducer would
            # multiply recovery wall-time exactly when the fleet is
            # degraded — siblings are verified and stashed for the
            # other reducers' recover() calls
            from ..trace import span as _trace_span
            wanted = tuple(sorted(ent.blocks))
            prev = getattr(_TLS, "in_recovery", False)
            _TLS.in_recovery = True
            try:
                # the recompute span carries the originating query's id
                # through the active trace: a kill-mid-query recovery is
                # attributable to the collect that paid for it
                with _trace_span("lineage.recompute", kind="lineage",
                                 block=f"s{shuffle_id}-m{map_id}-"
                                       f"r{reduce_id}",
                                 fragment=ent.input_digest):
                    out = with_retry_no_split(
                        lambda: ent.recompute(wanted), catalog=catalog,
                        name=f"lineage.recompute(s{shuffle_id})",
                        cancelled=cancel[0] if cancel is not None
                        else None)
            except RetryCancelledError as ce:
                raise (cancel[1] if cancel is not None
                       else RecomputeCancelledError)(str(ce)) from ce
            finally:
                _TLS.in_recovery = prev
            out = out or {}
            for r_, digest in ent.blocks.items():
                got = out.get(r_)
                if got is None or _digest(got) != digest:
                    # the input digest NAMES the misbehaving recipe
                    # (schema sig + fragment coordinates) so the report
                    # identifies which plan fragment's re-run diverged
                    raise LineageVerificationError(
                        f"recomputed block s{shuffle_id}-m{map_id}-"
                        f"r{r_} (fragment {ent.input_digest}) does not "
                        f"match its publish-time digest — the producing "
                        f"fragment is not deterministic; refusing to "
                        f"resume with different rows") from cause
            with self._lock:
                ent.recovered.update(
                    (r_, b) for r_, b in out.items() if r_ != reduce_id)
        finally:
            if not nested:
                self._recover_lock.release()
        _METRICS.note_recompute((shuffle_id, map_id, reduce_id))
        return out[reduce_id]

    def _serve_stash(self, ent: FragmentLineage,
                     reduce_id: int) -> Optional[bytes]:
        """Pop an already-verified sibling block from a prior recovery
        run of the same fragment (or None when absent)."""
        with self._lock:
            got = ent.recovered.pop(reduce_id, None)
        if got is not None:
            _METRICS.note_recompute(
                (ent.shuffle_id, ent.map_id, reduce_id))
        return got

    # ---- lifecycle ----------------------------------------------------

    def remove_shuffle(self, shuffle_id: int) -> None:
        with self._lock:
            self._shuffles.pop(shuffle_id, None)
        # the metrics dedup set follows the lineage out: its blocks can
        # never recompute again, so keeping their ids would only leak
        _METRICS.forget_shuffle(shuffle_id)

    def __len__(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._shuffles.values())


_REGISTRY = LineageRegistry()


def lineage_registry() -> LineageRegistry:
    """The process-wide registry (the executor-singleton shape every
    other recovery layer uses); tests construct private instances."""
    return _REGISTRY


# ---------------------------------------------------------------------------
# recovering fetch (the reduce-side seam)
# ---------------------------------------------------------------------------

class _NeedsRecovery:
    """Sentinel a pool fetch task returns instead of BLOCKING in
    recovery: pool workers must never wait on the recover lock or run a
    recompute (whose re-executed child may submit work to the very same
    shared reader pool — all workers occupied by waiting recoveries is
    a process-wide deadlock). Recovery runs on the CONSUMING thread."""

    __slots__ = ("cause",)

    def __init__(self, cause: BaseException):
        self.cause = cause


def fetch_many_with_recovery(transport, ids, registry: LineageRegistry,
                             max_in_flight: int = 4, republish=None,
                             catalog=None, cancel=None):
    """``transport.fetch_many`` with per-block lineage recovery: a fetch
    that exhausts failover (missing everywhere, or the serving peer is
    dead) recomputes the block instead of raising, republishes it via
    ``republish`` (normally the reading transport's local store, so
    sibling reads and peers are served without recomputing again), and
    resumes the pipelined read bit-for-bit. Yields (block_id, bytes) in
    input order, like fetch_many.

    Threading: pool tasks only FETCH (bounded by the transport's
    deadlines); every recovery runs on the consuming thread, in yield
    order — so neither the recover lock's wait nor the recompute itself
    can tie up shared pool workers, and a read nested inside another
    recompute fetches serially instead of competing for the pool."""
    # evaluated on the CONSUMING thread at first next(): when this read
    # runs inside a recompute re-run (nested recovery), its recoveries
    # skip the recover lock the outer recovery already holds
    nested = in_active_recovery()
    # pool fetch tasks inherit the consuming thread's trace context so
    # their per-peer fetch spans carry the originating query_id (None —
    # and free — when tracing is off)
    from ..trace import attached, capture
    tok = capture()

    def fetch_one(b):
        with attached(tok):
            try:
                return transport.fetch(*b)
            except (BlockMissingError, PeerUnreachableError) as ex:
                return _NeedsRecovery(ex)

    def stream():
        if nested:
            for b in list(ids):
                yield b, fetch_one(b)
            return
        from ..io.source import bounded_map, reader_pool
        pool = reader_pool(max(2, max_in_flight))
        yield from bounded_map(pool, list(ids), fetch_one, max_in_flight,
                               force_parallel=True)

    for b, got in stream():
        if isinstance(got, _NeedsRecovery):
            got = registry.recover(*b, catalog=catalog, cancel=cancel,
                                   cause=got.cause, nested=nested)
            if republish is not None:
                republish(*b, got)
        yield b, got
