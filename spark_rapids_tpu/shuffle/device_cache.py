"""Device-resident cross-process shuffle cache.

Reference: RapidsShuffleInternalManagerBase.scala:876 (RapidsCachingWriter)
+ ShuffleBufferCatalog.scala + RapidsShuffleTransport.scala:303 — the UCX
"cached" mode: map outputs STAY in device memory as spillable catalog
entries; peers pull them through the transport; nothing touches the
shared filesystem unless memory pressure spills it.

TPU shape: each block is a ``SpillableBatch`` riding the tiered memory
catalog (DEVICE→HOST→DISK under pressure), registered LAZILY with the
TCP transport — serialization (D2H + framed codec) happens only when a
peer actually fetches the block. Local reads hand back the device batch
with zero serialization. Peer liveness comes from the plugin heartbeat
registry through the transport's ``liveness`` hook (the reference's
RapidsShuffleHeartbeatManager feeding endpoint setup).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

from ..batch import ColumnarBatch, Schema
from .serializer import deserialize_batch, serialize_batch
from .transport import ShuffleTransport, TransportError


class DeviceShuffleCache:
    """ShuffleBufferCatalog analogue over the spill catalog + transport."""

    def __init__(self, transport, catalog=None, codec=None):
        from ..memory import device_budget
        self.transport = transport
        self.catalog = catalog or device_budget()
        #: serialization codec for P2P serves (session shuffle codec)
        self.codec = codec
        self._blocks: Dict[Tuple[int, int, int], tuple] = {}
        self._lock = threading.Lock()
        transport.resolver = self._serve

    # ---- writer side (RapidsCachingWriter.write) ----
    def add_batch(self, shuffle_id: int, map_id: int, reduce_id: int,
                  batch: ColumnarBatch, schema: Schema) -> None:
        from ..memory import register_with_retry
        sb = register_with_retry(batch, schema, catalog=self.catalog,
                                 name="device_cache")
        with self._lock:
            self._blocks[(shuffle_id, map_id, reduce_id)] = (sb, schema)
        self.transport.publish_lazy(shuffle_id, map_id, reduce_id)

    # ---- local reader: zero-serialization device handoff ----
    def get_local(self, shuffle_id: int, map_id: int,
                  reduce_id: int) -> Optional[ColumnarBatch]:
        with self._lock:
            ent = self._blocks.get((shuffle_id, map_id, reduce_id))
        if ent is None:
            return None
        sb, _ = ent
        from ..memory import acquire_with_retry
        out = acquire_with_retry(sb, name="device_cache")
        sb.done_with()
        return out

    # ---- transport resolver: serialize ON DEMAND for remote fetches ----
    def _serve(self, shuffle_id: int, map_id: int,
               reduce_id: int) -> Optional[bytes]:
        with self._lock:
            ent = self._blocks.get((shuffle_id, map_id, reduce_id))
        if ent is None:
            return None
        sb, schema = ent
        from ..memory import acquire_with_retry
        batch = acquire_with_retry(sb, name="device_cache")
        try:
            return serialize_batch(batch, schema, self.codec)
        finally:
            sb.done_with()

    # ---- remote reader ----
    def fetch(self, shuffle_id: int, map_id: int, reduce_id: int,
              schema: Schema) -> ColumnarBatch:
        """Local catalog hit or a transport pull from whichever LIVE peer
        owns the block; the deserialized batch lands on THIS device.
        A fetch that exhausts failover (missing everywhere / dead peer)
        falls through to lineage recompute when the shuffle is
        lineage-TRACKED in this process; otherwise (the CACHED mode's
        device-resident blocks register no recompute recipe, or lineage
        is disabled) the typed transport error propagates unchanged —
        re-typing it as a lineage miss would charge the lineageMissCount
        metric for a feature that was never in play."""
        from .transport import BlockMissingError, PeerUnreachableError
        local = self.get_local(shuffle_id, map_id, reduce_id)
        if local is not None:
            return local
        try:
            data = self.transport.fetch(shuffle_id, map_id, reduce_id)
        except (BlockMissingError, PeerUnreachableError) as ex:
            from .lineage import current_cancel, lineage_registry
            reg = lineage_registry()
            if not reg.knows_shuffle(shuffle_id):
                raise
            data = reg.recover(
                shuffle_id, map_id, reduce_id, catalog=self.catalog,
                cancel=current_cancel(), cause=ex)
            self.transport.publish(shuffle_id, map_id, reduce_id, data)
        return deserialize_batch(data, schema)

    def remove_shuffle(self, shuffle_id: int) -> None:
        with self._lock:
            gone = [k for k in self._blocks if k[0] == shuffle_id]
            for k in gone:
                sb, _ = self._blocks.pop(k)
                sb.close()
        self.transport.remove_shuffle(shuffle_id)

    def close(self) -> None:
        with self._lock:
            for sb, _ in self._blocks.values():
                sb.close()
            self._blocks.clear()


_SHARED = None
_SHARED_LOCK = threading.Lock()


def shared_device_cache(conf=None) -> DeviceShuffleCache:
    """Process-wide cache over a lazily started TCP transport. With
    spark.rapids.tpu.shuffle.cached.registry set, the transport's peer
    table is DISCOVERED through the driver registry (heartbeat-driven —
    reference: RapidsShuffleHeartbeatManager feeding UCX endpoints);
    otherwise peers must be injected explicitly (tests/single-process)."""
    global _SHARED
    with _SHARED_LOCK:
        if _SHARED is None:
            from .transport import TcpTransport
            registry_conf = ""
            codec = None
            if conf is not None:
                from ..config import CACHED_REGISTRY, SHUFFLE_COMPRESSION
                registry_conf = str(conf.get(CACHED_REGISTRY.key) or "")
                codec = str(conf.get(SHUFFLE_COMPRESSION.key))
            # cross-host peers must be able to reach the block server:
            # bind wide when discovery is configured, loopback otherwise
            window = None
            retries = 3
            connect_ms, io_ms = 30000, 30000
            backoff_ms, backoff_max_ms = 10, 1000
            if conf is not None:
                from ..config import (TRANSPORT_BACKOFF_MAX_MS,
                                      TRANSPORT_BACKOFF_MS,
                                      TRANSPORT_CONNECT_TIMEOUT_MS,
                                      TRANSPORT_IO_TIMEOUT_MS,
                                      TRANSPORT_RETRIES,
                                      TRANSPORT_WINDOW_BYTES)
                window = int(conf.get(TRANSPORT_WINDOW_BYTES.key))
                retries = int(conf.get(TRANSPORT_RETRIES.key))
                connect_ms = int(conf.get(TRANSPORT_CONNECT_TIMEOUT_MS.key))
                io_ms = int(conf.get(TRANSPORT_IO_TIMEOUT_MS.key))
                backoff_ms = int(conf.get(TRANSPORT_BACKOFF_MS.key))
                backoff_max_ms = int(conf.get(TRANSPORT_BACKOFF_MAX_MS.key))
            from .transport import DEFAULT_WINDOW_BYTES
            transport = TcpTransport(
                host="0.0.0.0" if registry_conf else "127.0.0.1",
                retries=retries,
                window_bytes=window or DEFAULT_WINDOW_BYTES,
                connect_timeout_s=connect_ms / 1000.0,
                io_timeout_s=io_ms / 1000.0 if io_ms else None,
                backoff_base_ms=backoff_ms,
                backoff_max_ms=backoff_max_ms)
            # report unreachable peers to the heartbeat registry so their
            # blocks stop being listed as live (reference: transport
            # errors feeding RapidsShuffleHeartbeatManager). liveness is
            # deliberately NOT wired here: in registry mode the
            # RegistryClient's live_table is the peer-liveness authority
            # (remote executors heartbeat the DRIVER registry, not this
            # process), and the local heartbeat table would veto every
            # remote peer; in-transport suspect ordering covers fetch
            # failover either way.
            from ..plugin import ExecutorRuntime
            runtime = ExecutorRuntime._instance
            if runtime is not None:
                transport.on_unreachable = runtime.mark_unreachable
            if conf is not None:
                from ..config import (CACHED_HEARTBEAT_INTERVAL_MS,
                                      EXECUTOR_ID)
                reg = registry_conf
                if reg:
                    from .discovery import RegistryClient
                    host, _, port = reg.rpartition(":")
                    client = RegistryClient(
                        (host, int(port)),
                        int(conf.get(EXECUTOR_ID.key)),
                        (socket_host(), transport.address[1]),
                        heartbeat_interval_s=conf.get(
                            CACHED_HEARTBEAT_INTERVAL_MS.key) / 1000.0)
                    transport.peer_source = client.peers
                    transport._registry_client = client
                    # unreachable verdicts fan out to the DRIVER registry
                    # too (suspect→dead promotion is cluster-wide): every
                    # executor's listing drops the dead peer, and only a
                    # fresh register handshake brings it back
                    local_report = transport.on_unreachable

                    def report(peer_id, _local=local_report,
                               _client=client):
                        if _local is not None:
                            _local(peer_id)
                        _client.report_unreachable(peer_id)

                    transport.on_unreachable = report
            _SHARED = DeviceShuffleCache(transport, codec=codec)
        return _SHARED


def socket_host() -> str:
    """Address peers can reach this host on (hostname IP, loopback as
    the single-machine fallback)."""
    import socket as _s
    try:
        return _s.gethostbyname(_s.gethostname())
    except OSError:  # net-ok: no resolvable hostname — loopback fallback
        return "127.0.0.1"
