"""Heartbeat-registry peer discovery for the CACHED (device-resident)
shuffle across REAL multi-host deployments.

Reference: RapidsShuffleHeartbeatManager.scala:49,186 — executors
heartbeat the driver and receive the full executor table, which feeds
UCXShuffleTransport endpoint setup (UCXShuffleTransport.scala:47). Same
shape here: a tiny driver-side TCP registry; executors REGISTER their
block-server address, heartbeat on the conf interval, and LIST the live
peer table, which the TcpTransport consumes as its dynamic peer source.

Wire format: one JSON object per line over a short-lived connection
(REGISTER / HEARTBEAT / LIST) — the registry is control-plane only; block
bytes never pass through it.
"""

from __future__ import annotations

import json
import socket
import socketserver
import threading
import time
from typing import Dict, Optional, Tuple


class _RegistryHandler(socketserver.StreamRequestHandler):
    def handle(self):
        try:
            line = self.rfile.readline()
            if not line:
                return
            msg = json.loads(line)
            reg = self.server.registry       # type: ignore
            op = msg.get("op")
            if op in ("register", "heartbeat"):
                reg._stamp(msg["id"], msg.get("host"), msg.get("port"))
                self.wfile.write(b'{"ok": true}\n')
            elif op == "list":
                self.wfile.write(
                    (json.dumps(reg.live_table()) + "\n").encode())
            else:
                self.wfile.write(b'{"error": "bad op"}\n')
        except (OSError, ValueError, KeyError):
            # net-ok: registry handler; a malformed/broken control-plane
            # request tears down its own short-lived connection
            pass


class _RegistryServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class PeerRegistry:
    """Driver-side executor table: id -> (host, port, last_seen)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 timeout_s: float = 30.0):
        self.timeout_s = timeout_s
        self._table: Dict[int, Tuple[str, int, float]] = {}
        self._lock = threading.Lock()
        self._server = _RegistryServer((host, port), _RegistryHandler)
        self._server.registry = self         # type: ignore
        self.address = self._server.server_address
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="peer-registry")
        self._thread.start()

    def _stamp(self, exec_id: int, host: Optional[str],
               port: Optional[int]) -> None:
        with self._lock:
            prev = self._table.get(exec_id)
            if host is None or port is None:
                if prev is None:
                    return
                host, port = prev[0], prev[1]
            self._table[exec_id] = (host, int(port), time.time())

    def live_table(self) -> Dict[str, Tuple[str, int]]:
        now = time.time()
        with self._lock:
            return {str(i): (h, p)
                    for i, (h, p, t) in self._table.items()
                    if now - t <= self.timeout_s}

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()


class RegistryClient:
    """Executor-side: register the local block server, heartbeat on an
    interval, and expose the live peer table (minus self) as the
    TcpTransport's dynamic peer source."""

    def __init__(self, registry_addr: Tuple[str, int], exec_id: int,
                 block_addr: Tuple[str, int],
                 heartbeat_interval_s: float = 5.0):
        self.registry_addr = registry_addr
        self.exec_id = exec_id
        self.block_addr = block_addr
        self._stop = threading.Event()
        self._rpc({"op": "register", "id": exec_id,
                   "host": block_addr[0], "port": block_addr[1]})
        self._thread = threading.Thread(
            target=self._beat, args=(heartbeat_interval_s,), daemon=True,
            name=f"registry-heartbeat-{exec_id}")
        self._thread.start()

    def _rpc(self, msg: dict) -> dict:
        with socket.create_connection(self.registry_addr, timeout=10) as s:
            s.sendall((json.dumps(msg) + "\n").encode())
            data = s.makefile().readline()
        return json.loads(data) if data else {}

    def _beat(self, interval_s: float) -> None:
        while not self._stop.wait(interval_s):
            try:
                self._rpc({"op": "heartbeat", "id": self.exec_id})
            except OSError:  # net-ok: registry down — peers see us expire
                pass

    def peers(self) -> Dict[int, Tuple[str, int]]:
        """Live peer table EXCLUDING self — TcpTransport peer_source."""
        try:
            table = self._rpc({"op": "list"})
        except OSError:
            # net-ok: registry unreachable — an empty peer table falls
            # back to the static table (transport merges over it)
            return {}
        return {int(i): (h, p) for i, (h, p) in table.items()
                if int(i) != self.exec_id}

    def close(self) -> None:
        self._stop.set()
