"""Heartbeat-registry peer discovery for the CACHED (device-resident)
shuffle across REAL multi-host deployments.

Reference: RapidsShuffleHeartbeatManager.scala:49,186 — executors
heartbeat the driver and receive the full executor table, which feeds
UCXShuffleTransport endpoint setup (UCXShuffleTransport.scala:47). Same
shape here: a tiny driver-side TCP registry; executors REGISTER their
block-server address, heartbeat on the conf interval, and LIST the live
peer table, which the TcpTransport consumes as its dynamic peer source.

Wire format: one JSON object per line over a short-lived connection
(REGISTER / HEARTBEAT / LIST) — the registry is control-plane only; block
bytes never pass through it.
"""

from __future__ import annotations

import json
import socket
import socketserver
import threading
import time
from typing import Dict, Optional, Tuple


class _RegistryHandler(socketserver.StreamRequestHandler):
    def handle(self):
        try:
            line = self.rfile.readline()
            if not line:
                return
            msg = json.loads(line)
            reg = self.server.registry       # type: ignore
            op = msg.get("op")
            if op == "register":
                reg.register_peer(msg["id"], msg.get("host"),
                                  msg.get("port"))
                self.wfile.write(b'{"ok": true}\n')
            elif op == "heartbeat":
                status = reg.heartbeat_peer(msg["id"], msg.get("host"),
                                            msg.get("port"))
                # a heartbeat from an executor this registry declared
                # DEAD is refused (not stamped): resurrection requires
                # the explicit re-register handshake, and the reply
                # tells the sender so it can perform it. UNKNOWN covers
                # a registry that lost its table (restart): the sender
                # believes it is heartbeating but nothing is stamped —
                # it too must re-register (with its address).
                self.wfile.write(
                    b'{"ok": true}\n' if status == "ok" else
                    b'{"ok": false, "dead": true}\n'
                    if status == "dead" else
                    b'{"ok": false, "unknown": true}\n')
            elif op == "unreachable":
                reg.mark_unreachable(msg["id"])
                self.wfile.write(b'{"ok": true}\n')
            elif op == "list":
                self.wfile.write(
                    (json.dumps(reg.live_table()) + "\n").encode())
            else:
                self.wfile.write(b'{"error": "bad op"}\n')
        except (OSError, ValueError, KeyError):
            # net-ok: registry handler; a malformed/broken control-plane
            # request tears down its own short-lived connection
            pass


class _RegistryServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class PeerRegistry:
    """Driver-side executor table: id -> (host, port, last_seen).

    Death is PROMOTED state, not just staleness: an executor a transport
    reported unreachable (``mark_unreachable``) leaves the live table
    AND lands in the dead set — a stray late heartbeat from it is
    refused, because its block server already proved unreachable and
    resurrecting it on a one-line ping would put a half-dead peer back
    into every reader's fetch ordering. Rehabilitation requires the
    explicit ``register`` handshake (the executor restating its block
    server address), after which it returns to normal ordering."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 timeout_s: float = 30.0):
        self.timeout_s = timeout_s
        self._table: Dict[int, Tuple[str, int, float]] = {}
        self._dead: set = set()
        self._lock = threading.Lock()
        self._server = _RegistryServer((host, port), _RegistryHandler)
        self._server.registry = self         # type: ignore
        self.address = self._server.server_address
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="peer-registry")
        self._thread.start()

    def _stamp_locked(self, exec_id: int, host: Optional[str],
                      port: Optional[int]) -> bool:
        """Insert/update one liveness entry; caller holds self._lock.
        Returns False when nothing was stamped (address-less ping for an
        executor this registry has no entry for — e.g. after a restart
        emptied the table); the caller must surface that, or the sender
        keeps heartbeating into the void while excluded from every
        listing."""
        prev = self._table.get(exec_id)
        if host is None or port is None:
            if prev is None:
                return False
            host, port = prev[0], prev[1]
        self._table[exec_id] = (host, int(port), time.time())
        return True

    def register_peer(self, exec_id: int, host: Optional[str],
                      port: Optional[int]) -> None:
        """The explicit liveness handshake — clears promoted-dead state
        and stamps in ONE atomic step under the lock."""
        with self._lock:
            self._dead.discard(str(exec_id))
            self._stamp_locked(exec_id, host, port)

    def heartbeat_peer(self, exec_id: int, host: Optional[str] = None,
                       port: Optional[int] = None) -> str:
        """Stamp liveness and return "ok"; "dead" (refused — promoted
        dead, must re-register) or "unknown" (nothing stamped: an
        address-less ping for an executor this registry has no entry
        for, i.e. the table was lost — must re-register with its
        address). The dead check and the stamp happen under ONE lock
        hold: a concurrent `unreachable` report between them must not
        be undone by a heartbeat that already passed the check (that
        would re-insert the half-dead peer into live_table for up to
        timeout_s)."""
        with self._lock:
            if str(exec_id) in self._dead:
                return "dead"
            if not self._stamp_locked(exec_id, host, port):
                return "unknown"
        return "ok"

    def mark_unreachable(self, exec_id) -> None:
        """Suspect→dead promotion: a transport's fetch retry budget was
        exhausted against this executor's block server."""
        with self._lock:
            self._dead.add(str(exec_id))
            for k in [k for k in self._table if str(k) == str(exec_id)]:
                del self._table[k]

    def live_table(self) -> Dict[str, Tuple[str, int]]:
        now = time.time()
        with self._lock:
            return {str(i): (h, p)
                    for i, (h, p, t) in self._table.items()
                    if now - t <= self.timeout_s}

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()


class RegistryClient:
    """Executor-side: register the local block server, heartbeat on an
    interval, and expose the live peer table (minus self) as the
    TcpTransport's dynamic peer source."""

    def __init__(self, registry_addr: Tuple[str, int], exec_id: int,
                 block_addr: Tuple[str, int],
                 heartbeat_interval_s: float = 5.0):
        self.registry_addr = registry_addr
        self.exec_id = exec_id
        self.block_addr = block_addr
        self._stop = threading.Event()
        self._rpc({"op": "register", "id": exec_id,
                   "host": block_addr[0], "port": block_addr[1]})
        self._thread = threading.Thread(
            target=self._beat, args=(heartbeat_interval_s,), daemon=True,
            name=f"registry-heartbeat-{exec_id}")
        self._thread.start()

    def _rpc(self, msg: dict) -> dict:
        with socket.create_connection(self.registry_addr, timeout=10) as s:
            s.sendall((json.dumps(msg) + "\n").encode())
            data = s.makefile().readline()
        return json.loads(data) if data else {}

    def _beat(self, interval_s: float) -> None:
        # re-registers back off exponentially while refusals recur: a
        # HALF-dead executor (beat loop alive, block server wedged)
        # must not undo its dead promotion every interval and re-tax
        # every reader's fetch ordering; a healthy stretch resets it
        rereg_backoff = interval_s
        last_rereg = time.time()
        healthy = 0
        while not self._stop.wait(interval_s):
            try:
                resp = self._rpc({"op": "heartbeat", "id": self.exec_id})
                if resp.get("dead") or resp.get("unknown"):
                    # dead: the registry promoted us dead (a peer's
                    # transport reported our block server unreachable,
                    # e.g. a transient partition) — a bare heartbeat can
                    # NEVER resurrect us. unknown: the registry lost its
                    # table (restart) and our address-less ping stamps
                    # nothing. Both rehabilitate the same way: the
                    # explicit re-register handshake restating our
                    # address.
                    healthy = 0
                    now = time.time()
                    if now - last_rereg >= rereg_backoff:
                        self.reregister()
                        last_rereg = now
                        rereg_backoff = min(rereg_backoff * 2,
                                            max(60.0, interval_s))
                else:
                    healthy += 1
                    if healthy >= 10:
                        rereg_backoff = interval_s
            except (OSError, ValueError):
                # net-ok: registry down — peers see us expire
                pass

    def reregister(self) -> None:
        """Fresh register handshake (rehabilitation after a dead
        promotion, or a registry restart that lost the table)."""
        self._rpc({"op": "register", "id": self.exec_id,
                   "host": self.block_addr[0],
                   "port": self.block_addr[1]})

    def report_unreachable(self, peer_id) -> None:
        """Transport hook: tell the driver registry a peer's block
        server proved unreachable, so every executor's listing excludes
        it (suspect→dead promotion is cluster-wide, not just local)."""
        try:
            self._rpc({"op": "unreachable", "id": peer_id})
        except (OSError, ValueError):
            # net-ok: best-effort death report — the local suspect
            # ordering still covers this transport's own fetches
            pass

    def peers(self) -> Dict[int, Tuple[str, int]]:
        """Live peer table EXCLUDING self — TcpTransport peer_source."""
        try:
            table = self._rpc({"op": "list"})
        except OSError:
            # net-ok: registry unreachable — an empty peer table falls
            # back to the static table (transport merges over it)
            return {}
        return {int(i): (h, p) for i, (h, p) in table.items()
                if int(i) != self.exec_id}

    def close(self) -> None:
        self._stop.set()
