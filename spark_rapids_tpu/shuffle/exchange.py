"""Shuffle and broadcast exchanges.

Reference: GpuShuffleExchangeExecBase.scala:152,262 (prepareBatchShuffleDependency:
partition-id eval → device slicing → serialized blocks),
GpuBroadcastExchangeExec.scala:319. This module is the DEFAULT/host-mediated
shuffle mode (SURVEY.md §2.10): per input batch, rows are sliced per target
partition ON DEVICE (one fused kernel computing partition ids + cumsum
compaction per target), and re-coalesced on the read side. The ICI
device-collective mode lives in parallel/mesh.py; both sit behind the same
exec surface the way the reference's three shuffle modes sit behind one
shuffle manager.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Tuple

import itertools
import threading

import jax
import jax.numpy as jnp

from ..batch import ColumnarBatch, Schema, bucket_capacity
from ..exec.base import Exec, UnaryExec
from ..exec.common import compact, concat_batches, slice_batch
from ..expressions.base import EvalContext
from ..memory.catalog import BufferCatalog, SpillableBatch
from .partitioning import Partitioning, RangePartitioning, SinglePartitioning

#: One reader partition = a list of (map-output partition, piece_lo, piece_hi)
#: piece ranges. This is the TPU analogue of Spark AQE's partition specs
#: (CoalescedPartitionSpec spans whole output partitions,
#: PartialReducerPartitionSpec takes a slice of one skewed partition).
ReadSpec = List[Tuple[int, int, int]]


def _coalesce_groups(counts: List[int], target_rows: int) -> List[List[int]]:
    """Greedy adjacent grouping of partitions so each group approaches
    target_rows (AQE coalesce-partitions)."""
    groups: List[List[int]] = []
    cur: List[int] = []
    cur_rows = 0
    for p, c in enumerate(counts):
        if cur and cur_rows + c > target_rows:
            groups.append(cur)
            cur, cur_rows = [], 0
        cur.append(p)
        cur_rows += c
    if cur:
        groups.append(cur)
    return groups or [[0]]


class ShuffleExchangeExec(UnaryExec):
    """All-to-all redistribution of rows by a partitioning.

    Spill discipline (reference: RapidsShuffleIterator/ShuffleBufferCatalog):
    every materialized partition piece is SHRUNK to its row-count bucket and
    registered with the buffer catalog, so a shuffle larger than the device
    budget spills to host/disk instead of accumulating unbudgeted device
    lists; pieces are acquired per read partition and freed after that
    partition is consumed.
    """

    @property
    def produces_single_batch(self):
        return True

    def __init__(self, partitioning: Partitioning, child: Exec,
                 ctx: Optional[EvalContext] = None, adaptive: bool = False,
                 target_rows: int = 1 << 20,
                 catalog: Optional[BufferCatalog] = None):
        super().__init__(child, ctx)
        self.partitioning = partitioning.bind(child.output_schema)
        self._materialized: Optional[
            List[List[Tuple[SpillableBatch, int]]]] = None
        # AQE (reference: GpuCustomShuffleReaderExec): after the stage
        # materializes, adjacent small output partitions coalesce into one
        # reader partition using real row counts.
        self.adaptive = adaptive
        self.target_rows = target_rows
        self._specs: Optional[List[ReadSpec]] = None
        self._use_left: Optional[Dict[Tuple[int, int], int]] = None
        self._catalog = catalog

        def slice_kernel(batch: ColumnarBatch, pids, p: int) -> ColumnarBatch:
            return compact(batch, pids == p)

        self._slice_jit = jax.jit(slice_kernel, static_argnums=2)
        self._shrink_jit = jax.jit(
            lambda b, cap: slice_batch(b, 0, b.num_rows, cap),
            static_argnums=1)
        self._pids_jit = jax.jit(
            lambda b: self.partitioning.partition_ids(b, self.ctx))
        from ..exec.base import DEBUG, MODERATE, Metric
        # wire-path visibility: serializeTime = framing/compression,
        # overlapTime = D2H staging hidden behind it (pipeline.py)
        self.metrics["serializeTime"] = Metric("serializeTime", MODERATE)
        self.metrics["overlapTime"] = Metric("overlapTime", MODERATE)
        self.metrics["prefetchWaitTime"] = Metric("prefetchWaitTime", DEBUG)

    def _cat(self) -> BufferCatalog:
        if self._catalog is None:
            from ..memory.catalog import device_budget
            self._catalog = device_budget()
        return self._catalog

    @property
    def output_schema(self) -> Schema:
        return self.child.output_schema

    @property
    def num_partitions(self) -> int:
        if self._specs is not None:
            return len(self._specs)
        if self.adaptive:
            return len(self._reader_specs())
        return self.partitioning.num_partitions

    def partition_row_counts(self) -> List[int]:
        """Materialized row count per map-output partition (the stage
        statistics AQE reader planning runs on)."""
        return [sum(rows for _, rows in pieces)
                for pieces in self._materialize()]

    def piece_row_counts(self, p: int) -> List[int]:
        return [rows for _, rows in self._materialize()[p]]

    def set_reader_specs(self, specs: List[ReadSpec]) -> None:
        """Fix the reader-side partition layout. Called either internally
        (solo adaptive coalesce) or by a join coordinating BOTH of its
        exchanges onto one layout (coordinate_join_reads below). Pieces
        referenced by several specs (skew-split build replication) are
        refcounted and freed after their last read."""
        self._materialize()
        use: Dict[Tuple[int, int], int] = {}
        for spec in specs:
            for op_, lo, hi in spec:
                for i in range(lo, hi):
                    use[(op_, i)] = use.get((op_, i), 0) + 1
        self._specs = specs
        self._use_left = use

    def _reader_specs(self) -> List[ReadSpec]:
        if self._specs is None:
            parts = self._materialize()
            if self.adaptive:
                counts = [sum(rows for _, rows in pieces) for pieces in parts]
                groups = _coalesce_groups(counts, self.target_rows)
                if len(groups) < len(parts):
                    from ..plan.adaptive import record_decision
                    record_decision(
                        "coalesce",
                        f"solo exchange: {len(parts)} materialized "
                        f"partitions -> {len(groups)} reader partitions "
                        f"(targetRows={self.target_rows})",
                        n=len(parts) - len(groups))
            else:
                groups = [[p] for p in range(len(parts))]
            self.set_reader_specs(
                [[(p, 0, len(parts[p])) for p in g] for g in groups])
        return self._specs

    def _sample_range_bounds(self, batches: List[ColumnarBatch]) -> None:
        """Compute range bounds from the materialized input (reference:
        GpuRangePartitioner.sketch/determineBounds)."""
        from ..exec.common import sort_operands, gather_column
        part: RangePartitioning = self.partitioning
        n = self.partitioning.num_partitions
        # concat all key columns, sort, take n-1 evenly spaced bound rows
        key_batches = []
        counts = []
        for b in batches:
            cols = part.key_columns(b, self.ctx)
            key_batches.append(ColumnarBatch(tuple(cols), b.num_rows))
            counts.append(b.num_rows)
        cap = bucket_capacity(sum(kb.capacity for kb in key_batches))
        allk = concat_batches(key_batches, cap)

        def bounds_kernel(kb: ColumnarBatch):
            live = kb.row_mask()
            ops = sort_operands(
                list(kb.columns), part._descending, part._nulls_first, live)
            iota = jnp.arange(kb.capacity, dtype=jnp.int32)
            perm = jax.lax.sort(ops + [iota], num_keys=len(ops) + 1)[-1]
            skeys = [gather_column(c, perm) for c in kb.columns]
            total = kb.num_rows
            # bound i sits at row (i+1)*total/n
            pos = ((jnp.arange(n - 1, dtype=jnp.int64) + 1) * total) // n
            pos = jnp.clip(pos, 0, kb.capacity - 1).astype(jnp.int32)
            return [gather_column(c, pos) for c in skeys]

        bound_cols = jax.jit(bounds_kernel)(allk)
        part.set_bounds(bound_cols, n - 1)

    def _register(self, staged, p: int, piece: ColumnarBatch) -> None:
        """Shrink a partition piece to its row-count bucket and hand it to
        the spill catalog (padding at full input capacity would multiply
        device residency by the partition count). Appends to ``staged``
        so a failed write attempt can free its partial pieces before the
        retry loop re-runs it."""
        rows = int(piece.num_rows)
        if rows == 0:
            return
        cap = bucket_capacity(rows)
        if cap < piece.capacity:
            piece = self._shrink_jit(piece, cap)
        # registration leaves the entry unpinned → spillable under pressure
        sb = SpillableBatch(self._cat(), piece,
                            self.output_schema)  # retry-ok: only write_body (runs under with_retry) calls _register
        staged.append((p, sb, rows))

    def _materialize(self) -> List[List[Tuple[SpillableBatch, int]]]:
        if self._materialized is not None:
            return self._materialized
        n = self.partitioning.num_partitions   # write-side nominal count
        out: List[List[Tuple[SpillableBatch, int]]] = [[] for _ in range(n)]
        range_part = isinstance(self.partitioning, RangePartitioning)
        if range_part:
            # bounds need the whole input; sampling keeps only key columns
            batches = [b for cp in range(self.child.num_partitions)
                       for b in self.child.execute_partition(cp)]
            if batches:
                self._sample_range_bounds(batches)
            stream = iter(batches)
        else:
            # STREAM the child: one input batch on device at a time; its
            # pieces go straight into the catalog
            stream = (b for cp in range(self.child.num_partitions)
                      for b in self.child.execute_partition(cp))
        cat = self._cat()
        spill0 = cat.spilled_to_host + cat.spilled_to_disk
        from ..memory.retry import (SpillableInput, split_input_halves,
                                    with_retry)
        from ..utils import tracing
        in_schema = self.child.output_schema

        def write_body(item: SpillableInput):
            """One write attempt over one (possibly split) input: slice
            per target partition and register the pieces. Transactional —
            an OOM mid-loop frees this attempt's pieces so the retry (or
            the half-inputs after a split) starts clean."""
            b = item.acquire()
            staged: List[Tuple[int, SpillableBatch, int]] = []
            try:
                if n == 1:
                    self._register(staged, 0, b)
                else:
                    pids = self._pids_jit(b)
                    for p in range(n):
                        self._register(staged, p,
                                       self._slice_jit(b, pids, p))
            except BaseException:
                for _p, sb, _r in staged:
                    sb.close()
                raise
            finally:
                item.release()
            return staged

        try:
            for batch in stream:
                with tracing.op_range(f"{self.name}.write"):
                    # the input batch rides the catalog across retry
                    # boundaries (SpillableColumnarBatch discipline); a
                    # repeated OOM halves it — half-inputs slice to the
                    # same pieces in the same order, so reads stay
                    # bit-for-bit
                    inp = SpillableInput.admit(batch, in_schema, cat,
                                               name=f"{self.name}.admit")
                    for staged in with_retry(inp, write_body,
                                             split=split_input_halves,
                                             catalog=cat, name=self.name):
                        for p, sb, rows in staged:
                            out[p].append((sb, rows))
        except BaseException:
            # a mid-stream failure (final OOM on a later batch, child
            # error) must free the pieces earlier batches already staged:
            # self._materialized is still None here, so do_close would
            # never see them
            for part in out:
                for sb, _rows in part:
                    sb.close()
            raise
        from ..exec.base import DEBUG, Metric
        self.metrics.setdefault(
            "spillBytes", Metric("spillBytes", DEBUG)).add(
            cat.spilled_to_host + cat.spilled_to_disk - spill0)
        self._materialized = out
        return out

    def do_execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        spec = self._reader_specs()[p]
        parts = self._materialize()
        entries = [parts[op_][i] for op_, lo, hi in spec
                   for i in range(lo, hi)]
        if not entries:
            return
        # shuffle-read coalesce (reference: GpuShuffleCoalesceExec)
        cap = bucket_capacity(max(sum(rows for _, rows in entries), 1))
        from ..memory.retry import with_retry_no_split

        def assemble():
            """The pin loop, transactional: a mid-loop OOM from get()
            unpins the ALREADY-PINNED entries before propagating — the
            retry loop (or a coordinated re-read) finds every piece
            unpinned and spillable, and `use` refcounts are only
            committed after a successful read below."""
            pinned: List[SpillableBatch] = []
            try:
                got = []
                for sb, _ in entries:
                    got.append(sb.get())
                    pinned.append(sb)
                if len(got) == 1:
                    return pinned, got[0]
                # per-batch dictionaries unify to ONE merged dictionary
                # via a device code-remap (eager: we are between kernels
                # here), so the shuffle-read coalesce keeps string
                # columns encoded across the concat
                from ..dictenc import unify_dict_batches
                got = unify_dict_batches(got)
                return pinned, concat_batches(got, cap)
            except BaseException:
                for sb in pinned:
                    sb.done_with()
                raise

        pinned, batch = with_retry_no_split(assemble, catalog=self._cat(),
                                            name=f"{self.name}.read")
        pinned_ids = {id(sb) for sb in pinned}
        try:
            yield batch
        finally:
            # free a piece after its LAST referencing read partition
            # (skew-split replicates build pieces across readers). An
            # abandoned generator (limit early-exit) may be finalized
            # AFTER do_close() already reset the refcounts (use is None
            # -> idempotent close).
            use = self._use_left
            for op_, lo, hi in spec:
                for i in range(lo, hi):
                    sb = parts[op_][i][0]
                    if use is None:
                        sb.close()
                        continue
                    use[(op_, i)] -= 1
                    if use[(op_, i)] <= 0:
                        sb.close()
                    elif id(sb) in pinned_ids:
                        sb.done_with()

    def serialized_partitions(self, codec: Optional[str] = None,
                              depth: Optional[int] = None
                              ) -> Iterator[Tuple[int, List[bytes]]]:
        """Wire export of the materialized shuffle — the host-boundary /
        DCN path (reference: GpuPartitioning.scala:52 serialize-once
        slicing + GpuShuffleExchangeExecBase serialized blocks).

        Yields ``(reader_partition, [frame, ...])`` in partition order.
        Each piece is serialized exactly ONCE: device-resident pieces take
        a single D2H staging pass into a PackedTable and are framed from
        it; pieces the catalog already spilled to the host tier frame
        straight from their existing PackedTable with NO device
        round-trip (and no Arrow materialization anywhere). The D2H
        staging of the next piece overlaps the framing/compression of the
        current one through the bounded pipeline (prefetch.depth; 0 =
        synchronous)."""
        import time as _time
        from ..pipeline import close_iterator, prefetched
        from ..utils import tracing
        from .serializer import frame_packed, pack_batch
        specs = self._reader_specs()
        parts = self._materialize()

        from ..memory.retry import with_retry_no_split

        def staged():
            # producer stage: D2H (or host-tier view) per piece. The
            # pack/pin of each piece runs under the retry loop — an OOM
            # on the producer thread (pin of a spilled piece reserving
            # budget) spills/retries there; an unretryable one is
            # re-raised at the consumer by the pipeline.
            for p, spec in enumerate(specs):
                for op_, lo, hi in spec:
                    for i in range(lo, hi):
                        sb = parts[op_][i][0]

                        def pack_one(sb=sb):
                            pt = sb.host_view()
                            if pt is None:
                                batch = sb.get()
                                try:
                                    pt = pack_batch(batch)
                                finally:
                                    sb.done_with()
                            return pt

                        yield p, with_retry_no_split(
                            pack_one, catalog=self._cat(),
                            name=f"{self.name}.wire")

        if depth is None:
            from ..config import PREFETCH_DEPTH, PREFETCH_ENABLED, _REGISTRY
            depth = int(_REGISTRY[PREFETCH_DEPTH.key].default) \
                if _REGISTRY[PREFETCH_ENABLED.key].default else 0
        it = prefetched(staged(), depth, metrics=self.metrics,
                        name="exchange-wire")
        next_p, frames = 0, []
        try:
            for p, pt in it:
                while next_p < p:
                    yield next_p, frames
                    next_p, frames = next_p + 1, []
                t0 = _time.perf_counter_ns()
                with tracing.op_range(f"{self.name}.serialize"):
                    frames.append(frame_packed(pt, codec))
                self.metrics["serializeTime"].add(
                    _time.perf_counter_ns() - t0)
            while next_p < len(specs):
                yield next_p, frames
                next_p, frames = next_p + 1, []
        finally:
            close_iterator(it)

    def do_close(self) -> None:
        # partitions the consumer never read (limits, early exit) still
        # hold catalog entries; SpillableBatch.close is idempotent
        if self._materialized is not None:
            for pieces in self._materialized:
                for sb, _ in pieces:
                    sb.close()
            self._materialized = None
            self._specs = None
            self._use_left = None


def coordinate_join_reads(stream: "ShuffleExchangeExec",
                          build: "ShuffleExchangeExec",
                          target_rows: int,
                          skew_split_rows: Optional[int] = None) -> int:
    """Jointly plan the reader partitions of a co-partitioned join's two
    exchanges (the role of Spark AQE's ShufflePartitionsUtil +
    OptimizeSkewedJoin): groups are computed once on COMBINED row counts so
    both sides agree on the layout — independent per-side coalescing would
    silently break co-partitioning. A skewed map-output partition (stream
    rows > skew_split_rows) is split into piece-range reader partitions,
    each paired with a full replica of the matching build partition
    (PartialReducerPartitionSpec semantics). Returns the number of skew
    splits performed."""
    from ..plan.adaptive import record_decision
    sc = stream.partition_row_counts()
    bc = build.partition_row_counts()
    assert len(sc) == len(bc), (len(sc), len(bc))
    combined = [a + b for a, b in zip(sc, bc)]
    if skew_split_rows:
        # skewed partitions are NOT coalesceable (OptimizeSkewedJoin
        # runs before coalescing): each becomes its own singleton group
        # so the split branch below sees it, and only the thin runs
        # BETWEEN skewed partitions coalesce toward target_rows.
        groups = []
        run: List[int] = []
        for p, c in enumerate(combined):
            if sc[p] > skew_split_rows:
                if run:
                    groups += [[run[i] for i in g] for g in
                               _coalesce_groups([combined[i] for i in run],
                                                target_rows)]
                    run = []
                groups.append([p])
            else:
                run.append(p)
        if run:
            groups += [[run[i] for i in g] for g in
                       _coalesce_groups([combined[i] for i in run],
                                        target_rows)]
    else:
        groups = _coalesce_groups(combined, target_rows)
    if len(groups) < len(combined):
        record_decision(
            "coalesce",
            f"coordinated join exchanges: {len(combined)} materialized "
            f"partitions -> {len(groups)} reader partitions "
            f"(targetRows={target_rows})",
            n=len(combined) - len(groups))
    s_specs: List[ReadSpec] = []
    b_specs: List[ReadSpec] = []
    n_splits = 0
    for g in groups:
        if skew_split_rows and len(g) == 1 and sc[g[0]] > skew_split_rows:
            p = g[0]
            rows = stream.piece_row_counts(p)
            chunks: List[Tuple[int, int]] = []
            lo, cur = 0, 0
            for i, r in enumerate(rows):
                if cur and cur + r > skew_split_rows:
                    chunks.append((lo, i))
                    lo, cur = i, 0
                cur += r
            chunks.append((lo, len(rows)))
            np_build = len(build.piece_row_counts(p))
            if len(chunks) > 1:
                n_splits += len(chunks) - 1
                record_decision(
                    "skewSplit",
                    f"partition {p}: {sc[p]} stream rows > "
                    f"splitRows={skew_split_rows} -> {len(chunks)} "
                    f"piece-range reader partitions (build replicated)",
                    n=len(chunks) - 1)
            for c_lo, c_hi in chunks:
                s_specs.append([(p, c_lo, c_hi)])
                b_specs.append([(p, 0, np_build)])
        else:
            s_specs.append([(p, 0, len(stream.piece_row_counts(p)))
                            for p in g])
            b_specs.append([(p, 0, len(build.piece_row_counts(p)))
                            for p in g])
    stream.set_reader_specs(s_specs)
    build.set_reader_specs(b_specs)
    return n_splits


class BroadcastTooLargeError(MemoryError):
    """The broadcast relation exceeds spark.rapids.tpu.broadcast.maxBytes
    (Spark's 8GB broadcast hard limit analogue) — the planner should have
    chosen a shuffled join for this build side."""


class BroadcastExchangeExec(UnaryExec):
    """Replicate the child's full output as one batch (reference:
    GpuBroadcastExchangeExec — host-serialized concat batches rebuilt on
    device per executor; single-process here, so it is a concat + cache).

    The cached relation is catalog-registered (spillable between reads)
    and bounded by spark.rapids.tpu.broadcast.maxBytes."""

    @property
    def produces_single_batch(self):
        return True

    def __init__(self, child: Exec, ctx: Optional[EvalContext] = None,
                 max_bytes: Optional[int] = None,
                 catalog: Optional[BufferCatalog] = None):
        super().__init__(child, ctx)
        self._sb: Optional[SpillableBatch] = None
        if max_bytes is None:
            from ..config import BROADCAST_LIMIT, RapidsTpuConf
            max_bytes = RapidsTpuConf().get(BROADCAST_LIMIT.key)
        self.max_bytes = max_bytes
        self._catalog = catalog

    @property
    def output_schema(self) -> Schema:
        return self.child.output_schema

    @property
    def num_partitions(self) -> int:
        return 1

    def do_execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        from ..memory.retry import acquire_with_retry, with_retry_no_split
        if self._sb is None:
            batches = [b for cp in range(self.child.num_partitions)
                       for b in self.child.execute_partition(cp)]
            if self._catalog is None:
                from ..memory.catalog import device_budget
                self._catalog = device_budget()

            def build():
                if not batches:
                    from ..batch import empty_batch
                    cached = empty_batch(self.output_schema)
                elif len(batches) == 1:
                    cached = batches[0]
                else:
                    cap = bucket_capacity(sum(b.capacity for b in batches))
                    cached = concat_batches(batches, cap)
                if cached.size_bytes() > self.max_bytes:
                    # NOT retryable: a planner-contract violation, no
                    # amount of spilling shrinks the relation
                    raise BroadcastTooLargeError(
                        f"broadcast relation is {cached.size_bytes()}b > "
                        f"spark.rapids.tpu.broadcast.maxBytes="
                        f"{self.max_bytes}; use a shuffled join for this "
                        f"build side")
                return SpillableBatch(self._catalog, cached,
                                      self.output_schema)

            self._sb = with_retry_no_split(build, catalog=self._catalog,
                                           name=self.name)
        batch = acquire_with_retry(self._sb, name=self.name)
        try:
            yield batch
        finally:
            self._sb.done_with()    # spillable again between reads

    def do_close(self) -> None:
        if self._sb is not None:
            self._sb.close()
            self._sb = None


_cached_shuffle_ids = itertools.count(1)


class CachedShuffleExchangeExec(UnaryExec):
    """Device-resident CACHED shuffle mode (reference: RapidsCachingWriter
    + ShuffleBufferCatalog, RapidsShuffleInternalManagerBase.scala:876):
    map outputs are registered as spillable DEVICE blocks in a
    DeviceShuffleCache; readers take local blocks as device batches with
    ZERO serialization and pull remote peers' blocks through the TCP
    transport. Within one process every block is local — a fully
    device-resident exchange."""

    def __init__(self, partitioning: Partitioning, child: Exec,
                 ctx: Optional[EvalContext] = None, cache=None, conf=None):
        super().__init__(child, ctx)
        self.partitioning = partitioning.bind(child.output_schema)
        self._shuffle_id = next(_cached_shuffle_ids)
        self._cache = cache
        self._conf = conf
        self._written = False
        self._write_lock = threading.Lock()
        self._slice_jit = jax.jit(
            lambda b, pids, p: compact(b, pids == p), static_argnums=2)
        self._pids_jit = jax.jit(
            lambda b: self.partitioning.partition_ids(b, self.ctx))

    def _get_cache(self):
        if self._cache is None:
            from .device_cache import shared_device_cache
            self._cache = shared_device_cache(getattr(self, "_conf", None))
        return self._cache

    @property
    def output_schema(self) -> Schema:
        return self.child.output_schema

    @property
    def num_partitions(self) -> int:
        return self.partitioning.num_partitions

    def _write(self) -> None:
        # double-checked under the lock: concurrent reduce-partition
        # consumers must not both enter and register duplicate blocks
        # (same discipline DeviceShuffleCache uses internally)
        if self._written:
            return
        with self._write_lock:
            if self._written:
                return
            self._write_locked()

    def _write_locked(self) -> None:
        cache = self._get_cache()
        schema = self.child.output_schema
        m = 0
        shrink = jax.jit(lambda b, cap: slice_batch(b, 0, b.num_rows, cap),
                         static_argnums=1)
        for cp in range(self.child.num_partitions):
            for batch in self.child.execute_partition(cp):
                pids = self._pids_jit(batch)
                for r in range(self.num_partitions):
                    piece = self._slice_jit(batch, pids, r)
                    rows = int(piece.num_rows)
                    if rows == 0:
                        continue   # absent blocks read as None downstream
                    cap = bucket_capacity(rows)
                    if cap < piece.capacity:
                        # full-capacity slices would multiply residency by
                        # the partition count (same policy as _register)
                        piece = shrink(piece, cap)
                    cache.add_batch(self._shuffle_id, m, r, piece, schema)
                m += 1
        self._n_maps = m
        self._written = True   # only after a COMPLETE write

    def do_execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        self._write()
        cache = self._get_cache()
        schema = self.child.output_schema
        for m in range(self._n_maps):
            out = cache.get_local(self._shuffle_id, m, p)
            if out is not None:
                yield out

    def do_close(self) -> None:
        if self._written:
            self._get_cache().remove_shuffle(self._shuffle_id)
            self._written = False
