"""Shuffle and broadcast exchanges.

Reference: GpuShuffleExchangeExecBase.scala:152,262 (prepareBatchShuffleDependency:
partition-id eval → device slicing → serialized blocks),
GpuBroadcastExchangeExec.scala:319. This module is the DEFAULT/host-mediated
shuffle mode (SURVEY.md §2.10): per input batch, rows are sliced per target
partition ON DEVICE (one fused kernel computing partition ids + cumsum
compaction per target), and re-coalesced on the read side. The ICI
device-collective mode lives in parallel/mesh.py; both sit behind the same
exec surface the way the reference's three shuffle modes sit behind one
shuffle manager.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional

import jax
import jax.numpy as jnp

from ..batch import ColumnarBatch, Schema, bucket_capacity
from ..exec.base import Exec, UnaryExec
from ..exec.common import compact, concat_batches
from ..expressions.base import EvalContext
from .partitioning import Partitioning, RangePartitioning, SinglePartitioning


class ShuffleExchangeExec(UnaryExec):
    """All-to-all redistribution of rows by a partitioning."""

    def __init__(self, partitioning: Partitioning, child: Exec,
                 ctx: Optional[EvalContext] = None, adaptive: bool = False,
                 target_rows: int = 1 << 20):
        super().__init__(child, ctx)
        self.partitioning = partitioning.bind(child.output_schema)
        self._materialized: Optional[List[List[ColumnarBatch]]] = None
        # AQE (reference: GpuCustomShuffleReaderExec): after the stage
        # materializes, adjacent small output partitions coalesce into one
        # reader partition using real row counts.
        self.adaptive = adaptive
        self.target_rows = target_rows
        self._groups: Optional[List[List[int]]] = None

        def slice_kernel(batch: ColumnarBatch, pids, p: int) -> ColumnarBatch:
            return compact(batch, pids == p)

        self._slice_jit = jax.jit(slice_kernel, static_argnums=2)
        self._pids_jit = jax.jit(
            lambda b: self.partitioning.partition_ids(b, self.ctx))

    @property
    def output_schema(self) -> Schema:
        return self.child.output_schema

    @property
    def num_partitions(self) -> int:
        if self.adaptive:
            return len(self._partition_groups())
        return self.partitioning.num_partitions

    def _partition_groups(self) -> List[List[int]]:
        """Greedy adjacent coalesce of small partitions by materialized row
        counts (AQE coalesce-partitions)."""
        if self._groups is not None:
            return self._groups
        parts = self._materialize()
        counts = [sum(int(b.num_rows) for b in pieces) for pieces in parts]
        groups: List[List[int]] = []
        cur: List[int] = []
        cur_rows = 0
        for p, c in enumerate(counts):
            if cur and cur_rows + c > self.target_rows:
                groups.append(cur)
                cur, cur_rows = [], 0
            cur.append(p)
            cur_rows += c
        if cur:
            groups.append(cur)
        self._groups = groups or [[0]]
        return self._groups

    def _sample_range_bounds(self, batches: List[ColumnarBatch]) -> None:
        """Compute range bounds from the materialized input (reference:
        GpuRangePartitioner.sketch/determineBounds)."""
        from ..exec.common import sort_operands, gather_column
        part: RangePartitioning = self.partitioning
        n = self.partitioning.num_partitions
        # concat all key columns, sort, take n-1 evenly spaced bound rows
        key_batches = []
        counts = []
        for b in batches:
            cols = part.key_columns(b, self.ctx)
            key_batches.append(ColumnarBatch(tuple(cols), b.num_rows))
            counts.append(b.num_rows)
        cap = bucket_capacity(sum(kb.capacity for kb in key_batches))
        allk = concat_batches(key_batches, cap)

        def bounds_kernel(kb: ColumnarBatch):
            live = kb.row_mask()
            ops = sort_operands(
                list(kb.columns), part._descending, part._nulls_first, live)
            iota = jnp.arange(kb.capacity, dtype=jnp.int32)
            perm = jax.lax.sort(ops + [iota], num_keys=len(ops) + 1)[-1]
            skeys = [gather_column(c, perm) for c in kb.columns]
            total = kb.num_rows
            # bound i sits at row (i+1)*total/n
            pos = ((jnp.arange(n - 1, dtype=jnp.int64) + 1) * total) // n
            pos = jnp.clip(pos, 0, kb.capacity - 1).astype(jnp.int32)
            return [gather_column(c, pos) for c in skeys]

        bound_cols = jax.jit(bounds_kernel)(allk)
        part.set_bounds(bound_cols, n - 1)

    def _materialize(self) -> List[List[ColumnarBatch]]:
        if self._materialized is not None:
            return self._materialized
        n = self.partitioning.num_partitions   # write-side nominal count
        out: List[List[ColumnarBatch]] = [[] for _ in range(n)]
        batches = [b for cp in range(self.child.num_partitions)
                   for b in self.child.execute_partition(cp)]
        if isinstance(self.partitioning, RangePartitioning) and batches:
            self._sample_range_bounds(batches)
        for batch in batches:
            if n == 1:
                out[0].append(batch)
                continue
            pids = self._pids_jit(batch)
            for p in range(n):
                piece = self._slice_jit(batch, pids, p)
                out[p].append(piece)
        self._materialized = out
        return out

    def do_execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        if self.adaptive:
            group = self._partition_groups()[p]
            pieces = [b for op_ in group for b in self._materialize()[op_]]
        else:
            pieces = self._materialize()[p]
        pieces = [b for b in pieces if int(b.num_rows) > 0]
        if not pieces:
            return
        # shuffle-read coalesce (reference: GpuShuffleCoalesceExec)
        cap = bucket_capacity(max(sum(int(b.num_rows) for b in pieces), 1))
        if len(pieces) == 1:
            yield pieces[0]
        else:
            yield concat_batches(pieces, cap)


class BroadcastExchangeExec(UnaryExec):
    """Replicate the child's full output as one batch (reference:
    GpuBroadcastExchangeExec — host-serialized concat batches rebuilt on
    device per executor; single-process here, so it is a concat + cache)."""

    def __init__(self, child: Exec, ctx: Optional[EvalContext] = None):
        super().__init__(child, ctx)
        self._cached: Optional[ColumnarBatch] = None

    @property
    def output_schema(self) -> Schema:
        return self.child.output_schema

    @property
    def num_partitions(self) -> int:
        return 1

    def do_execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        if self._cached is None:
            batches = [b for cp in range(self.child.num_partitions)
                       for b in self.child.execute_partition(cp)]
            if not batches:
                from ..batch import empty_batch
                self._cached = empty_batch(self.output_schema)
            elif len(batches) == 1:
                self._cached = batches[0]
            else:
                cap = bucket_capacity(sum(b.capacity for b in batches))
                self._cached = concat_batches(batches, cap)
        yield self._cached
