"""Framed, compressed batch wire format.

Reference: GpuColumnarBatchSerializer.scala:124 over JCudfSerialization
(host-framed tables for the default shuffle path) + TableCompressionCodec
(batched nvcomp LZ4). Same layering here: a host-framed format whose column
payloads run through the native LZ4 (utils/native.py, C++) — used by the
disk spill tier and the multithreaded shuffle, and as the DCN wire format.

Frame layout (little-endian):
  magic 'RTPU' | u32 version | u32 crc32(body) | u32 ncols | i64 nrows
  per column:
    u8 has_lengths | u8 codec(0=none,1=lz4,2=zlib,3=zstd) padding x2
    u32 name_len | name bytes
    u8  numpy dtype string len | dtype bytes | u32 extra(max_len)
    i64 raw_data_len | i64 comp_data_len | payload
    i64 raw_valid_len | i64 comp_valid_len | payload
    [i64 raw_lengths_len | i64 comp_len | payload]
"""

from __future__ import annotations

import io
import struct
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..batch import ColumnarBatch, DeviceColumn, Schema
from ..types import TypeKind
from ..utils import native

MAGIC = b"RTPU"
#: v2 added the envelope CRC32 (integrity of wire frames + spill files)
VERSION = 2
_CODEC = {"none": 0, "lz4": 1, "zlib": 2, "zstd": 3}
_CODEC_R = {v: k for k, v in _CODEC.items()}

#: magic(4) + version(4) + crc(4): the body the CRC covers starts here
_HEADER_LEN = 12


class FrameChecksumError(RuntimeError):
    """The frame body does not match the CRC32 its envelope carries —
    the bytes were damaged between serialize (exchange wire export,
    disk-tier spill write) and deserialize (fetch decode, spill read).
    Failing loudly here is the contract: a corrupt frame must never
    decode into silently-wrong rows."""


def _start_frame() -> io.BytesIO:
    """Open a frame with a zero CRC placeholder; _seal_frame patches it."""
    out = io.BytesIO()
    out.write(MAGIC)
    out.write(struct.pack("<II", VERSION, 0))
    return out


def _seal_frame(out: io.BytesIO) -> bytes:
    """Patch the envelope CRC32 in place — no extra full-frame copy on
    the spill/wire hot path (a multi-hundred-MB frame must not
    transiently double while the process is spilling under pressure)."""
    buf = out.getbuffer()
    crc = zlib.crc32(buf[_HEADER_LEN:]) & 0xFFFFFFFF
    struct.pack_into("<I", buf, 8, crc)
    del buf          # release the memoryview before getvalue()
    return out.getvalue()


def _write_blob(out: io.BytesIO, raw,
                codec: Optional[str] = None) -> None:
    """``raw`` may be bytes or a contiguous byte memoryview into a shared
    buffer (the packed-table fast path): every codec path consumes it
    without an intermediate copy (np.frombuffer / zlib accept views)."""
    payload, codec = native.compress(raw, codec)
    if len(payload) >= len(raw):
        payload, codec = raw, "none"
    out.write(struct.pack("<qqB", len(raw), len(payload), _CODEC[codec]))
    out.write(payload)


def _read_blob(buf: memoryview, pos: int) -> Tuple[bytes, int]:
    raw_len, comp_len, codec = struct.unpack_from("<qqB", buf, pos)
    pos += 17
    payload = bytes(buf[pos: pos + comp_len])
    pos += comp_len
    return native.decompress(payload, _CODEC_R[codec], raw_len), pos


def serialize_host(arrays: Dict[str, np.ndarray], num_rows: int,
                   codec: Optional[str] = None) -> bytes:
    """Serialize named host arrays (the spill-store / shuffle-write side).
    ``codec`` overrides the process default (per-session shuffle codec)."""
    out = _start_frame()
    out.write(struct.pack("<Iq", len(arrays), num_rows))
    for name, arr in arrays.items():
        arr = np.asarray(arr)   # NOT ascontiguousarray: it promotes 0-d to 1-d
        nb = name.encode()
        dt = arr.dtype.str.encode()
        out.write(struct.pack("<I", len(nb)))
        out.write(nb)
        out.write(struct.pack("<B", len(dt)))
        out.write(dt)
        out.write(struct.pack("<B", arr.ndim))
        for s in arr.shape:
            out.write(struct.pack("<q", s))
        _write_blob(out, arr.tobytes(), codec)
    return _seal_frame(out)


def deserialize_host(data: bytes) -> Tuple[Dict[str, np.ndarray], int]:
    buf = memoryview(data)
    assert bytes(buf[:4]) == MAGIC, "bad frame magic"
    version, crc = struct.unpack_from("<II", buf, 4)
    assert version == VERSION, f"frame version {version} != {VERSION}"
    # verified on EVERY deserialize — shuffle fetch decode and disk-tier
    # spill read alike (reference: the per-buffer checksums the UCX
    # shuffle validates on receive)
    if zlib.crc32(buf[_HEADER_LEN:]) & 0xFFFFFFFF != crc:
        raise FrameChecksumError(
            f"frame body fails its envelope CRC32 "
            f"({len(data) - _HEADER_LEN} bytes)")
    ncols, num_rows = struct.unpack_from("<Iq", buf, _HEADER_LEN)
    pos = _HEADER_LEN + 12
    arrays: Dict[str, np.ndarray] = {}
    for _ in range(ncols):
        (nlen,) = struct.unpack_from("<I", buf, pos)
        pos += 4
        name = bytes(buf[pos: pos + nlen]).decode()
        pos += nlen
        (dlen,) = struct.unpack_from("<B", buf, pos)
        pos += 1
        dt = bytes(buf[pos: pos + dlen]).decode()
        pos += dlen
        (ndim,) = struct.unpack_from("<B", buf, pos)
        pos += 1
        shape = []
        for _ in range(ndim):
            (s,) = struct.unpack_from("<q", buf, pos)
            pos += 8
            shape.append(s)
        raw, pos = _read_blob(buf, pos)
        arrays[name] = np.frombuffer(raw, dtype=np.dtype(dt)).reshape(shape)
    return arrays, num_rows


def _col_to_arrays(c: DeviceColumn, key: str,
                   arrays: Dict[str, np.ndarray]) -> None:
    """Flatten one column's device lanes under path-encoded keys; struct
    children recurse as ``{key}.{j}`` (the schema drives reassembly)."""
    import jax
    arrays[f"v{key}"] = np.asarray(jax.device_get(c.validity))
    if c.is_struct:
        for j, kid in enumerate(c.struct_fields):
            _col_to_arrays(kid, f"{key}.{j}", arrays)
        return
    arrays[f"d{key}"] = np.asarray(jax.device_get(c.data))
    if c.lengths is not None:
        arrays[f"l{key}"] = np.asarray(jax.device_get(c.lengths))
    if c.data2 is not None:     # map values / string-array lengths
        arrays[f"m{key}"] = np.asarray(jax.device_get(c.data2))
    if c.dict_data is not None:
        # dict strings ship dictionary + codes (d{key} above IS the code
        # lane) instead of a padded byte matrix — the compressed wire form
        arrays[f"D{key}"] = np.asarray(jax.device_get(c.dict_data))
        arrays[f"e{key}"] = np.asarray(jax.device_get(c.dict_lengths))


def _col_from_arrays(dtype, key: str,
                     arrays: Dict[str, np.ndarray]) -> DeviceColumn:
    import jax.numpy as jnp
    from ..types import TypeKind
    validity = jnp.asarray(arrays[f"v{key}"])
    if dtype.kind is TypeKind.STRUCT:
        kids = tuple(_col_from_arrays(ct, f"{key}.{j}", arrays)
                     for j, ct in enumerate(dtype.children))
        return DeviceColumn(kids, validity, None, dtype)
    lengths = jnp.asarray(arrays[f"l{key}"]) if f"l{key}" in arrays else None
    data2 = jnp.asarray(arrays[f"m{key}"]) if f"m{key}" in arrays else None
    dict_data = jnp.asarray(arrays[f"D{key}"]) \
        if f"D{key}" in arrays else None
    dict_lengths = jnp.asarray(arrays[f"e{key}"]) \
        if f"e{key}" in arrays else None
    return DeviceColumn(jnp.asarray(arrays[f"d{key}"]), validity,
                        lengths, dtype, data2, dict_data, dict_lengths)


def batch_to_arrays(batch: ColumnarBatch) -> Dict[str, np.ndarray]:
    """D2H every lane of a device batch under its path-encoded keys."""
    arrays: Dict[str, np.ndarray] = {}
    for i, c in enumerate(batch.columns):
        _col_to_arrays(c, str(i), arrays)
    return arrays


def pack_batch(batch: ColumnarBatch):
    """One D2H staging pass: the device batch's lanes land in a single
    contiguous host PackedTable (memory/packed.py — the pinned-staging
    shape), which BOTH the spill host tier and `frame_packed` consume
    without reparsing. This is the serialize-once carrier: a batch packed
    here is never re-flattened, whether it goes to the wire, to disk, or
    back to the device."""
    from ..memory.packed import PackedTable
    return PackedTable.pack(batch_to_arrays(batch), int(batch.num_rows))


def frame_packed(packed, codec: Optional[str] = None) -> bytes:
    """PackedTable -> RTPU frame, slicing each section's payload straight
    out of the packed buffer (no per-array tobytes round-trip; the only
    remaining copy is the codec's own output). Byte-compatible with
    serialize_host — deserialize_host/deserialize_batch read both."""
    mv = memoryview(packed.buffer).cast("B")
    out = _start_frame()
    out.write(struct.pack("<Iq", len(packed.meta.sections),
                          packed.meta.num_rows))
    for s in packed.meta.sections:
        nb = s.key.encode()
        dt = s.dtype.encode()
        out.write(struct.pack("<I", len(nb)))
        out.write(nb)
        out.write(struct.pack("<B", len(dt)))
        out.write(dt)
        out.write(struct.pack("<B", len(s.shape)))
        for dim in s.shape:
            out.write(struct.pack("<q", dim))
        _write_blob(out, mv[s.offset: s.offset + s.nbytes], codec)
    return _seal_frame(out)


def serialize_batch(batch: ColumnarBatch, schema: Schema,
                    codec: Optional[str] = None) -> bytes:
    """Device batch -> framed bytes: ONE D2H staging pass into a packed
    table, then frame directly from it (reference: the serialize-once
    contiguous-split + JCudfSerialization write path,
    GpuPartitioning.scala:52)."""
    from ..trace import span as _trace_span
    with _trace_span("serializer.pack", kind="serializer") as sp:
        data = frame_packed(pack_batch(batch), codec)
        if sp is not None:
            sp.attrs["bytes"] = len(data)
        return data


def iter_framed(batches, codec: Optional[str] = None,
                depth: Optional[int] = None, metrics=None):
    """Frame a stream of device batches with the D2H stage of batch N+1
    overlapped with the framing/compression of batch N (the exchange-side
    use of the bounded pipeline; depth=0 = synchronous). Yields
    (item, frame_bytes) pairs where ``batches`` yields (item, batch)."""
    from ..pipeline import close_iterator, prefetched

    def staged():
        for item, b in batches:
            yield item, pack_batch(b)     # D2H on the producer thread

    if depth is None:
        from ..config import PREFETCH_DEPTH, PREFETCH_ENABLED, _REGISTRY
        depth = int(_REGISTRY[PREFETCH_DEPTH.key].default) \
            if _REGISTRY[PREFETCH_ENABLED.key].default else 0
    it = prefetched(staged(), depth, metrics=metrics,
                    name="exchange-serialize")
    try:
        for item, packed in it:
            yield item, frame_packed(packed, codec)
    finally:
        close_iterator(it)


def deserialize_batch(data: bytes, schema: Schema) -> ColumnarBatch:
    import jax.numpy as jnp

    from ..trace import span as _trace_span
    with _trace_span("serializer.unpack", kind="serializer",
                     bytes=len(data)):
        arrays, num_rows = deserialize_host(data)
        cols: List[DeviceColumn] = []
        for i, f in enumerate(schema):
            cols.append(_col_from_arrays(f.dtype, str(i), arrays))
        return ColumnarBatch(tuple(cols), jnp.asarray(num_rows, jnp.int32))
