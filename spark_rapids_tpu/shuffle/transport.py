"""Pluggable cross-process shuffle transport.

Reference: shuffle-plugin/.../RapidsShuffleTransport.scala:303 — the
trait behind the UCX shuffle: a SERVER publishing this executor's shuffle
blocks, CLIENTS fetching peers' blocks as framed TRANSACTIONS, and a
registry mapping (shuffle, map, reduce) to buffers. The reference tests
the protocol against mocked peers (RapidsShuffleTestHelper.scala); the
same strategy applies here.

TPU context: INSIDE one process the ICI mesh moves shuffle data as one
XLA all_to_all — no transport needed. The transport exists for the
CROSS-PROCESS tier (multi-host DCN without jax.distributed, spill-backed
elastic shuffles). Two implementations of one interface:

- LocalFsTransport — shared-filesystem blocks (the multithreaded shuffle
  mode's storage, behind the trait so it is swappable),
- TcpTransport — a length-prefixed binary protocol over sockets:
  HELLO version handshake, FETCH(shuffle, map, reduce) → OK payload /
  MISSING / ERROR, connection-per-request clients with retry.

Every payload is the framed serializer format (serializer.py), so blocks
are compressed once on publish and device-decoded once on fetch.
"""

from __future__ import annotations

import os
import socket
import socketserver
import struct
import threading
from typing import Dict, List, Optional, Tuple

_MAGIC = b"RTPU"
_VERSION = 1

# ops
_HELLO, _FETCH, _OK, _MISSING, _ERROR, _LIST = 1, 2, 3, 4, 5, 6
# windowed-block streaming (reference: WindowedBlockIterator +
# BounceBufferManager — large blocks move in fixed-size staging windows)
_SIZE, _FETCH_AT = 7, 8

#: default staging window for large-block fetches (one bounce buffer)
DEFAULT_WINDOW_BYTES = 4 << 20


class TransportError(RuntimeError):
    pass


class BlockId(Tuple):
    """(shuffle_id, map_id, reduce_id)"""


class ShuffleTransport:
    """The RapidsShuffleTransport role: publish local blocks, fetch any
    block (local or remote)."""

    def publish(self, shuffle_id: int, map_id: int, reduce_id: int,
                payload: bytes) -> None:
        raise NotImplementedError

    def fetch(self, shuffle_id: int, map_id: int, reduce_id: int) -> bytes:
        raise NotImplementedError

    def list_blocks(self, shuffle_id: int, reduce_id: int
                    ) -> List[Tuple[int, int, int]]:
        """All published (shuffle, map, reduce) blocks for a reducer,
        including remote peers' blocks."""
        raise NotImplementedError

    def fetch_many(self, ids, max_in_flight: int = 4):
        """Yield (block_id, bytes) for many blocks; subclasses with a
        wire pipeline overlap the fetches."""
        for b in ids:
            yield b, self.fetch(*b)

    def remove_shuffle(self, shuffle_id: int) -> None:
        """Drop every local block of one shuffle (end-of-query cleanup)."""
        pass

    def close(self) -> None:
        pass


class LocalFsTransport(ShuffleTransport):
    """Shared-directory blocks (works across processes on one host or any
    shared filesystem — the reference's fallback shuffle storage)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, s: int, m: int, r: int) -> str:
        return os.path.join(self.root, f"s{s}-m{m}-r{r}.rtpu")

    def publish(self, s: int, m: int, r: int, payload: bytes) -> None:
        tmp = self._path(s, m, r) + ".tmp"
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, self._path(s, m, r))    # atomic publish

    def fetch(self, s: int, m: int, r: int) -> bytes:
        try:
            with open(self._path(s, m, r), "rb") as f:
                return f.read()
        except FileNotFoundError:
            raise TransportError(f"missing block s{s}-m{m}-r{r}")

    def list_blocks(self, s: int, r: int):
        out = []
        for name in os.listdir(self.root):
            if name.startswith(f"s{s}-") and name.endswith(f"-r{r}.rtpu"):
                m = int(name.split("-")[1][1:])
                out.append((s, m, r))
        return sorted(out)

    def remove_shuffle(self, s: int) -> None:
        for name in os.listdir(self.root):
            if name.startswith(f"s{s}-"):
                try:
                    os.remove(os.path.join(self.root, name))
                except OSError:
                    pass

    def close(self) -> None:
        import shutil
        shutil.rmtree(self.root, ignore_errors=True)


# ---------------------------------------------------------------------------
# TCP transport
# ---------------------------------------------------------------------------

def _send_frame(sock: socket.socket, op: int, payload: bytes) -> None:
    sock.sendall(_MAGIC + struct.pack("<BI", op, len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise TransportError("peer closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> Tuple[int, bytes]:
    head = _recv_exact(sock, 9)
    if head[:4] != _MAGIC:
        raise TransportError("bad magic")
    op, ln = struct.unpack("<BI", head[4:])
    return op, _recv_exact(sock, ln)


class _BlockServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        store: "TcpTransport" = self.server.transport   # type: ignore
        try:
            op, payload = _recv_frame(self.request)
            if op != _HELLO or struct.unpack("<I", payload)[0] != _VERSION:
                _send_frame(self.request, _ERROR, b"version mismatch")
                return
            _send_frame(self.request, _HELLO, struct.pack("<I", _VERSION))
            while True:
                op, payload = _recv_frame(self.request)
                if op == _LIST:
                    s, r = struct.unpack("<qq", payload)
                    maps = [m for (_, m, _) in
                            store.local_blocks(s, r)]
                    _send_frame(self.request, _OK,
                                struct.pack(f"<{len(maps)}q", *maps))
                    continue
                if op == _SIZE:
                    s, m, r = struct.unpack("<qqq", payload)
                    blk = store._resolve(s, m, r)
                    if blk is None:
                        _send_frame(self.request, _MISSING, b"")
                    else:
                        _send_frame(self.request, _OK,
                                    struct.pack("<q", len(blk)))
                    continue
                if op == _FETCH_AT:
                    s, m, r, off, ln = struct.unpack("<qqqqq", payload)
                    blk = store._resolve(s, m, r)
                    if blk is None or off < 0 or off + ln > len(blk):
                        _send_frame(self.request, _MISSING, b"")
                    else:
                        _send_frame(self.request, _OK, blk[off:off + ln])
                    continue
                if op != _FETCH:
                    _send_frame(self.request, _ERROR, b"bad op")
                    return
                s, m, r = struct.unpack("<qqq", payload)
                blk = store._resolve(s, m, r)
                if blk is None:
                    _send_frame(self.request, _MISSING, b"")
                else:
                    _send_frame(self.request, _OK, blk)
        except (TransportError, ConnectionError, OSError):
            return


class TcpTransport(ShuffleTransport):
    """Framed TCP block server + fetch clients.

    Transactions mirror the reference's request/response shape
    (RapidsShuffleTransport's Transaction + BlockIds): one HELLO
    handshake per connection, then FETCH transactions. ``peers`` maps
    executor id → (host, port); blocks published locally are served to
    any peer."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 peers: Optional[Dict[int, Tuple[str, int]]] = None,
                 retries: int = 3, liveness=None, peer_source=None,
                 window_bytes: int = DEFAULT_WINDOW_BYTES):
        self._local: Dict[Tuple[int, int, int], bytes] = {}
        #: staging window for large-block fetches (the bounce-buffer
        #: size); blocks above it stream as _FETCH_AT range reads
        self.window_bytes = max(64 << 10, window_bytes)
        # persistent per-peer connections (reference: UCX keeps endpoints
        # alive; connection-per-request was the r4 design's weakness)
        self._conns: Dict[Tuple[str, int], socket.socket] = {}
        self._conn_locks: Dict[Tuple[str, int], threading.Lock] = {}
        self._conns_guard = threading.Lock()
        #: small FIFO cache of lazily-resolved blocks so a windowed read
        #: does not re-serialize the device batch per window — sized for
        #: several INTERLEAVED readers (a single slot would thrash when
        #: two reducers stream two large blocks concurrently)
        self._resolved_cache: Dict[Tuple[int, int, int], bytes] = {}
        self._resolved_cache_slots = 8
        self._index: Dict[Tuple[int, int], List[Tuple[int, int, int]]] = {}
        #: optional (s, m, r) -> bytes|None hook serving LAZY blocks whose
        #: payload lives elsewhere (the device-resident shuffle cache)
        self.resolver = None
        self.peers = dict(peers or {})
        self.retries = retries
        # liveness: () -> iterable of live peer ids, normally the driver
        # heartbeat registry's live_executors (reference:
        # RapidsShuffleHeartbeatManager feeding UCX endpoint setup).
        # Peers missing from it are skipped WITHOUT paying a socket
        # timeout; None = treat every configured peer as live.
        self.liveness = liveness
        # peer_source: () -> {id: (host, port)} — DYNAMIC discovery
        # (RegistryClient.peers); merged over the static table each
        # listing, so executors that join after this transport started
        # are still consulted (reference: heartbeat-driven endpoint
        # table updates)
        self.peer_source = peer_source
        self._server = _BlockServer((host, port), _Handler)
        self._server.transport = self       # type: ignore
        self.address = self._server.server_address
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        self._lock = threading.Lock()

    # ---- local publication ----
    def publish(self, s: int, m: int, r: int, payload: bytes) -> None:
        with self._lock:
            self._local[(s, m, r)] = payload
            self._index.setdefault((s, r), []).append((s, m, r))

    def publish_lazy(self, s: int, m: int, r: int) -> None:
        """Register a block whose bytes the ``resolver`` produces on
        demand (device-resident until fetched)."""
        with self._lock:
            self._index.setdefault((s, r), []).append((s, m, r))

    def local_blocks(self, s: int, r: int):
        with self._lock:
            return sorted(self._index.get((s, r), []))

    def _resolve(self, s: int, m: int, r: int) -> Optional[bytes]:
        """Materialized bytes of a local block: published payload, or the
        lazy resolver's output (cached one slot so windowed range reads
        serialize the device batch once)."""
        blk = self._local.get((s, m, r))
        if blk is not None:
            return blk
        if self.resolver is None:
            return None
        with self._lock:
            blk = self._resolved_cache.get((s, m, r))
        if blk is not None:
            return blk
        blk = self.resolver(s, m, r)
        if blk is not None:
            with self._lock:
                while len(self._resolved_cache) >= \
                        self._resolved_cache_slots:
                    self._resolved_cache.pop(
                        next(iter(self._resolved_cache)))
                self._resolved_cache[(s, m, r)] = blk
        return blk

    def _live_peers(self) -> Dict:
        peers = dict(self.peers)
        if self.peer_source is not None:
            peers.update(self.peer_source())
        if self.liveness is None:
            return peers
        live = set(self.liveness())
        return {pid: a for pid, a in peers.items() if pid in live}

    def list_blocks(self, s: int, r: int):
        """Local blocks UNION every LIVE peer's blocks (the shuffle
        reader must see remote map outputs); a live-but-unreachable peer
        raises — a silent partial listing would silently drop its rows.
        Peers the heartbeat registry declares dead are excluded up front
        (their tasks get rescheduled by the driver, the reference's
        executor-death story)."""
        out = set(self.local_blocks(s, r))
        for peer_id, addr in self._live_peers().items():
            maps = self._retrying(addr, self._list_from, s, r)
            out.update((s, m, r) for m in maps)
        return sorted(out)

    def remove_shuffle(self, s: int) -> None:
        with self._lock:
            for key in [k for k in self._local if k[0] == s]:
                del self._local[key]
            for key in [k for k in self._index if k[0] == s]:
                del self._index[key]

    def _retrying(self, addr, fn, *args):
        last: Optional[Exception] = None
        for _ in range(self.retries):
            try:
                return fn(addr, *args)
            except (TransportError, ConnectionError, OSError) as ex:
                last = ex
                if isinstance(ex, TransportError) and \
                        "missing" in str(ex):
                    raise
        raise TransportError(f"peer {addr} unreachable: {last}")

    # ---- fetch (local fast path, else ask each peer) ----
    def fetch(self, s: int, m: int, r: int) -> bytes:
        blk = self._local.get((s, m, r))
        if blk is not None:
            return blk
        last: Optional[Exception] = None
        for peer_id, addr in self._live_peers().items():
            try:
                return self._retrying(addr, self._fetch_from, s, m, r)
            except TransportError as ex:
                # missing on this peer or peer dead: try the next peer
                last = ex
        raise TransportError(f"block s{s}-m{m}-r{r} not found on any peer"
                             + (f" (last: {last})" if last else ""))

    # ---- persistent per-peer connections --------------------------------
    def _conn_of(self, addr):
        """(socket, lock) for ``addr``; connects + handshakes once and
        keeps the connection for the transport's lifetime (the reference
        keeps UCX endpoints alive the same way)."""
        with self._conns_guard:
            sock = self._conns.get(addr)
            lock = self._conn_locks.setdefault(addr, threading.Lock())
        if sock is not None:
            return sock, lock
        sock = socket.create_connection(addr, timeout=30)
        try:
            _send_frame(sock, _HELLO, struct.pack("<I", _VERSION))
            op, payload = _recv_frame(sock)
            if op != _HELLO:
                raise TransportError(f"handshake failed: {payload!r}")
        except BaseException:
            sock.close()
            raise
        with self._conns_guard:
            # lost the race: keep the winner's connection
            existing = self._conns.get(addr)
            if existing is not None:
                sock.close()
                return existing, lock
            self._conns[addr] = sock
        return sock, lock

    def _drop_conn(self, addr, sock) -> None:
        with self._conns_guard:
            if self._conns.get(addr) is sock:
                del self._conns[addr]
        try:
            sock.close()
        except OSError:
            pass

    def _transact(self, addr, op: int, payload: bytes):
        """One request/response on the persistent connection; a transport
        failure drops the connection so retries reconnect."""
        sock, lock = self._conn_of(addr)
        try:
            with lock:
                _send_frame(sock, op, payload)
                return _recv_frame(sock)
        except (TransportError, ConnectionError, OSError):
            self._drop_conn(addr, sock)
            raise

    def _list_from(self, addr, s: int, r: int) -> List[int]:
        op, payload = self._transact(addr, _LIST,
                                     struct.pack("<qq", s, r))
        if op != _OK:
            raise TransportError(f"list failed: {payload!r}")
        k = len(payload) // 8
        return list(struct.unpack(f"<{k}q", payload))

    def _fetch_from(self, addr, s: int, m: int, r: int) -> bytes:
        # size probe decides plain vs windowed streaming
        op, payload = self._transact(addr, _SIZE,
                                     struct.pack("<qqq", s, m, r))
        if op == _MISSING:
            raise TransportError("missing block")
        if op != _OK:
            raise TransportError(f"peer error: {payload!r}")
        (total,) = struct.unpack("<q", payload)
        if total <= self.window_bytes:
            op, payload = self._transact(addr, _FETCH,
                                         struct.pack("<qqq", s, m, r))
            if op == _OK:
                return payload
            if op == _MISSING:
                raise TransportError("missing block")
            raise TransportError(f"peer error: {payload!r}")
        # windowed streaming: fixed-size range reads into one buffer
        # (WindowedBlockIterator over bounce-buffer-sized steps)
        buf = bytearray(total)
        for off in range(0, total, self.window_bytes):
            ln = min(self.window_bytes, total - off)
            op, payload = self._transact(
                addr, _FETCH_AT, struct.pack("<qqqqq", s, m, r, off, ln))
            if op != _OK or len(payload) != ln:
                raise TransportError(
                    f"windowed read failed at {off} ({op})")
            buf[off:off + ln] = payload
        return bytes(buf)

    def fetch_many(self, ids, max_in_flight: int = 4):
        """Pipelined fetch of many blocks: yields (id, bytes) in input
        order while later fetches proceed in the background, so device
        decode overlaps the wire (the reference's windowed pending-fetch
        pipeline). Different peers progress in parallel; one peer's
        frames serialize on its connection."""
        from ..io.source import bounded_map, reader_pool
        pool = reader_pool(max(2, max_in_flight))
        yield from bounded_map(pool, list(ids),
                               lambda b: self.fetch(*b), max_in_flight,
                               force_parallel=True)

    def close(self) -> None:
        with self._conns_guard:
            conns = list(self._conns.values())
            self._conns.clear()
        for sock in conns:
            try:
                sock.close()
            except OSError:
                pass
        self._server.shutdown()
        self._server.server_close()
        self._local.clear()
