"""Pluggable cross-process shuffle transport.

Reference: shuffle-plugin/.../RapidsShuffleTransport.scala:303 — the
trait behind the UCX shuffle: a SERVER publishing this executor's shuffle
blocks, CLIENTS fetching peers' blocks as framed TRANSACTIONS, and a
registry mapping (shuffle, map, reduce) to buffers. The reference tests
the protocol against mocked peers (RapidsShuffleTestHelper.scala); the
same strategy applies here.

TPU context: INSIDE one process the ICI mesh moves shuffle data as one
XLA all_to_all — no transport needed. The transport exists for the
CROSS-PROCESS tier (multi-host DCN without jax.distributed, spill-backed
elastic shuffles). Two implementations of one interface:

- LocalFsTransport — shared-filesystem blocks (the multithreaded shuffle
  mode's storage, behind the trait so it is swappable),
- TcpTransport — a length-prefixed binary protocol over sockets:
  HELLO version handshake, FETCH(shuffle, map, reduce) → OK payload /
  MISSING / ERROR, persistent per-peer connections with retry.

Fault model (reference: RapidsShuffleIterator's retry/transaction story):

- every frame carries a CRC32 of its payload, verified on receive — a
  corrupt frame is a typed ``BlockCorruptError`` retried against the
  SAME peer (the bytes exist there; the wire lied);
- a block a peer answers MISSING for is a ``BlockMissingError`` that
  fails over to the next peer immediately (no same-peer retry);
- connect and post-connect I/O both carry conf-driven deadlines
  (`spark.rapids.tpu.shuffle.transport.{connectTimeoutMs,ioTimeoutMs}`)
  so a peer that accepts then goes silent times out instead of
  deadlocking the per-peer connection lock; retries back off with
  jittered exponential delay; a peer that exhausts its retry budget is
  a ``PeerUnreachableError``, reported through ``on_unreachable`` (the
  heartbeat-registry hook) and deprioritized for subsequent fetches so
  one dead peer degrades one block's latency, not the whole read.

Every payload is the framed serializer format (serializer.py), so blocks
are compressed once on publish and device-decoded once on fetch.
"""

from __future__ import annotations

import os
import random
import re
import socket
import socketserver
import struct
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

from .netfault import fault_recv, fault_send, net_injector

_MAGIC = b"RTPU"
#: v2 added the per-frame payload CRC32 to the header
_VERSION = 2

# ops
_HELLO, _FETCH, _OK, _MISSING, _ERROR, _LIST = 1, 2, 3, 4, 5, 6
# windowed-block streaming (reference: WindowedBlockIterator +
# BounceBufferManager — large blocks move in fixed-size staging windows)
_SIZE, _FETCH_AT = 7, 8
# map-output replication (spark.rapids.tpu.shuffle.replicas): the map
# side PUTs a published piece onto K peers so a dead primary's blocks
# are served from a replica instead of recomputed from lineage, and
# REMOVEs them at end-of-query cleanup so replicas don't accumulate in
# peer stores for the life of the peer process
_PUT, _REMOVE = 9, 10

#: default staging window for large-block fetches (one bounce buffer)
DEFAULT_WINDOW_BYTES = 4 << 20


class TransportError(RuntimeError):
    pass


class BlockMissingError(TransportError):
    """The asked peer does not hold the block — fail over to other
    peers; retrying the same peer cannot help (reference: the
    BlockNotFound transaction status)."""


class BlockCorruptError(TransportError):
    """A frame failed its checksum — the peer holds the bytes but the
    wire (or a spill tier) damaged them; retry against the SAME peer."""


class PeerUnreachableError(TransportError):
    """Connect/transact with a peer kept failing past the retry budget —
    report to the heartbeat registry and fail over (reference: the
    executor-death story behind RapidsShuffleHeartbeatManager)."""


class BlockId(Tuple):
    """(shuffle_id, map_id, reduce_id)"""


# ---------------------------------------------------------------------------
# transport metrics (reference: the shuffle fetch/retry SQLMetrics the
# RapidsShuffleIterator posts; rolled into Session.metrics() like the
# retry framework's counters)
# ---------------------------------------------------------------------------

class TransportMetrics:
    """Process-wide fetch-retry counters; sessions report deltas."""

    def __init__(self):
        self._lock = threading.Lock()
        self.fetch_retry_count = 0
        self.fetch_backoff_time_ns = 0
        self.corrupt_frame_count = 0
        self.peer_failover_count = 0

    def note_retry(self) -> None:
        with self._lock:
            self.fetch_retry_count += 1

    def note_backoff(self, ns: int) -> None:
        with self._lock:
            self.fetch_backoff_time_ns += int(ns)

    def note_corrupt(self) -> None:
        with self._lock:
            self.corrupt_frame_count += 1

    def note_failover(self) -> None:
        with self._lock:
            self.peer_failover_count += 1

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {
                "fetchRetryCount": self.fetch_retry_count,
                "fetchBackoffTime": self.fetch_backoff_time_ns,
                "corruptFrameCount": self.corrupt_frame_count,
                "peerFailoverCount": self.peer_failover_count,
            }


_METRICS = TransportMetrics()


def transport_metrics() -> TransportMetrics:
    return _METRICS


class ShuffleTransport:
    """The RapidsShuffleTransport role: publish local blocks, fetch any
    block (local or remote)."""

    def publish(self, shuffle_id: int, map_id: int, reduce_id: int,
                payload: bytes) -> None:
        raise NotImplementedError

    def fetch(self, shuffle_id: int, map_id: int, reduce_id: int) -> bytes:
        raise NotImplementedError

    def list_blocks(self, shuffle_id: int, reduce_id: int
                    ) -> List[Tuple[int, int, int]]:
        """All published (shuffle, map, reduce) blocks for a reducer,
        including remote peers' blocks."""
        raise NotImplementedError

    def fetch_many(self, ids, max_in_flight: int = 4):
        """Yield (block_id, bytes) for many blocks; subclasses with a
        wire pipeline overlap the fetches."""
        for b in ids:
            yield b, self.fetch(*b)

    def replicate(self, shuffle_id: int, map_id: int, reduce_id: int,
                  payload: bytes, k: int) -> int:
        """Write a published block to up to ``k`` peers; returns how many
        replicas landed. Base/shared-filesystem transports are already
        readable by every peer — nothing to do."""
        return 0

    def remove_shuffle(self, shuffle_id: int) -> None:
        """Drop every local block of one shuffle (end-of-query cleanup)."""
        pass

    def close(self) -> None:
        pass


#: strict block filename shape; anything else in the root is a bug or
#: corruption, never silently skipped
_BLOCK_FILE_RE = re.compile(r"s(\d+)-m(\d+)-r(\d+)\.rtpu\Z")


class LocalFsTransport(ShuffleTransport):
    """Shared-directory blocks (works across processes on one host or any
    shared filesystem — the reference's fallback shuffle storage)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, s: int, m: int, r: int) -> str:
        if s < 0 or m < 0 or r < 0:
            # a negative id would embed an extra '-' in the filename and
            # make it unparseable on the list side
            raise TransportError(f"invalid block id s{s}-m{m}-r{r}")
        return os.path.join(self.root, f"s{s}-m{m}-r{r}.rtpu")

    def publish(self, s: int, m: int, r: int, payload: bytes) -> None:
        tmp = self._path(s, m, r) + ".tmp"
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, self._path(s, m, r))    # atomic publish

    def fetch(self, s: int, m: int, r: int) -> bytes:
        try:
            with open(self._path(s, m, r), "rb") as f:
                return f.read()
        except FileNotFoundError:
            raise BlockMissingError(f"missing block s{s}-m{m}-r{r}")

    def list_blocks(self, s: int, r: int):
        """Strictly parsed directory listing: every ``*.rtpu`` file must
        match the block filename shape exactly — a malformed name (e.g.
        an id that itself contained ``-``) raises instead of being
        silently skipped, which would silently drop its rows.
        ``*.tmp`` staging files from in-flight publishes are ignored."""
        out = []
        for name in os.listdir(self.root):
            if not name.endswith(".rtpu"):
                continue
            match = _BLOCK_FILE_RE.fullmatch(name)
            if match is None:
                raise TransportError(
                    f"malformed block file {name!r} in {self.root}")
            fs, fm, fr = (int(g) for g in match.groups())
            if fs == s and fr == r:
                out.append((fs, fm, fr))
        return sorted(out)

    def remove_shuffle(self, s: int) -> None:
        for name in os.listdir(self.root):
            if name.startswith(f"s{s}-"):
                try:
                    os.remove(os.path.join(self.root, name))
                except OSError:  # net-ok: concurrent cleanup, best effort
                    pass

    def close(self) -> None:
        import shutil
        shutil.rmtree(self.root, ignore_errors=True)


# ---------------------------------------------------------------------------
# TCP transport
# ---------------------------------------------------------------------------

def _crc(payload) -> int:
    return zlib.crc32(payload) & 0xFFFFFFFF


def _send_frame(sock: socket.socket, op: int, payload: bytes,
                site: Optional[str] = None) -> None:
    """Encode ``magic | op u8 | len u32 | crc32 u32 | payload`` and send.
    ``site`` names a CLIENT-side call for the fault injector (server
    replies pass None: the client seam already observes every way a
    server can die, and injecting on both sides of one transaction would
    make every-1 schedules non-convergent)."""
    frame = _MAGIC + struct.pack("<BII", op, len(payload),
                                 _crc(payload)) + payload
    if site is not None and net_injector().enabled:
        frame = fault_send(sock, frame, site)
    sock.sendall(frame)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise TransportError("peer closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)


def _recv_frame(sock: socket.socket,
                site: Optional[str] = None) -> Tuple[int, bytes]:
    head = _recv_exact(sock, 13)
    if head[:4] != _MAGIC:
        raise TransportError("bad magic")
    op, ln, crc = struct.unpack("<BII", head[4:])
    payload = _recv_exact(sock, ln)
    if site is not None and net_injector().enabled:
        payload = fault_recv(sock, payload, site)
    if _crc(payload) != crc:
        _METRICS.note_corrupt()
        raise BlockCorruptError(
            f"frame checksum mismatch (op {op}, {ln} bytes)")
    return op, payload


class _BlockServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        store: "TcpTransport" = self.server.transport   # type: ignore
        try:
            op, payload = _recv_frame(self.request)
            if op != _HELLO or struct.unpack("<I", payload)[0] != _VERSION:
                _send_frame(self.request, _ERROR, b"version mismatch")
                return
            _send_frame(self.request, _HELLO, struct.pack("<I", _VERSION))
            while True:
                op, payload = _recv_frame(self.request)
                if op == _LIST:
                    s, r = struct.unpack("<qq", payload)
                    maps = [m for (_, m, _) in
                            store.local_blocks(s, r)]
                    _send_frame(self.request, _OK,
                                struct.pack(f"<{len(maps)}q", *maps))
                    continue
                if op == _SIZE:
                    s, m, r = struct.unpack("<qqq", payload)
                    blk = store._resolve(s, m, r)
                    if blk is None:
                        _send_frame(self.request, _MISSING, b"")
                    else:
                        _send_frame(self.request, _OK,
                                    struct.pack("<q", len(blk)))
                    continue
                if op == _PUT:
                    # replica write: a peer pushes one of ITS published
                    # blocks here so this executor can serve it after
                    # the primary dies (conf-gated on the writing side)
                    s, m, r = struct.unpack_from("<qqq", payload)
                    store.publish(s, m, r, payload[24:])
                    _send_frame(self.request, _OK, b"")
                    continue
                if op == _REMOVE:
                    # end-of-query replica cleanup from the owner
                    (s,) = struct.unpack("<q", payload)
                    store.remove_shuffle(s)
                    _send_frame(self.request, _OK, b"")
                    continue
                if op == _FETCH_AT:
                    s, m, r, off, ln = struct.unpack("<qqqqq", payload)
                    blk = store._resolve(s, m, r)
                    if blk is None or off < 0 or off + ln > len(blk):
                        _send_frame(self.request, _MISSING, b"")
                    else:
                        _send_frame(self.request, _OK, blk[off:off + ln])
                    continue
                if op != _FETCH:
                    _send_frame(self.request, _ERROR, b"bad op")
                    return
                s, m, r = struct.unpack("<qqq", payload)
                blk = store._resolve(s, m, r)
                if blk is None:
                    _send_frame(self.request, _MISSING, b"")
                else:
                    _send_frame(self.request, _OK, blk)
        except (TransportError, ConnectionError, OSError):
            # net-ok: server side of a broken/corrupt connection — the
            # teardown IS the reply; the client's retry loop reconnects
            return


class TcpTransport(ShuffleTransport):
    """Framed TCP block server + fetch clients.

    Transactions mirror the reference's request/response shape
    (RapidsShuffleTransport's Transaction + BlockIds): one HELLO
    handshake per connection, then FETCH transactions. ``peers`` maps
    executor id → (host, port); blocks published locally are served to
    any peer."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 peers: Optional[Dict[int, Tuple[str, int]]] = None,
                 retries: int = 3, liveness=None, peer_source=None,
                 window_bytes: int = DEFAULT_WINDOW_BYTES,
                 connect_timeout_s: float = 30.0,
                 io_timeout_s: Optional[float] = 30.0,
                 backoff_base_ms: float = 10.0,
                 backoff_max_ms: float = 1000.0,
                 on_unreachable=None,
                 suspect_ttl_s: float = 30.0):
        self._local: Dict[Tuple[int, int, int], bytes] = {}
        #: staging window for large-block fetches (the bounce-buffer
        #: size); blocks above it stream as _FETCH_AT range reads
        self.window_bytes = max(64 << 10, window_bytes)
        # persistent per-peer connections (reference: UCX keeps endpoints
        # alive; connection-per-request was the r4 design's weakness)
        self._conns: Dict[Tuple[str, int], socket.socket] = {}
        self._conn_locks: Dict[Tuple[str, int], threading.Lock] = {}
        self._conns_guard = threading.Lock()
        #: small FIFO cache of lazily-resolved blocks so a windowed read
        #: does not re-serialize the device batch per window — sized for
        #: several INTERLEAVED readers (a single slot would thrash when
        #: two reducers stream two large blocks concurrently)
        self._resolved_cache: Dict[Tuple[int, int, int], bytes] = {}
        self._resolved_cache_slots = 8
        #: shuffle_id -> peer addrs holding replicas we wrote (_PUT);
        #: remove_shuffle sends them a best-effort _REMOVE
        self._replicated: Dict[int, set] = {}
        self._index: Dict[Tuple[int, int], List[Tuple[int, int, int]]] = {}
        #: optional (s, m, r) -> bytes|None hook serving LAZY blocks whose
        #: payload lives elsewhere (the device-resident shuffle cache)
        self.resolver = None
        self.peers = dict(peers or {})
        self.retries = max(int(retries), 1)
        #: conf-driven deadlines (transport.{connectTimeoutMs,ioTimeoutMs})
        self.connect_timeout_s = connect_timeout_s
        self.io_timeout_s = io_timeout_s if io_timeout_s else None
        #: jittered exponential backoff between retry attempts
        self.backoff_base_s = max(backoff_base_ms, 0.0) / 1000.0
        self.backoff_max_s = max(backoff_max_ms, 0.0) / 1000.0
        #: peer-id hook fired when a peer exhausts its retry budget —
        #: normally ExecutorRuntime.mark_unreachable, so the heartbeat
        #: registry stops listing the peer as live
        self.on_unreachable = on_unreachable
        #: peers that recently proved unreachable are tried LAST for the
        #: ttl, so one dead peer taxes only the blocks it exclusively
        #: owns instead of every fetch in the read
        self.suspect_ttl_s = suspect_ttl_s
        self._suspects: Dict[Tuple[str, int], float] = {}
        # liveness: () -> iterable of live peer ids, normally the driver
        # heartbeat registry's live_executors (reference:
        # RapidsShuffleHeartbeatManager feeding UCX endpoint setup).
        # Peers missing from it are skipped WITHOUT paying a socket
        # timeout; None = treat every configured peer as live.
        self.liveness = liveness
        # peer_source: () -> {id: (host, port)} — DYNAMIC discovery
        # (RegistryClient.peers); merged over the static table each
        # listing, so executors that join after this transport started
        # are still consulted (reference: heartbeat-driven endpoint
        # table updates)
        self.peer_source = peer_source
        self._server = _BlockServer((host, port), _Handler)
        self._server.transport = self       # type: ignore
        self.address = self._server.server_address
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        self._lock = threading.Lock()

    # ---- local publication ----
    def publish(self, s: int, m: int, r: int, payload: bytes) -> None:
        with self._lock:
            self._local[(s, m, r)] = payload
            self._index.setdefault((s, r), []).append((s, m, r))

    def publish_lazy(self, s: int, m: int, r: int) -> None:
        """Register a block whose bytes the ``resolver`` produces on
        demand (device-resident until fetched)."""
        with self._lock:
            self._index.setdefault((s, r), []).append((s, m, r))

    def local_blocks(self, s: int, r: int):
        with self._lock:
            return sorted(self._index.get((s, r), []))

    def _resolve(self, s: int, m: int, r: int) -> Optional[bytes]:
        """Materialized bytes of a local block: published payload, or the
        lazy resolver's output (cached one slot so windowed range reads
        serialize the device batch once)."""
        blk = self._local.get((s, m, r))
        if blk is not None:
            return blk
        if self.resolver is None:
            return None
        with self._lock:
            blk = self._resolved_cache.get((s, m, r))
        if blk is not None:
            return blk
        blk = self.resolver(s, m, r)
        if blk is not None:
            with self._lock:
                while len(self._resolved_cache) >= \
                        self._resolved_cache_slots:
                    self._resolved_cache.pop(
                        next(iter(self._resolved_cache)))
                self._resolved_cache[(s, m, r)] = blk
        return blk

    def _live_peers(self) -> Dict:
        peers = dict(self.peers)
        if self.peer_source is not None:
            peers.update(self.peer_source())
        if self.liveness is None:
            return peers
        # ids compare as strings: the heartbeat registry normalizes its
        # keys, while peer tables may key on int executor ids
        live = {str(x) for x in self.liveness()}
        return {pid: a for pid, a in peers.items() if str(pid) in live}

    def _ordered_peers(self) -> List[Tuple[object, Tuple[str, int]]]:
        """Live peers, recently-unreachable suspects LAST (stable order
        otherwise) — healthy peers answer first, so a dead peer's
        timeout is only paid for blocks no healthy peer holds."""
        peers = list(self._live_peers().items())
        now = time.time()
        with self._conns_guard:
            suspects = {a for a, t in self._suspects.items()
                        if now - t < self.suspect_ttl_s}
        peers.sort(key=lambda kv: kv[1] in suspects)
        return peers

    def _note_unreachable(self, peer_id, addr) -> None:
        with self._conns_guard:
            self._suspects[addr] = time.time()
        if self.on_unreachable is not None:
            try:
                self.on_unreachable(peer_id)
            except Exception:
                # robust-ok: reporting must never mask the fetch error
                pass

    def _note_reachable(self, addr) -> None:
        """A completed transaction proves the peer alive: clear it from
        the suspect set IMMEDIATELY so a recovered peer returns to
        normal fetch ordering, instead of being tried last (and eating
        misdirected first-fetch latency) until suspect_ttl_s ages the
        entry out."""
        with self._conns_guard:
            self._suspects.pop(addr, None)

    def list_blocks(self, s: int, r: int):
        """Local blocks UNION every LIVE peer's blocks (the shuffle
        reader must see remote map outputs); a live-but-unreachable peer
        raises — a silent partial listing would silently drop its rows.
        Peers the heartbeat registry declares dead are excluded up front
        (their tasks get rescheduled by the driver, the reference's
        executor-death story)."""
        out = set(self.local_blocks(s, r))
        for peer_id, addr in self._live_peers().items():
            try:
                maps = self._retrying(addr, self._list_from, s, r)
            except PeerUnreachableError:
                self._note_unreachable(peer_id, addr)
                raise
            self._note_reachable(addr)
            out.update((s, m, r) for m in maps)
        return sorted(out)

    def remove_shuffle(self, s: int) -> None:
        with self._lock:
            for key in [k for k in self._local if k[0] == s]:
                del self._local[key]
            for key in [k for k in self._index if k[0] == s]:
                del self._index[key]
            replica_holders = self._replicated.pop(s, ())
        for addr in replica_holders:
            # best effort: a peer that died keeps nothing anyway, and
            # cleanup must never fail the query's teardown
            try:
                op, resp = self._transact(addr, _REMOVE,
                                          struct.pack("<q", s))
                if op != _OK:
                    raise TransportError(f"remove failed: {resp!r}")
            except (TransportError, ConnectionError, OSError):
                # net-ok: best-effort replica cleanup on teardown
                pass

    # ---- retry policy -------------------------------------------------

    def _backoff(self, attempt: int) -> None:
        """Jittered exponential backoff (reference: the shuffle fetch
        retry wait) — full jitter in [base/2, base] * 2^(attempt-1)."""
        if self.backoff_base_s <= 0:
            return
        from ..trace import span as _trace_span
        delay = min(self.backoff_base_s * (1 << min(attempt - 1, 10)),
                    self.backoff_max_s)
        delay *= 0.5 + random.random() * 0.5
        t0 = time.perf_counter_ns()
        with _trace_span("transport.backoff", kind="transport",
                         attempt=attempt):
            time.sleep(delay)
        _METRICS.note_backoff(time.perf_counter_ns() - t0)

    def _retrying(self, addr, fn, *args):
        """Typed retry loop for one peer transaction:

        - BlockMissingError propagates immediately — the caller fails
          over to the next peer; retrying the same peer cannot help;
        - BlockCorruptError retries the SAME peer (the bytes are there,
          the wire lied) and stays typed when retries run out;
        - everything else (reset, timeout, mid-frame close) retries with
          jittered backoff and becomes PeerUnreachableError when the
          budget is exhausted."""
        last: Optional[Exception] = None
        corrupt_last = False
        for attempt in range(self.retries):
            if attempt:
                self._backoff(attempt)
            try:
                if attempt == 0:
                    return fn(addr, *args)
                # re-attempts never start NEW injected faults — recovery
                # must converge (mirror of the OOM injector's contract)
                with net_injector().suppressed():
                    return fn(addr, *args)
            except BlockMissingError:
                raise
            except BlockCorruptError as ex:
                last, corrupt_last = ex, True
                _METRICS.note_retry()
            except (TransportError, ConnectionError, OSError) as ex:
                # net-ok: counted + retried; budget exhaustion re-raises
                # typed (PeerUnreachableError) below the loop
                last, corrupt_last = ex, False
                _METRICS.note_retry()
        if corrupt_last:
            raise BlockCorruptError(
                f"peer {addr}: corrupt frames through "
                f"{self.retries} attempts: {last}")
        raise PeerUnreachableError(
            f"peer {addr} unreachable after {self.retries} "
            f"attempts: {last}")

    # ---- fetch (local fast path, else ask each peer) ----
    def fetch(self, s: int, m: int, r: int) -> bytes:
        blk = self._local.get((s, m, r))
        if blk is not None:
            return blk
        from ..trace import span as _trace_span
        missing: List[Exception] = []
        failed: List[Exception] = []
        with _trace_span("transport.fetch", kind="transport",
                         block=f"s{s}-m{m}-r{r}") as fsp:
            for peer_id, addr in self._ordered_peers():
                # per-peer sub-span: a failover shows as one failed peer
                # attempt next to the successful one, with the backoff
                # sleeps (transport.backoff) nested inside
                with _trace_span("transport.peer", kind="transport",
                                 peer=f"{addr[0]}:{addr[1]}") as psp:
                    try:
                        data = self._retrying(addr, self._fetch_from,
                                              s, m, r)
                        # a suspect that served the block is
                        # rehabilitated NOW — later fetches order it
                        # normally again instead of waiting out
                        # suspect_ttl_s
                        self._note_reachable(addr)
                        if psp is not None:
                            psp.attrs["outcome"] = "served"
                            psp.attrs["bytes"] = len(data)
                        if fsp is not None:
                            fsp.attrs["bytes"] = len(data)
                        return data
                    except BlockMissingError as ex:
                        # a MISSING answer is still a completed round
                        # trip: the peer is alive, just not holding
                        # this block
                        self._note_reachable(addr)
                        missing.append(ex)
                        if psp is not None:
                            psp.attrs["outcome"] = "missing"
                    except PeerUnreachableError as ex:
                        self._note_unreachable(peer_id, addr)
                        _METRICS.note_failover()
                        failed.append(ex)
                        if psp is not None:
                            psp.attrs["outcome"] = "unreachable"
                    except TransportError as ex:  # corrupt past budget
                        _METRICS.note_failover()
                        failed.append(ex)
                        if psp is not None:
                            psp.attrs["outcome"] = "corrupt"
        if failed:
            if all(isinstance(ex, BlockCorruptError) for ex in failed):
                # every serving peer is reachable but the bytes keep
                # failing their CRC: that is a data-integrity problem,
                # not a reachability one — keep the taxonomy honest
                raise BlockCorruptError(
                    f"block s{s}-m{m}-r{r} corrupt on every serving "
                    f"peer (last: {failed[-1]})")
            # the block may live on a peer we could not reach — surface
            # the reachability failure, not a bogus "missing"
            raise PeerUnreachableError(
                f"block s{s}-m{m}-r{r} unresolved: {len(failed)} peer "
                f"fetch(es) failed (last: {failed[-1]}), missing on "
                f"{len(missing)} peer(s)")
        raise BlockMissingError(
            f"block s{s}-m{m}-r{r} not found on any peer"
            + (f" (last: {missing[-1]})" if missing else ""))

    # ---- persistent per-peer connections --------------------------------
    def _conn_of(self, addr):
        """(socket, lock) for ``addr``; connects + handshakes once and
        keeps the connection for the transport's lifetime (the reference
        keeps UCX endpoints alive the same way). The connect deadline
        covers the handshake; after it the socket switches to the I/O
        deadline so no later recv can block forever."""
        with self._conns_guard:
            sock = self._conns.get(addr)
            lock = self._conn_locks.setdefault(addr, threading.Lock())
        if sock is not None:
            return sock, lock
        sock = socket.create_connection(addr,
                                        timeout=self.connect_timeout_s)
        try:
            _send_frame(sock, _HELLO, struct.pack("<I", _VERSION),
                        site="hello.send")
            op, payload = _recv_frame(sock, site="hello.recv")
            if op != _HELLO:
                raise TransportError(f"handshake failed: {payload!r}")
            sock.settimeout(self.io_timeout_s)
        except BaseException:
            sock.close()
            raise
        with self._conns_guard:
            # lost the race: keep the winner's connection
            existing = self._conns.get(addr)
            if existing is not None:
                sock.close()
                return existing, lock
            self._conns[addr] = sock
        return sock, lock

    def _drop_conn(self, addr, sock) -> None:
        with self._conns_guard:
            if self._conns.get(addr) is sock:
                del self._conns[addr]
        try:
            sock.close()
        except OSError:  # net-ok: already-dead socket, teardown path
            pass

    def _transact(self, addr, op: int, payload: bytes):
        """One request/response on the persistent connection; a transport
        failure drops the connection so retries reconnect. The per-peer
        lock is held across one bounded (io-deadline) round trip — a
        hung peer times out instead of deadlocking every fetching
        thread behind the lock."""
        sock, lock = self._conn_of(addr)
        try:
            with lock:
                _send_frame(sock, op, payload, site="transact.send")
                return _recv_frame(sock, site="transact.recv")
        except (TransportError, ConnectionError, OSError):
            # includes BlockCorruptError: after a corrupt frame the
            # stream may be desynced — reconnect before the retry
            self._drop_conn(addr, sock)
            raise

    def _list_from(self, addr, s: int, r: int) -> List[int]:
        op, payload = self._transact(addr, _LIST,
                                     struct.pack("<qq", s, r))
        if op != _OK:
            raise TransportError(f"list failed: {payload!r}")
        k = len(payload) // 8
        return list(struct.unpack(f"<{k}q", payload))

    def _fetch_from(self, addr, s: int, m: int, r: int) -> bytes:
        # size probe decides plain vs windowed streaming
        op, payload = self._transact(addr, _SIZE,
                                     struct.pack("<qqq", s, m, r))
        if op == _MISSING:
            raise BlockMissingError("missing block")
        if op != _OK:
            raise TransportError(f"peer error: {payload!r}")
        (total,) = struct.unpack("<q", payload)
        if total <= self.window_bytes:
            op, payload = self._transact(addr, _FETCH,
                                         struct.pack("<qqq", s, m, r))
            if op == _OK:
                return payload
            if op == _MISSING:
                raise BlockMissingError("missing block")
            raise TransportError(f"peer error: {payload!r}")
        # windowed streaming: fixed-size range reads into one buffer
        # (WindowedBlockIterator over bounce-buffer-sized steps)
        buf = bytearray(total)
        for off in range(0, total, self.window_bytes):
            ln = min(self.window_bytes, total - off)
            op, payload = self._transact(
                addr, _FETCH_AT, struct.pack("<qqqqq", s, m, r, off, ln))
            if op != _OK or len(payload) != ln:
                raise TransportError(
                    f"windowed read failed at {off} ({op})")
            buf[off:off + ln] = payload
        return bytes(buf)

    # ---- replication (spark.rapids.tpu.shuffle.replicas) ----------------

    def _put_to(self, addr, s: int, m: int, r: int,
                payload: bytes) -> None:
        op, resp = self._transact(
            addr, _PUT, struct.pack("<qqq", s, m, r) + payload)
        if op != _OK:
            raise TransportError(f"replica put failed: {resp!r}")

    def replicate(self, s: int, m: int, r: int, payload: bytes,
                  k: int) -> int:
        """Write one published block to up to ``k`` live peers (healthy
        peers first — a suspect makes a poor replica target). Best
        effort PER PEER: a replica that cannot be written is skipped
        and the next peer tried — replication narrows the recovery path
        to a failover, it must never widen a publish into a query
        failure (lineage recompute remains the floor). Returns the
        number of replicas actually written; replicaBytes counts them
        for Session.metrics()/serving_stats()."""
        if k <= 0:
            return 0
        from .lineage import metrics as lineage_metrics
        # memoize the ordered peer list briefly: replicate runs once per
        # published PIECE on the writer hot path, and _ordered_peers
        # consults peer_source — in registry mode a framed-TCP 'list'
        # RPC per call. The table changes on heartbeat timescales, so a
        # 1-second memo drops B×P discovery round trips per shuffle to
        # ~one without serving a stale view longer than a heartbeat.
        now = time.time()
        ts, peers = getattr(self, "_replicate_peers_memo", (0.0, None))
        if peers is None or now - ts > 1.0:
            peers = self._ordered_peers()
            self._replicate_peers_memo = (now, peers)
        from ..trace import span as _trace_span
        written = 0
        for peer_id, addr in peers:
            if written >= k:
                break
            with _trace_span("transport.replicate", kind="transport",
                             peer=f"{addr[0]}:{addr[1]}",
                             bytes=len(payload)):
                try:
                    self._retrying(addr, self._put_to, s, m, r, payload)
                except PeerUnreachableError:
                    self._note_unreachable(peer_id, addr)
                    continue
                except TransportError:
                    continue
            self._note_reachable(addr)
            with self._lock:
                # remember who holds replicas of this shuffle, so
                # remove_shuffle can clean them off the peers — replica
                # bytes must not outlive the query in peer processes
                self._replicated.setdefault(s, set()).add(addr)
            lineage_metrics().note_replica(len(payload))
            written += 1
        return written

    def fetch_many(self, ids, max_in_flight: int = 4):
        """Pipelined fetch of many blocks: yields (id, bytes) in input
        order while later fetches proceed in the background, so device
        decode overlaps the wire (the reference's windowed pending-fetch
        pipeline). Different peers progress in parallel; one peer's
        frames serialize on its connection. Failover is PER BLOCK
        (each fetch() retries/fails over independently, and the first
        unreachable verdict deprioritizes that peer for the rest of the
        read) — one dead peer degrades the latency of the blocks only
        it held, instead of aborting the whole exchange read."""
        from ..io.source import bounded_map, reader_pool
        from ..trace import call_attached, capture
        pool = reader_pool(max(2, max_in_flight))
        # pool workers inherit the consuming thread's trace context so
        # per-peer fetch spans land in the query's tree (token is None —
        # and the shim free — when tracing is off)
        tok = capture()
        yield from bounded_map(pool, list(ids),
                               lambda b: call_attached(
                                   tok, self.fetch, *b), max_in_flight,
                               force_parallel=True)

    def close(self) -> None:
        with self._conns_guard:
            conns = list(self._conns.values())
            self._conns.clear()
        for sock in conns:
            try:
                sock.close()
            except OSError:  # net-ok: teardown, socket may already be dead
                pass
        self._server.shutdown()
        self._server.server_close()
        self._local.clear()
