"""Shuffle manager façade: one mode-selection point for the exchange
data plane.

Reference: RapidsShuffleInternalManagerBase.scala:1018 + the per-version
RapidsShuffleManager façades — Spark asks ONE manager object for writers/
readers and the manager proxies to the configured implementation (default
sort-shuffle with the GPU serializer, MULTITHREADED thread pools, UCX
device-resident transport). Here the planner asks the manager for an
exchange exec; the ICI mode additionally marks the plan for whole-stage
mesh lowering at the session layer (collectives replace the exchange
entirely — the device-resident shuffle of SURVEY §2.10 re-shaped
collective-first)."""

from __future__ import annotations

from typing import Optional

from ..config import (ADAPTIVE_ENABLED, ADAPTIVE_TARGET_ROWS, SHUFFLE_MODE,
                      RapidsTpuConf)
from ..exec.base import Exec
from .exchange import ShuffleExchangeExec
from .partitioning import Partitioning


class ShuffleManager:
    """Mode façade; construct through get_shuffle_manager."""

    #: modes, mirroring the reference's three shuffle managers
    DEFAULT = "DEFAULT"
    MULTITHREADED = "MULTITHREADED"
    ICI = "ICI"
    CACHED = "CACHED"

    def __init__(self, conf: RapidsTpuConf):
        self.conf = conf
        self.mode = str(conf.get(SHUFFLE_MODE.key)).upper()
        if self.mode not in (self.DEFAULT, self.MULTITHREADED, self.ICI,
                             self.CACHED):
            raise ValueError(
                f"spark.rapids.tpu.shuffle.mode must be DEFAULT, "
                f"MULTITHREADED, ICI or CACHED, got {self.mode!r}")
        # validate the serialized-batch codec conf (none/lz4/zstd) HERE,
        # not silently downstream; the value rides each exchange (no
        # process-global mutation — two sessions with different codecs
        # coexist, frames self-describe via per-column tags)
        from ..config import SHUFFLE_COMPRESSION
        from ..utils import native
        self.codec = str(conf.get(SHUFFLE_COMPRESSION.key))
        native.validate_codec(self.codec)

    def create_exchange(self, partitioning: Partitioning,
                        child: Exec) -> Exec:
        """The exchange exec for the configured mode (the reference's
        getWriter/getReader moment). ICI mode still plants the
        host-mediated exchange — the session's mesh lowering replaces the
        whole pipeline with one SPMD program when the plan shape allows,
        and the host exchange is the fallback for shapes it cannot fuse."""
        if self.mode == self.MULTITHREADED:
            from ..config import (SHUFFLE_LINEAGE_ENABLED,
                                  SHUFFLE_MT_MAX_BYTES_IN_FLIGHT,
                                  SHUFFLE_MT_WRITER_THREADS,
                                  SHUFFLE_REPLICAS,
                                  TRANSPORT_MAX_IN_FLIGHT)
            from .multithreaded import MultithreadedShuffleExchangeExec
            from ..config import SHUFFLE_MT_READER_THREADS
            return MultithreadedShuffleExchangeExec(
                partitioning, child,
                num_threads=int(self.conf.get(
                    SHUFFLE_MT_WRITER_THREADS.key)),
                reader_threads=int(self.conf.get(
                    SHUFFLE_MT_READER_THREADS.key)),
                max_in_flight_fetches=int(self.conf.get(
                    TRANSPORT_MAX_IN_FLIGHT.key)),
                max_bytes_in_flight=int(self.conf.get(
                    SHUFFLE_MT_MAX_BYTES_IN_FLIGHT.key)),
                codec=self.codec,
                replicas=int(self.conf.get(SHUFFLE_REPLICAS.key)),
                lineage_enabled=bool(self.conf.get(
                    SHUFFLE_LINEAGE_ENABLED.key)))
        if self.mode == self.CACHED:
            # device-resident blocks in the spillable cache, served P2P
            # (the reference's UCX cached mode)
            from .exchange import CachedShuffleExchangeExec
            return CachedShuffleExchangeExec(partitioning, child,
                                             conf=self.conf)
        return ShuffleExchangeExec(
            partitioning, child,
            adaptive=self.conf.get(ADAPTIVE_ENABLED.key),
            target_rows=self.conf.get(ADAPTIVE_TARGET_ROWS.key))

    @property
    def wants_mesh_lowering(self) -> bool:
        return self.mode == self.ICI


def get_shuffle_manager(conf: Optional[RapidsTpuConf] = None
                        ) -> ShuffleManager:
    return ShuffleManager(conf or RapidsTpuConf())
