"""Multithreaded file-backed shuffle mode.

Reference: SURVEY.md §2.10 — RapidsShuffleThreadedWriterBase:228 /
ReaderBase:504 (thread-pooled parallel writers/readers over Spark shuffle
files, with BytesInFlightLimiter:574). This is the middle of the three
shuffle modes: rows leave the device once (serialize), land in per-
(mapper, reducer) framed files via the writer pool, and reducers decode
with a reader pool — the shape that scales past one process and feeds the
DCN path, with the in-flight byte limiter bounding host memory.
"""

from __future__ import annotations

import concurrent.futures as cf
import os
import threading
import uuid
from typing import Iterator, List, Optional

import jax

from ..batch import ColumnarBatch, Schema, bucket_capacity
from ..exec.base import Exec, UnaryExec
from ..exec.common import compact, concat_batches
from ..expressions.base import EvalContext
from .partitioning import Partitioning, RangePartitioning
from .serializer import deserialize_batch, serialize_batch


class BytesInFlightLimiter:
    """Bounds serialized bytes buffered across the writer pool
    (reference: BytesInFlightLimiter — backpressure, not a hard error)."""

    def __init__(self, limit: int = 512 << 20):
        self.limit = limit
        self._used = 0
        self._cv = threading.Condition()

    def acquire(self, n: int) -> None:
        with self._cv:
            while self._used + n > self.limit and self._used > 0:
                self._cv.wait()
            self._used += n

    def release(self, n: int) -> None:
        with self._cv:
            self._used -= n
            self._cv.notify_all()


class MultithreadedShuffleExchangeExec(UnaryExec):
    """Shuffle through framed spill files with writer/reader thread pools."""

    def __init__(self, partitioning: Partitioning, child: Exec,
                 shuffle_dir: Optional[str] = None,
                 num_threads: int = 8,
                 reader_threads: Optional[int] = None,
                 max_in_flight_fetches: Optional[int] = None,
                 max_bytes_in_flight: int = 512 << 20,
                 ctx: Optional[EvalContext] = None,
                 transport=None,
                 read_transport=None,
                 codec: Optional[str] = None):
        super().__init__(child, ctx)
        self.partitioning = partitioning.bind(child.output_schema)
        self.shuffle_dir = shuffle_dir or os.path.join(
            "/tmp/rapids_tpu_shuffle", uuid.uuid4().hex)
        self.num_threads = num_threads
        self.reader_threads = reader_threads or num_threads
        #: bound on concurrently outstanding transport fetches
        #: (spark.rapids.tpu.shuffle.transport.maxInFlightFetches)
        self.max_in_flight_fetches = \
            max_in_flight_fetches or self.reader_threads
        self.codec = codec
        self.limiter = BytesInFlightLimiter(max_bytes_in_flight)
        self._written = False
        self._write_lock = threading.Lock()
        # blocks ride a pluggable transport (reference:
        # RapidsShuffleTransport); default = shared-filesystem blocks
        if transport is None:
            from .transport import LocalFsTransport
            transport = LocalFsTransport(self.shuffle_dir)
            self._owns_transport = True
        else:
            self._owns_transport = False
        self.transport = transport
        # cross-process shape: the map side publishes into ``transport``
        # (this executor's block server) while reducers pull through
        # ``read_transport`` — a fetching client whose peer table sees
        # the map side over the wire. Defaults to the same transport
        # (single-process: local fast path).
        self.read_transport = read_transport or transport
        # random 63-bit id: per-process counters COLLIDE when two
        # processes share one transport root (cross-process mode)
        self.shuffle_id = uuid.uuid4().int & ((1 << 63) - 1)

        def slice_kernel(batch, pids, p: int):
            return compact(batch, pids == p)

        self._slice_jit = jax.jit(slice_kernel, static_argnums=2)
        self._pids_jit = jax.jit(
            lambda b: self.partitioning.partition_ids(b, self.ctx))

    @property
    def output_schema(self) -> Schema:
        return self.child.output_schema

    @property
    def num_partitions(self) -> int:
        return self.partitioning.num_partitions

    # ------------------------------------------------------------------
    # write side (map tasks)
    # ------------------------------------------------------------------

    def _write_all(self) -> None:
        with self._write_lock:
            if self._written:
                return
            n = self.num_partitions
            schema = self.output_schema
            pool = cf.ThreadPoolExecutor(self.num_threads,
                                         thread_name_prefix="shuffle-write")
            futures = []
            seq = 0
            for cp in range(self.child.num_partitions):
                for batch in self.child.execute_partition(cp):
                    pids = self._pids_jit(batch)
                    for p in range(n):
                        piece = self._slice_jit(batch, pids, p)
                        if int(piece.num_rows) == 0:
                            continue
                        futures.append(pool.submit(
                            self._write_piece, piece, schema, seq, p))
                        seq += 1
            for f in futures:
                f.result()
            pool.shutdown()
            self._written = True

    def _write_piece(self, piece: ColumnarBatch, schema: Schema,
                     map_id: int, reduce_id: int) -> None:
        data = serialize_batch(piece, schema,
                               self.codec)   # D2H + frame + compress
        self.limiter.acquire(len(data))
        try:
            self.transport.publish(self.shuffle_id, map_id, reduce_id,
                                   data)
        finally:
            self.limiter.release(len(data))

    # ------------------------------------------------------------------
    # read side (reduce tasks)
    # ------------------------------------------------------------------

    def do_execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        self._write_all()
        blocks = self.read_transport.list_blocks(self.shuffle_id, p)
        if not blocks:
            return
        schema = self.output_schema
        # pipelined fetch: decode each block the moment its bytes land
        # while later fetches keep streaming (transport.fetch_many)
        batches = [deserialize_batch(data, schema)
                   for _, data in self.read_transport.fetch_many(
                       blocks,
                       max_in_flight=self.max_in_flight_fetches)]
        total = sum(int(b.num_rows) for b in batches)
        if total == 0:
            return
        if len(batches) == 1:
            yield batches[0]
        else:
            yield concat_batches(batches, bucket_capacity(total))

    def cleanup(self) -> None:
        # always drop this shuffle's blocks; close the transport only if
        # this exec created it (an injected transport may serve peers)
        self.transport.remove_shuffle(self.shuffle_id)
        if self._owns_transport:
            self.transport.close()

    def do_close(self) -> None:
        self.cleanup()
