"""Multithreaded file-backed shuffle mode.

Reference: SURVEY.md §2.10 — RapidsShuffleThreadedWriterBase:228 /
ReaderBase:504 (thread-pooled parallel writers/readers over Spark shuffle
files, with BytesInFlightLimiter:574). This is the middle of the three
shuffle modes: rows leave the device once (serialize), land in per-
(mapper, reducer) framed files via the writer pool, and reducers decode
with a reader pool — the shape that scales past one process and feeds the
DCN path, with the in-flight byte limiter bounding host memory.
"""

from __future__ import annotations

import concurrent.futures as cf
import os
import threading
import uuid
from typing import Iterator, List, Optional

import jax

from ..batch import ColumnarBatch, Schema, bucket_capacity
from ..exec.base import Exec, UnaryExec
from ..exec.common import compact, concat_batches
from ..expressions.base import EvalContext
from .partitioning import Partitioning, RangePartitioning
from .serializer import deserialize_batch, serialize_batch
from .transport import BlockMissingError, PeerUnreachableError


class BytesInFlightLimiter:
    """Bounds serialized bytes buffered across the writer pool
    (reference: BytesInFlightLimiter — backpressure, not a hard error)."""

    def __init__(self, limit: int = 512 << 20):
        self.limit = limit
        self._used = 0
        self._cv = threading.Condition()

    def acquire(self, n: int) -> None:
        with self._cv:
            while self._used + n > self.limit and self._used > 0:
                self._cv.wait()
            self._used += n

    def release(self, n: int) -> None:
        with self._cv:
            self._used -= n
            self._cv.notify_all()


class MultithreadedShuffleExchangeExec(UnaryExec):
    """Shuffle through framed spill files with writer/reader thread pools."""

    def __init__(self, partitioning: Partitioning, child: Exec,
                 shuffle_dir: Optional[str] = None,
                 num_threads: int = 8,
                 reader_threads: Optional[int] = None,
                 max_in_flight_fetches: Optional[int] = None,
                 max_bytes_in_flight: int = 512 << 20,
                 ctx: Optional[EvalContext] = None,
                 transport=None,
                 read_transport=None,
                 codec: Optional[str] = None,
                 replicas: int = 0,
                 lineage_enabled: bool = True,
                 lineage_registry=None):
        super().__init__(child, ctx)
        self.partitioning = partitioning.bind(child.output_schema)
        self.shuffle_dir = shuffle_dir or os.path.join(
            "/tmp/rapids_tpu_shuffle", uuid.uuid4().hex)
        self.num_threads = num_threads
        self.reader_threads = reader_threads or num_threads
        #: bound on concurrently outstanding transport fetches
        #: (spark.rapids.tpu.shuffle.transport.maxInFlightFetches)
        self.max_in_flight_fetches = \
            max_in_flight_fetches or self.reader_threads
        self.codec = codec
        self.limiter = BytesInFlightLimiter(max_bytes_in_flight)
        self._written = False
        self._write_lock = threading.Lock()
        # blocks ride a pluggable transport (reference:
        # RapidsShuffleTransport); default = shared-filesystem blocks
        if transport is None:
            from .transport import LocalFsTransport
            transport = LocalFsTransport(self.shuffle_dir)
            self._owns_transport = True
        else:
            self._owns_transport = False
        self.transport = transport
        # cross-process shape: the map side publishes into ``transport``
        # (this executor's block server) while reducers pull through
        # ``read_transport`` — a fetching client whose peer table sees
        # the map side over the wire. Defaults to the same transport
        # (single-process: local fast path).
        self.read_transport = read_transport or transport
        # random 63-bit id: per-process counters COLLIDE when two
        # processes share one transport root (cross-process mode)
        self.shuffle_id = uuid.uuid4().int & ((1 << 63) - 1)
        #: conf-gated map-output replication (shuffle.replicas): pieces
        #: are pushed to K peers at publish so a dead primary's blocks
        #: are served by failover, with recompute as the floor
        self.replicas = max(int(replicas), 0)
        # lineage (shuffle.lineage.enabled): every map output records
        # its producing fragment so the read side can recompute a lost
        # block deterministically once transport failover is exhausted.
        # The recompute contract: the CHILD must be re-executable (true
        # of the data plane's execs — scans re-read, exchanges re-fetch
        # their still-published blocks).
        if lineage_enabled:
            from .lineage import lineage_registry as _global_registry
            self._lineage = lineage_registry or _global_registry()
        else:
            self._lineage = None

        def slice_kernel(batch, pids, p: int):
            return compact(batch, pids == p)

        self._slice_jit = jax.jit(slice_kernel, static_argnums=2)
        self._pids_jit = jax.jit(
            lambda b: self.partitioning.partition_ids(b, self.ctx))

    @property
    def output_schema(self) -> Schema:
        return self.child.output_schema

    @property
    def num_partitions(self) -> int:
        return self.partitioning.num_partitions

    # ------------------------------------------------------------------
    # write side (map tasks)
    # ------------------------------------------------------------------

    def _write_all(self) -> None:
        from ..trace import call_attached, capture, span
        with self._write_lock:
            if self._written:
                return
            n = self.num_partitions
            schema = self.output_schema
            pool = cf.ThreadPoolExecutor(self.num_threads,
                                         thread_name_prefix="shuffle-write")
            futures = []
            # writer-pool tasks inherit this thread's trace context so
            # their serializer.pack / transport.replicate spans join the
            # query's tree (tok is None — and the shim free — untraced)
            tok = capture()
            # map_id identifies one INPUT BATCH (child partition cp,
            # batch index bi) — the recompute unit: lineage re-executes
            # that fragment ONCE and re-slices every lost reduce
            # partition from it. Per-batch ids keep (map, reduce) keys
            # unique and preserve the read side's sorted concat order.
            if self._lineage is not None:
                # even a zero-batch child marks the shuffle as tracked:
                # an empty shuffle behind a dead peer must read as
                # provably empty, not fail its listing
                self._lineage.register_shuffle(self.shuffle_id)
            with span("shuffle.write", kind="shuffle",
                      shuffleId=self.shuffle_id):
                m = 0
                for cp in range(self.child.num_partitions):
                    bi = 0
                    for batch in self.child.execute_partition(cp):
                        if self._lineage is not None:
                            self._lineage.register_fragment(
                                self.shuffle_id, m,
                                self._make_recompute(cp, bi),
                                input_digest=self._fragment_digest(
                                    cp, bi))
                        pids = self._pids_jit(batch)
                        for p in range(n):
                            piece = self._slice_jit(batch, pids, p)
                            if int(piece.num_rows) == 0:
                                continue
                            futures.append(pool.submit(
                                call_attached, tok, self._write_piece,
                                piece, schema, m, p))
                        m += 1
                        bi += 1
                for f in futures:
                    f.result()
                pool.shutdown()
            self._written = True

    def _fragment_digest(self, cp: int, bi: int) -> str:
        """Input-split digest of one map fragment (the PR-10 fingerprint
        machinery): fragment coordinates + output schema — it names the
        recompute recipe in LineageVerificationError reports, so a
        nondeterministic fragment is identifiable across shuffles and
        plan shapes. The schema leg is hashed once per exchange, not
        per input batch (the registration runs on the write hot path)."""
        from ..plan.plancache import _hash
        sig = getattr(self, "_schema_sig", None)
        if sig is None:
            sig = _hash([[getattr(f, "name", str(i)), str(f.dtype)]
                         for i, f in enumerate(self.output_schema)])
            self._schema_sig = sig
        return f"{sig}:s{self.shuffle_id}:f{cp}.{bi}"

    def _make_recompute(self, cp: int, bi: int):
        """Deterministic recompute of lost blocks: re-execute the child
        partition stream to batch ``bi`` ONCE, slice every asked reduce
        partition from it with the SAME jitted kernels, serialize with
        the same codec — bit-for-bit the published bytes (hash
        partitioning and the frame format are both deterministic; the
        registry verifies the publish-time digests to prove it)."""
        schema = self.output_schema

        def recompute(reduce_ids):
            for i, batch in enumerate(self.child.execute_partition(cp)):
                if i == bi:
                    pids = self._pids_jit(batch)
                    out = {}
                    for r in reduce_ids:
                        piece = self._slice_jit(batch, pids, r)
                        out[r] = None if int(piece.num_rows) == 0 else \
                            serialize_batch(piece, schema, self.codec)
                    return out
            return {}

        return recompute

    def _write_piece(self, piece: ColumnarBatch, schema: Schema,
                     map_id: int, reduce_id: int) -> None:
        data = serialize_batch(piece, schema,
                               self.codec)   # D2H + frame + compress
        self.limiter.acquire(len(data))
        try:
            if self._lineage is not None:
                # digest BEFORE publish: a peer death any time after the
                # block becomes fetchable must find its lineage complete
                self._lineage.note_block(self.shuffle_id, map_id,
                                         reduce_id, data)
            self.transport.publish(self.shuffle_id, map_id, reduce_id,
                                   data)
            if self.replicas > 0:
                self.transport.replicate(self.shuffle_id, map_id,
                                         reduce_id, data, self.replicas)
        finally:
            self.limiter.release(len(data))

    # ------------------------------------------------------------------
    # read side (reduce tasks)
    # ------------------------------------------------------------------

    def _reduce_blocks(self, p: int):
        """Block listing for one reducer: the transport's live listing
        UNIONED with lineage's authoritative set. The union is what
        makes a dead peer a recovery event instead of silent row loss —
        blocks the heartbeat registry stopped listing (dead executor)
        still surface here and get recomputed; and when the ONLY serving
        peer is unreachable, the lineage listing stands in for the raise
        the strict transport listing would otherwise be right to make."""
        lineage_blocks = [] if self._lineage is None else \
            self._lineage.blocks(self.shuffle_id, p)
        try:
            listed = self.read_transport.list_blocks(self.shuffle_id, p)
        except (BlockMissingError, PeerUnreachableError):
            if self._lineage is None or \
                    not self._lineage.knows_shuffle(self.shuffle_id):
                raise
            # lineage registered this shuffle: its listing is
            # authoritative even when EMPTY (a reducer that genuinely
            # received no rows) — the strict transport listing's raise
            # is survivable because no row can be silently dropped
            listed = []
        return sorted(set(listed) | set(lineage_blocks))

    def do_execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        self._write_all()
        blocks = self._reduce_blocks(p)
        if not blocks:
            return
        schema = self.output_schema
        # pipelined fetch: decode each block the moment its bytes land
        # while later fetches keep streaming. With lineage on, a fetch
        # that exhausts failover recomputes the lost partition (riding
        # with_retry) and resumes bit-for-bit instead of raising; the
        # server's cancel flag (stop()/watchdog) is captured HERE on the
        # query thread and polled by the recovery loop.
        from ..trace import span
        if self._lineage is not None:
            from .lineage import current_cancel, fetch_many_with_recovery
            fetched = fetch_many_with_recovery(
                self.read_transport, blocks, self._lineage,
                max_in_flight=self.max_in_flight_fetches,
                republish=self.read_transport.publish,
                cancel=current_cancel())
        else:
            fetched = self.read_transport.fetch_many(
                blocks, max_in_flight=self.max_in_flight_fetches)
        with span("shuffle.read", kind="shuffle", partition=p,
                  blocks=len(blocks)):
            batches = [deserialize_batch(data, schema)
                       for _, data in fetched]
        total = sum(int(b.num_rows) for b in batches)
        if total == 0:
            return
        if len(batches) == 1:
            yield batches[0]
        else:
            yield concat_batches(batches, bucket_capacity(total))

    def cleanup(self) -> None:
        # always drop this shuffle's blocks (and their lineage — the
        # recompute closures pin the child exec tree otherwise); close
        # the transport only if this exec created it (an injected
        # transport may serve peers)
        self.transport.remove_shuffle(self.shuffle_id)
        if self.read_transport is not self.transport:
            # recovered blocks were republished into the reading
            # transport's local store; drop them with the shuffle
            self.read_transport.remove_shuffle(self.shuffle_id)
        if self._lineage is not None:
            self._lineage.remove_shuffle(self.shuffle_id)
        if self._owns_transport:
            self.transport.close()

    def do_close(self) -> None:
        self.cleanup()
