"""Partitionings — the engine's parallelism strategies.

Reference: GpuHashPartitioningBase.scala (murmur3 + Table.partition),
GpuRangePartitioner.scala:171 (sampled bounds + sort-based slicing),
GpuRoundRobinPartitioning.scala, GpuSinglePartitioning.scala; device-side
slicing in GpuPartitioning.scala:30-86.

Spark-compatibility matters here: HashPartitioning must produce
``pmod(murmur3(row, seed=42), n)`` bit-exactly, or a mixed CPU/TPU cluster
would route the same key to different reducers (the reference carries the
same constraint vs CPU Spark — HashFunctions.scala).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp

from ..batch import ColumnarBatch, DeviceColumn, Schema
from ..expressions.base import EvalContext, Expression
from ..expressions.hashing import murmur3_batch


class Partitioning:
    num_partitions: int

    def bind(self, schema: Schema) -> "Partitioning":
        return self

    def partition_ids(self, batch: ColumnarBatch,
                      ctx: EvalContext = EvalContext()) -> jnp.ndarray:
        """int32[cap] target partition per row (live rows only meaningful)."""
        raise NotImplementedError


@dataclass
class HashPartitioning(Partitioning):
    exprs: Sequence[Expression]
    num_partitions: int = 8

    def bind(self, schema: Schema) -> "HashPartitioning":
        return HashPartitioning([e.bind(schema) for e in self.exprs],
                                self.num_partitions)

    def partition_ids(self, batch, ctx=EvalContext()):
        # raw_eval keeps dict-encoded string keys in code form:
        # murmur3_batch hashes the dictionary entries once and gathers,
        # still bit-exact with Spark's pmod(murmur3(row, 42), n) routing
        from ..expressions.base import raw_eval
        cols = [raw_eval(e, batch, ctx) for e in self.exprs]
        h = murmur3_batch(cols)
        m = h % jnp.int32(self.num_partitions)
        return jnp.where(m < 0, m + self.num_partitions, m).astype(jnp.int32)


@dataclass
class RoundRobinPartitioning(Partitioning):
    num_partitions: int = 8
    start: int = 0

    def partition_ids(self, batch, ctx=EvalContext()):
        cap = batch.capacity
        return ((jnp.arange(cap, dtype=jnp.int32) + self.start)
                % self.num_partitions)


@dataclass
class SinglePartitioning(Partitioning):
    num_partitions: int = 1

    def partition_ids(self, batch, ctx=EvalContext()):
        return jnp.zeros(batch.capacity, jnp.int32)


@dataclass
class RangePartitioning(Partitioning):
    """Range partitioning from sampled bounds.

    The exchange samples key rows across input batches (reference:
    SamplingUtils.scala reservoir sample), sorts them, and picks
    ``num_partitions - 1`` bound rows; each data row then binary-searches its
    target partition. Bounds are set once via ``set_bounds`` before use.
    """

    orders: Sequence  # List[SortOrder]
    num_partitions: int = 8

    def __post_init__(self):
        self._bound_words: Optional[List[jnp.ndarray]] = None
        self._descending = [o.descending for o in self.orders]
        self._nulls_first = [o.effective_nulls_first for o in self.orders]

    def bind(self, schema: Schema) -> "RangePartitioning":
        p = RangePartitioning([o.bind(schema) for o in self.orders],
                              self.num_partitions)
        return p

    def key_columns(self, batch: ColumnarBatch,
                    ctx: EvalContext = EvalContext()) -> List[DeviceColumn]:
        return [o.child.eval(batch, ctx) for o in self.orders]

    def _norm_words(self, key_cols: List[DeviceColumn],
                    live: jnp.ndarray) -> List[jnp.ndarray]:
        from ..exec.common import sort_operands
        # drop the leading liveness operand: bounds and rows share it
        return sort_operands(key_cols, self._descending, self._nulls_first,
                             live)[1:]

    def set_bounds(self, bound_cols: List[DeviceColumn], n_bounds) -> None:
        """``bound_cols`` hold the sorted bound rows (possibly fewer than
        num_partitions-1; n_bounds is traced-safe static int)."""
        live = jnp.arange(bound_cols[0].validity.shape[0]) < n_bounds
        self._bound_words = self._norm_words(bound_cols, live)
        self._n_bounds = n_bounds

    def partition_ids(self, batch, ctx=EvalContext()):
        assert self._bound_words is not None, "set_bounds first"
        keys = self.key_columns(batch, ctx)
        words = self._norm_words(keys, batch.row_mask())
        cap = batch.capacity
        pid = jnp.zeros(cap, jnp.int32)
        # row > bound lexicographically → row belongs to a later partition
        for b in range(self._n_bounds):
            gt = jnp.zeros(cap, bool)
            decided = jnp.zeros(cap, bool)
            for w, bw in zip(words, self._bound_words):
                bv = bw[b]
                gt = gt | (~decided & (w > bv))
                decided = decided | (w != bv)
            # Spark's RangePartitioner: keys <= bound stay in the earlier
            # partition (lteq in getPartition), so only strictly-greater
            # rows advance.
            pid = pid + gt.astype(jnp.int32)
        return jnp.minimum(pid, self.num_partitions - 1)
