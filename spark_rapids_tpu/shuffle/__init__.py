"""Distribution layer: partitionings, shuffle exchange, broadcast.

Reference inventory: SURVEY.md §2.8/§2.10 — GpuHashPartitioningBase,
GpuRangePartitioner, GpuRoundRobinPartitioning, GpuSinglePartitioning,
GpuShuffleExchangeExecBase, GpuBroadcastExchangeExec and the three-mode
shuffle manager (RapidsShuffleInternalManagerBase).
"""

from .partitioning import (HashPartitioning, Partitioning,
                           RangePartitioning, RoundRobinPartitioning,
                           SinglePartitioning)
from .exchange import BroadcastExchangeExec, ShuffleExchangeExec
from .multithreaded import MultithreadedShuffleExchangeExec
from .transport import (BlockCorruptError, BlockMissingError,
                        PeerUnreachableError, TransportError)
from .lineage import (LineageMissError, LineageRegistry,
                      LineageVerificationError, lineage_registry)

__all__ = [n for n in dir() if not n.startswith("_")]
