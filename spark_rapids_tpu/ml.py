"""Zero-copy export of query results to ML frameworks.

Reference: sql-plugin/.../execution/InternalColumnarRddConverter.scala
(769 LoC) — the reference hands GPU-resident columnar RDDs to XGBoost
without a host round-trip. The TPU-native analogue is stronger: a planned
query's result is ALREADY jax arrays in HBM, so "export" is handing the
device buffers over — `collect_jax` returns them as-is (zero copy, still
on the TPU, ready for jit-compiled training steps), `collect_torch`
bridges through dlpack/numpy for the CPU-torch stack in this image.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from .batch import ColumnarBatch, Schema, bucket_capacity
from .types import TypeKind


def collect_device(session, df) -> Tuple[ColumnarBatch, Schema]:
    """Run a DataFrame and return its result as ONE device-resident
    ColumnarBatch (concatenated across partitions) — no host transfer.
    Plans that fell back to CPU (or run interpreted: sql disabled /
    explain-only mode) are re-imported to the device."""
    from .exec.common import concat_batches
    from .plan.interpreter import Interpreter
    from .batch import from_arrow

    kind, plan = session.prepare(df)
    if kind == "interpret":
        table = Interpreter(ansi=session.conf.ansi).execute(df.plan)
        return from_arrow(table)
    if kind == "fallback":
        return from_arrow(plan.interpret())
    try:
        batches = [b for p in range(plan.num_partitions)
                   for b in plan.execute_partition(p)]
        schema = plan.output_schema
        if not batches:
            from .batch import empty_batch
            return empty_batch(schema), schema
        if len(batches) == 1:
            return batches[0], schema
        cap = bucket_capacity(sum(b.capacity for b in batches))
        return concat_batches(batches, cap), schema
    finally:
        plan.close()


_NUMERIC_KINDS = (TypeKind.INT8, TypeKind.INT16, TypeKind.INT32,
                  TypeKind.INT64, TypeKind.FLOAT32, TypeKind.FLOAT64,
                  TypeKind.BOOLEAN, TypeKind.DATE, TypeKind.TIMESTAMP)


def collect_jax(session, df, compact: bool = True
                ) -> Dict[str, Tuple[jnp.ndarray, jnp.ndarray]]:
    """name -> (values, mask) jax arrays, still on device. `mask[i]` False
    means NULL (and for padded capacity rows). With compact=True the
    arrays are trimmed to the bucketed row capacity of the true row count.

    The arrays are the engine's own buffers — feeding them into a jitted
    training step involves no host transfer at all."""
    batch, schema = collect_device(session, df)
    out: Dict[str, Tuple[jnp.ndarray, jnp.ndarray]] = {}
    live = batch.row_mask()
    n = int(batch.num_rows)
    cap = bucket_capacity(max(n, 1)) if compact else batch.capacity
    for f, c in zip(schema.fields, batch.columns):
        if f.dtype.kind not in _NUMERIC_KINDS:
            raise TypeError(
                f"column {f.name}: {f.dtype} export is numeric-only "
                f"(strings/arrays have engine-internal layouts); cast or "
                f"project first")
        data, mask = c.data, c.validity & live
        if cap != batch.capacity:
            data, mask = data[:cap], mask[:cap]
        out[f.name] = (data, mask)
    return out


def collect_numpy(session, df, nulls_to: Optional[float] = None
                  ) -> Dict[str, np.ndarray]:
    """name -> numpy array of exactly num_rows values (one D2H copy).
    Nulls become `nulls_to` (float columns) or raise if present and
    nulls_to is None."""
    batch, schema = collect_device(session, df)
    n = int(batch.num_rows)
    out: Dict[str, np.ndarray] = {}
    live = np.asarray(batch.row_mask())[:n] if n else np.zeros(0, bool)
    for f, c in zip(schema.fields, batch.columns):
        if f.dtype.kind not in _NUMERIC_KINDS:
            raise TypeError(f"column {f.name}: numeric-only export")
        vals = np.asarray(c.data)[:n]
        mask = np.asarray(c.validity)[:n] & live
        if not mask.all():
            if nulls_to is None:
                raise ValueError(
                    f"column {f.name} contains nulls; pass nulls_to=")
            vals = vals.astype(np.float64, copy=True)
            vals[~mask] = nulls_to
        out[f.name] = vals
    return out


def collect_torch(session, df, nulls_to: Optional[float] = None):
    """name -> torch tensor (via numpy; torch in this image is CPU-only,
    so the bridge is one host copy — on a GPU/TPU torch build this would
    ride dlpack device-to-device). The copy is deliberate: collect_numpy
    may return read-only views of the engine's own buffers, and a shared
    tensor would let in-place torch ops corrupt cached column data."""
    import torch
    return {k: torch.from_numpy(np.array(v))
            for k, v in collect_numpy(session, df, nulls_to).items()}
