"""Self-documenting configuration registry.

TPU-native analogue of the reference's `RapidsConf` builder DSL
(reference: sql-plugin/.../RapidsConf.scala:119-308 — 168 typed
`spark.rapids.*` entries with generated docs). Entries here use the
`spark.rapids.tpu.*` namespace; `generate_docs()` renders the table the same
way `RapidsConf.help` generates docs/configs.md in the reference.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

_REGISTRY: Dict[str, "ConfEntry"] = {}


@dataclass
class ConfEntry:
    key: str
    default: Any
    doc: str
    conv: Callable[[str], Any]
    startup_only: bool = False
    internal: bool = False

    def get(self, conf: "RapidsTpuConf") -> Any:
        return conf.get(self.key)


def _register(entry: ConfEntry) -> ConfEntry:
    if entry.key in _REGISTRY:
        raise ValueError(f"duplicate conf {entry.key}")
    _REGISTRY[entry.key] = entry
    return entry


class ConfBuilder:
    def __init__(self, key: str):
        self.key = key
        self._doc = ""
        self._startup = False
        self._internal = False

    def doc(self, d: str) -> "ConfBuilder":
        self._doc = " ".join(d.split())
        return self

    def startup_only(self) -> "ConfBuilder":
        self._startup = True
        return self

    def internal(self) -> "ConfBuilder":
        self._internal = True
        return self

    def _make(self, default, conv):
        return _register(ConfEntry(self.key, default, self._doc, conv,
                                   self._startup, self._internal))

    def boolean(self, default: bool) -> ConfEntry:
        return self._make(default, lambda s: str(s).strip().lower() in ("true", "1"))

    def integer(self, default: int) -> ConfEntry:
        return self._make(default, int)

    def floating(self, default: float) -> ConfEntry:
        return self._make(default, float)

    def bytes_(self, default: int) -> ConfEntry:
        return self._make(default, parse_bytes)

    def text(self, default: str) -> ConfEntry:
        return self._make(default, str)


def conf(key: str) -> ConfBuilder:
    return ConfBuilder(key)


def parse_bytes(s) -> int:
    if isinstance(s, (int, float)):
        return int(s)
    s = str(s).strip().lower()
    units = {"k": 1 << 10, "m": 1 << 20, "g": 1 << 30, "t": 1 << 40, "b": 1}
    for suffix in ("kb", "mb", "gb", "tb", "k", "m", "g", "t", "b"):
        if s.endswith(suffix):
            return int(float(s[: -len(suffix)]) * units[suffix[0]])
    return int(float(s))


# ---------------------------------------------------------------------------
# Entries. Grouped like the reference's RapidsConf sections.
# ---------------------------------------------------------------------------

SQL_ENABLED = conf("spark.rapids.tpu.sql.enabled").doc(
    "Master switch: when false every operator stays on CPU (differential-test "
    "oracle mode; reference: spark.rapids.sql.enabled).").boolean(True)

EXPLAIN = conf("spark.rapids.tpu.sql.explain").doc(
    "NONE, ALL, or NOT_ON_TPU: log why parts of a query were not placed on the "
    "TPU (reference: spark.rapids.sql.explain).").text("NONE")

MODE = conf("spark.rapids.tpu.sql.mode").doc(
    "executeontpu or explainonly: explainonly plans as if a TPU were present "
    "but executes on CPU (reference: spark.rapids.sql.mode=explainonly).").text(
    "executeontpu")

INCOMPATIBLE_OPS = conf("spark.rapids.tpu.sql.incompatibleOps.enabled").doc(
    "Enable operators whose results differ from Spark in corner cases (float "
    "aggregation order, XLA float rounding vs CUDA; reference: "
    "spark.rapids.sql.incompatibleOps.enabled).").boolean(False)

ANSI_ENABLED = conf("spark.rapids.tpu.sql.ansi.enabled").doc(
    "ANSI SQL mode: overflow and invalid casts raise instead of null/wrap."
).boolean(False)

BATCH_SIZE_BYTES = conf("spark.rapids.tpu.sql.batchSizeBytes").doc(
    "Target device batch size; operator output batches are coalesced up to "
    "this size (reference: spark.rapids.sql.batchSizeBytes=1GiB).").bytes_(
    512 << 20)

BATCH_ROW_CAPACITY = conf("spark.rapids.tpu.sql.batchRowCapacity").doc(
    "Maximum rows per device batch. Row counts are padded to bucketed "
    "capacities (powers of two) so XLA recompiles are bounded — the TPU "
    "answer to cudf's fully dynamic shapes.").integer(1 << 20)

CONCURRENT_TPU_TASKS = conf("spark.rapids.tpu.sql.concurrentTpuTasks").doc(
    "Admission-control semaphore: number of tasks that may hold device "
    "memory concurrently per executor (reference: "
    "spark.rapids.sql.concurrentGpuTasks=2).").integer(2)

HBM_POOL_FRACTION = conf("spark.rapids.tpu.memory.hbm.poolFraction").doc(
    "Fraction of HBM reserved for the framework's budget allocator "
    "(reference: spark.rapids.memory.gpu.allocFraction).").startup_only().floating(0.85)

HBM_RESERVE = conf("spark.rapids.tpu.memory.hbm.reserve").doc(
    "Bytes of HBM held back for XLA scratch/fusion temporaries (reference: "
    "spark.rapids.memory.gpu.reserve).").startup_only().bytes_(2 << 30)

HOST_SPILL_LIMIT = conf("spark.rapids.tpu.memory.host.spillStorageSize").doc(
    "Bytes of host memory for spilled device buffers before overflowing to "
    "disk (reference: spark.rapids.memory.host.spillStorageSize).").bytes_(4 << 30)

SPILL_DIR = conf("spark.rapids.tpu.memory.spillDir").doc(
    "Directory for disk-tier spill files.").text("/tmp/rapids_tpu_spill")

BROADCAST_LIMIT = conf("spark.rapids.tpu.broadcast.maxBytes").doc(
    "Maximum device bytes for one broadcast relation; larger builds must "
    "shuffle instead (reference: Spark's 8GB broadcast hard limit / "
    "spark.sql.autoBroadcastJoinThreshold escalation).").bytes_(1 << 30)

METRICS_LEVEL = conf("spark.rapids.tpu.sql.metrics.level").doc(
    "ESSENTIAL, MODERATE or DEBUG metric collection (reference: "
    "spark.rapids.sql.metrics.level).").text("MODERATE")

STRING_MAX_BYTES = conf("spark.rapids.tpu.sql.stringMaxBytes").doc(
    "Default maximum encoded byte length for device string columns. Strings "
    "are fixed-width padded byte matrices on TPU; longer inputs fall back to "
    "CPU or are re-bucketed.").integer(64)

MULTITHREADED_READ_THREADS = conf(
    "spark.rapids.tpu.sql.multiThreadedRead.numThreads").doc(
    "Thread-pool size for the multithreaded multi-file reader (reference: "
    "spark.rapids.sql.multiThreadedRead.numThreads).").integer(8)

READER_TYPE = conf("spark.rapids.tpu.sql.format.parquet.reader.type").doc(
    "PERFILE, COALESCING, MULTITHREADED or AUTO (reference: "
    "spark.rapids.sql.format.parquet.reader.type).").text("AUTO")

AGG_MAX_RESULT_ROWS = conf("spark.rapids.tpu.sql.agg.maxResultRows").doc(
    "Device row budget for one aggregation's result layout; aggregations "
    "whose distinct-group estimate exceeds it take the sort-based "
    "out-of-core fallback (reference: the merge/sort-fallback sizing in "
    "aggregate.scala computeTargetBatchSize).").integer(1 << 22)

COALESCE_MAX_ROWS = conf("spark.rapids.tpu.sql.coalesce.maxRows").doc(
    "Row cap per coalesced output batch in CoalesceBatchesExec — bounds "
    "the concat kernel's capacity bucket even when batchSizeBytes would "
    "admit more rows (reference: the row-count guard in "
    "GpuCoalesceBatches' TargetSize goal).").integer(1 << 22)

TRANSPORT_RETRIES = conf(
    "spark.rapids.tpu.shuffle.transport.retries").doc(
    "Connection attempts per peer before a fetch/list fails over or "
    "errors (reference: the UCX transport's connection retry policy)."
).integer(3)

TRANSPORT_WINDOW_BYTES = conf(
    "spark.rapids.tpu.shuffle.transport.windowBytes").doc(
    "Staging-window size for large-block transport fetches: blocks above "
    "this stream as fixed-size range reads over the persistent peer "
    "connection instead of one giant frame (reference: bounce buffers + "
    "WindowedBlockIterator in the UCX shuffle, "
    "BounceBufferManager.scala).").integer(4 << 20)

TRANSPORT_MAX_IN_FLIGHT = conf(
    "spark.rapids.tpu.shuffle.transport.maxInFlightFetches").doc(
    "Bound on concurrently outstanding block fetches in the pipelined "
    "shuffle read (transport.fetch_many) — decode overlaps the wire "
    "while memory stays bounded (reference: "
    "spark.rapids.shuffle.ucx.activeMessages / maxBytesInFlight "
    "pipelining).").integer(4)

TRANSPORT_CONNECT_TIMEOUT_MS = conf(
    "spark.rapids.tpu.shuffle.transport.connectTimeoutMs").doc(
    "Deadline for establishing (and handshaking) a peer connection; an "
    "unreachable peer surfaces as PeerUnreachableError after the retry "
    "budget instead of blocking a fetching thread (reference: the UCX "
    "transport's endpoint setup timeout).").integer(30000)

TRANSPORT_IO_TIMEOUT_MS = conf(
    "spark.rapids.tpu.shuffle.transport.ioTimeoutMs").doc(
    "Post-connect socket deadline on every transport send/recv: a peer "
    "that accepts then goes silent times out instead of deadlocking the "
    "per-peer connection lock forever (reference: the transaction "
    "timeouts on RapidsShuffleClient requests).").integer(30000)

TRANSPORT_BACKOFF_MS = conf(
    "spark.rapids.tpu.shuffle.transport.retryBackoffMs").doc(
    "Base delay of the jittered exponential backoff between transport "
    "retry attempts (delay ~ base * 2^attempt * jitter, capped by "
    "retryBackoffMaxMs); 0 disables backoff (reference: the shuffle "
    "fetch retry wait in RapidsShuffleIterator).").integer(10)

TRANSPORT_BACKOFF_MAX_MS = conf(
    "spark.rapids.tpu.shuffle.transport.retryBackoffMaxMs").doc(
    "Upper bound on one transport retry backoff sleep."
).integer(1000)

SHUFFLE_REPLICAS = conf("spark.rapids.tpu.shuffle.replicas").doc(
    "Replication factor for published map outputs: each serialized piece "
    "is additionally written to this many live peers at publish time, so "
    "a dead executor's exclusively-held blocks are served from a replica "
    "(plain failover) instead of recomputed. 0 (default) = no "
    "replication — lineage recompute is the only recovery for blocks the "
    "dead peer alone held. Surviving the dead peer's FAILED LISTING "
    "additionally requires lineage.enabled (the default): replica writes "
    "are best-effort, so only the lineage registry can certify a "
    "partial listing lost no rows (reference: external shuffle "
    "services' block replication story).").integer(0)

SHUFFLE_LINEAGE_ENABLED = conf(
    "spark.rapids.tpu.shuffle.lineage.enabled").doc(
    "Record shuffle lineage — producing plan fragment + input digest per "
    "published map output — so a reduce-side fetch whose failover is "
    "exhausted (BlockMissingError with no serving peer, "
    "PeerUnreachableError on a dead executor) deterministically "
    "RECOMPUTES exactly the lost map partitions, verifies them against "
    "the publish-time content digest, and resumes bit-for-bit instead of "
    "failing the query (reference: Spark's MapOutputTracker + "
    "stage-resubmission recovery, compressed to the fragment level)."
).boolean(True)

PARQUET_NATIVE_DECODE = conf(
    "spark.rapids.tpu.sql.format.parquet.nativeDecode.enabled").doc(
    "Decode parquet column chunks with the native C++ decoder "
    "(native/src/rtpu_parquet.cpp: thrift footer parse + "
    "PLAIN/RLE_DICTIONARY page decode, SNAPPY/ZSTD) instead of pyarrow; "
    "files outside the native subset (nested schemas, INT96, exotic "
    "codecs) silently fall back per row group (reference: the JNI footer "
    "parse + libcudf readParquet device path, "
    "GpuParquetScan.scala:539-597).").boolean(True)

FUSION_ENABLED = conf("spark.rapids.tpu.sql.fusion.enabled").doc(
    "Whole-stage fusion: compile an eligible linear single-batch stage "
    "(scan/filter/project/join/sort/topN/aggregate) into ONE XLA program "
    "with optimistic join sizing and flag-validated retries (the XLA twin "
    "of Spark's whole-stage codegen; reference: GpuTieredProject / "
    "whole-stage pipelining, SURVEY.md §3.3).").boolean(True)

# ---- per-format enables (reference: spark.rapids.sql.format.*.enabled) ----

PARQUET_ENABLED = conf("spark.rapids.tpu.sql.format.parquet.enabled").doc(
    "Accelerate parquet scans; disabled scans fall back to the CPU "
    "interpreter (reference: spark.rapids.sql.format.parquet.enabled)."
).boolean(True)

ORC_ENABLED = conf("spark.rapids.tpu.sql.format.orc.enabled").doc(
    "Accelerate ORC scans (reference: spark.rapids.sql.format.orc.enabled)."
).boolean(True)

CSV_ENABLED = conf("spark.rapids.tpu.sql.format.csv.enabled").doc(
    "Accelerate CSV scans (reference: spark.rapids.sql.format.csv.enabled)."
).boolean(True)

JSON_ENABLED = conf("spark.rapids.tpu.sql.format.json.enabled").doc(
    "Accelerate JSON-lines scans (reference: "
    "spark.rapids.sql.format.json.enabled).").boolean(True)

AVRO_ENABLED = conf("spark.rapids.tpu.sql.format.avro.enabled").doc(
    "Accelerate Avro OCF scans (reference: "
    "spark.rapids.sql.format.avro.enabled).").boolean(True)

HIVE_TEXT_ENABLED = conf(
    "spark.rapids.tpu.sql.format.hiveText.enabled").doc(
    "Accelerate Hive delimited-text (LazySimpleSerDe) scans (reference: "
    "spark.rapids.sql.format.hive.text.enabled / "
    "GpuHiveTableScanExec).").boolean(True)

REGEXP_ENABLED = conf("spark.rapids.tpu.sql.regexp.enabled").doc(
    "Master switch for device regular expressions (RLike, regexp_extract, "
    "regexp_replace, split): disabled, every regex expression falls back "
    "to the CPU interpreter — large/pathological patterns can be slower "
    "on accelerators (reference: spark.rapids.sql.regexp.enabled)."
).boolean(True)

PREFETCH_ENABLED = conf("spark.rapids.tpu.prefetch.enabled").doc(
    "Pipelined host prefetch (spark_rapids_tpu/pipeline.py): scans decode "
    "batch N+1 on a background thread while batch N is in device_put/"
    "compute, and exchange serialization D2H-stages partition P+1 while "
    "partition P is framed/compressed (reference: pinned-memory prefetch, "
    "GpuMultiFileReader.scala:441 + PinnedMemoryPool). Disabling "
    "reproduces the synchronous path bit for bit; single-core hosts skip "
    "the thread handoff automatically.").boolean(True)

PREFETCH_DEPTH = conf("spark.rapids.tpu.prefetch.depth").doc(
    "Bounded look-ahead of each prefetch pipeline stage (items buffered "
    "ahead of the consumer). 2 = double buffering; 0 disables, identical "
    "to prefetch.enabled=false.").integer(2)

READER_BATCH_ROWS = conf("spark.rapids.tpu.sql.reader.batchSizeRows").doc(
    "Row target per decoded host batch a scan emits (reference: "
    "spark.rapids.sql.reader.batchSizeRows).").integer(1 << 20)

MT_READER_MAX_TASKS = conf(
    "spark.rapids.tpu.sql.format.multithreaded.maxTasksInFlight").doc(
    "Bound on decode tasks submitted to the shared reader pool at once; "
    "keeps many-file scans from queueing unbounded host memory "
    "(reference: spark.rapids.sql.multiThreadedRead.maxNumFilesParallel)."
).integer(64)

COALESCING_PARALLEL_FILES = conf(
    "spark.rapids.tpu.sql.format.coalescing.numFilesParallel").doc(
    "Files decoded concurrently by the COALESCING reader before the "
    "concat (reference: the coalescing reader's parallel footer+decode "
    "stage).").integer(8)

FILECACHE_ENABLED = conf("spark.rapids.tpu.filecache.enabled").doc(
    "Cache decoded parquet blobs for re-reads within a session "
    "(reference: spark.rapids.filecache.enabled).").boolean(True)

SHUFFLE_MT_WRITER_THREADS = conf(
    "spark.rapids.tpu.shuffle.multiThreaded.writer.threads").doc(
    "Writer-side thread count of the MULTITHREADED shuffle (reference: "
    "spark.rapids.shuffle.multiThreaded.writer.threads).").integer(8)

SHUFFLE_MT_READER_THREADS = conf(
    "spark.rapids.tpu.shuffle.multiThreaded.reader.threads").doc(
    "Reader-side thread count of the MULTITHREADED shuffle (reference: "
    "spark.rapids.shuffle.multiThreaded.reader.threads).").integer(8)

SHUFFLE_MT_MAX_BYTES_IN_FLIGHT = conf(
    "spark.rapids.tpu.shuffle.multiThreaded.maxBytesInFlight").doc(
    "Serialized bytes a multithreaded shuffle keeps in flight before "
    "writers block (reference: "
    "spark.rapids.shuffle.multiThreaded.maxBytesInFlight)."
).integer(512 << 20)

CACHED_REGISTRY = conf(
    "spark.rapids.tpu.shuffle.cached.registry").doc(
    "host:port of the driver-side peer registry for the CACHED "
    "shuffle's cross-host peer discovery; empty = single-process "
    "(reference: RapidsShuffleHeartbeatManager endpoint table)."
).text("")

EXECUTOR_ID = conf("spark.rapids.tpu.executorId").doc(
    "Numeric executor id for shuffle peer identity (reference: the "
    "executor id UCX endpoints key on).").integer(0)

CACHED_HEARTBEAT_INTERVAL_MS = conf(
    "spark.rapids.tpu.shuffle.cached.heartbeatIntervalMs").doc(
    "Executor heartbeat period feeding CACHED-shuffle peer liveness "
    "(reference: spark.rapids.shuffle.ucx.managementServer heartbeats)."
).integer(5000)

CACHED_HEARTBEAT_TIMEOUT_MS = conf(
    "spark.rapids.tpu.shuffle.cached.heartbeatTimeoutMs").doc(
    "Silence after which a CACHED-shuffle peer counts as dead and its "
    "blocks are re-fetched elsewhere (reference: "
    "RapidsShuffleHeartbeatManager timeout).").integer(30000)

PYTHON_WORKER_PROCESSES = conf(
    "spark.rapids.tpu.python.worker.processes").doc(
    "Default size of the process-wide forked Python UDF worker pool, "
    "read when the pool is FIRST created; per-exec override via the "
    "exec's pool_size attribute (reference: python daemon pool sizing)."
).startup_only().integer(4)

GENERATE_MAX_REPEAT = conf(
    "spark.rapids.tpu.sql.generate.maxRepeat").doc(
    "Static per-row budget for ReplicateRows/explode fan-out on device."
).integer(64)

SHUFFLE_MODE = conf("spark.rapids.tpu.shuffle.mode").doc(
    "Shuffle manager mode: DEFAULT (serialized host batches), MULTITHREADED "
    "(thread-pooled writers/readers) or ICI (device-resident, collective "
    "data plane; reference: rapids-shuffle.md three modes).").text("DEFAULT")

DPP_ENABLED = conf(
    "spark.rapids.tpu.sql.dynamicPartitionPruning.enabled").doc(
    "Prune hive-partitioned scan files at plan time using the distinct "
    "join-key values of a broadcast build side (reference: "
    "GpuSubqueryBroadcastExec / dpp_test.py).").boolean(True)

BROADCAST_THRESHOLD = conf(
    "spark.rapids.tpu.sql.autoBroadcastJoinThreshold").doc(
    "Max estimated build-side bytes for a broadcast hash join; larger (or "
    "unknown-size) builds shuffle both sides on the join keys instead "
    "(spark.sql.autoBroadcastJoinThreshold analogue; reference: "
    "GpuShuffledHashJoinExec build-side selection).").integer(10 << 20)

JOIN_MAX_BUILD_ROWS = conf("spark.rapids.tpu.sql.join.maxBuildRows").doc(
    "Per-partition build-side row budget; bigger builds grace-hash "
    "sub-partition both sides (reference: GpuHashJoin.scala:811 oversized-"
    "build sub-partitioning).").integer(1 << 22)

MESH_DEVICES = conf("spark.rapids.tpu.mesh.devices").doc(
    "Device count for the ICI mesh data axis (0 = all visible devices). "
    "Used when shuffle.mode=ICI fuses planned queries onto one SPMD "
    "program.").integer(0)

SHUFFLE_PARTITIONS = conf("spark.rapids.tpu.shuffle.partitions").doc(
    "Default number of shuffle partitions (spark.sql.shuffle.partitions "
    "analogue).").integer(8)

SHUFFLE_COMPRESSION = conf("spark.rapids.tpu.shuffle.compression.codec").doc(
    "Codec for serialized shuffle/spill batches: none, lz4 or zstd "
    "(reference: nvcomp TableCompressionCodec).").text("lz4")

LEAK_DETECTION = conf("spark.rapids.tpu.memory.leakDetection").doc(
    "Record the registration site of every buffer-catalog handle and "
    "report handles that outlive their owner (reference: cudf "
    "MemoryCleaner refcount leak checks). Small hot-path cost; meant for "
    "tests and debugging."
).boolean(False)

OOM_DUMP_DIR = conf("spark.rapids.tpu.memory.oomDumpDir").doc(
    "If set, dump the buffer-catalog state here when an allocation cannot be "
    "satisfied even after spilling (reference: "
    "spark.rapids.memory.gpu.oomDumpDir).").text("")

TEST_RETAG = conf("spark.rapids.tpu.sql.test.allowedNonTpu").doc(
    "Comma-separated exec names allowed to stay on CPU during tests "
    "(reference: the integration harness's allow_non_gpu marker).").internal().text("")

ADAPTIVE_ENABLED = conf("spark.rapids.tpu.sql.adaptive.enabled").doc(
    "Adaptive query execution: coalesce small shuffle output partitions "
    "using materialized stage statistics (reference: "
    "GpuCustomShuffleReaderExec / AQE integration).").boolean(True)

ADAPTIVE_TARGET_ROWS = conf(
    "spark.rapids.tpu.sql.adaptive.coalescePartitions.targetRows").doc(
    "Row target when coalescing adjacent small shuffle partitions."
).integer(1 << 20)

SKEW_JOIN_ENABLED = conf("spark.rapids.tpu.sql.adaptive.skewJoin.enabled").doc(
    "Split a skewed stream-side shuffle partition of a co-partitioned join "
    "into multiple reader partitions, replicating the matching build "
    "partition (spark.sql.adaptive.skewJoin analogue)."
).boolean(True)

SKEW_SPLIT_ROWS = conf(
    "spark.rapids.tpu.sql.adaptive.skewJoin.splitRows").doc(
    "Stream-side rows above which one shuffle partition counts as skewed "
    "and is split (spark.sql.adaptive.skewJoin.skewedPartitionThreshold "
    "analogue, in rows)."
).integer(1 << 21)

ADAPTIVE_COST_ENABLED = conf(
    "spark.rapids.tpu.sql.adaptive.costFeedback.enabled").doc(
    "Cost-fed planning: when the observed-cost store holds measured "
    "whole-query wall times for this plan's shape fingerprint "
    "(query:device / query:cpu entries), CPU-vs-device placement "
    "replays the measured winner instead of the modeled CBO scores. "
    "Cost-fed plans bypass the planning cache in both directions so a "
    "measured decision never poisons a cached fingerprint (see "
    "docs/adaptive.md). Requires planCache.enabled and "
    "trace.costStore.enabled to have anything to consume."
).boolean(False)

ADAPTIVE_COST_MIN_COUNT = conf(
    "spark.rapids.tpu.sql.adaptive.costFeedback.minObservations").doc(
    "Observed-cost EWMA count a query:device / query:cpu entry needs "
    "before cost-fed planning trusts it; below this the modeled "
    "pipeline decides."
).integer(1)

ADAPTIVE_EXPLORE_EVERY = conf(
    "spark.rapids.tpu.sql.adaptive.costFeedback.exploreEvery").doc(
    "Exploration floor for cost-fed planning: every Nth cost-fed plan "
    "of a fingerprint runs the losing — or never-measured — placement "
    "so its wall-time EWMA exists and stays fresh (a placement that "
    "was never measured still gets tried). 0 disables exploration "
    "(pure exploitation of the measured winner)."
).integer(16)

ADAPTIVE_BROADCAST_ENABLED = conf(
    "spark.rapids.tpu.sql.adaptive.broadcastJoin.enabled").doc(
    "Runtime shuffled-to-broadcast join switch: after the build-side "
    "shuffle materializes, a build that measures at or under "
    "adaptive.broadcastJoin.maxBuildRows is replicated to every "
    "stream partition instead of co-partition-probed — the planner's "
    "byte ESTIMATE said shuffle, the measured rows say broadcast "
    "(spark.sql.adaptive OptimizeShuffledHashJoin/broadcast demotion "
    "analogue). Join types with build-side null tails (RIGHT/FULL "
    "outer) never switch."
).boolean(True)

ADAPTIVE_BROADCAST_MAX_BUILD_ROWS = conf(
    "spark.rapids.tpu.sql.adaptive.broadcastJoin.maxBuildRows").doc(
    "Measured build-side row total at or under which a shuffled hash "
    "join switches to broadcast at runtime."
).integer(1 << 16)

WINDOW_BATCH_ROWS = conf("spark.rapids.tpu.sql.window.batchRows").doc(
    "Row target for key-complete window batches: a window partition's "
    "rows are re-chunked on group-key boundaries so one batch never holds "
    "more than ~this many rows (reference: GpuKeyBatchingIterator)."
).integer(1 << 20)

DICT_ENCODING_ENABLED = conf("spark.rapids.tpu.dictEncoding.enabled").doc(
    "Compressed execution for string columns (dictenc.py): scans hand "
    "dictionary codes straight to HBM, equality filters / hash partitioning "
    "/ group-by keys operate on codes, and exchange/spill ship "
    "dictionary+codes instead of padded byte matrices (reference: cudf "
    "dictionary columns + nvcomp keeping data in wire form until the "
    "device needs it). Operators that need bytes decode lazily at the "
    "point of use — results are bit-for-bit identical either way."
).boolean(True)

DICT_MAX_CARDINALITY = conf(
    "spark.rapids.tpu.dictEncoding.maxCardinality").doc(
    "Distinct-value budget per dictionary-encoded string column; columns "
    "above it fall back to the padded byte-matrix path with a recorded "
    "reason tag (high-cardinality dictionaries stop paying for "
    "themselves).").integer(1 << 16)

DICT_MAX_CARD_FRACTION = conf(
    "spark.rapids.tpu.dictEncoding.maxCardinalityFraction").doc(
    "Dictionary cardinality must stay below this fraction of the batch's "
    "rows for encoding to be kept at the scan boundary — near-unique "
    "columns ship smaller as plain padded bytes.").floating(0.5)

DICT_SCAN_ENABLED = conf("spark.rapids.tpu.dictEncoding.scan.enabled").doc(
    "Ask the parquet readers (pyarrow read_dictionary and the native "
    "RLE_DICTIONARY codes decode) to PRESERVE dictionary pages for string "
    "columns instead of materializing bytes at decode time. Only "
    "meaningful while dictEncoding.enabled is true.").boolean(True)

RETRY_ENABLED = conf("spark.rapids.tpu.retry.enabled").doc(
    "OOM retry state machine (memory/retry.py): an operator that hits a "
    "retryable device OOM (buffer-catalog OutOfBudgetError or XLA "
    "RESOURCE_EXHAUSTED) releases its pins, forces a synchronous spill, "
    "backs off while other semaphore holders drain and re-runs — halving "
    "its input down to retry.splitFloorRows on repeated OOM — instead of "
    "failing the query (reference: RmmRapidsRetryIterator withRetry/"
    "withRetryNoSplit). Disabled, OOMs propagate immediately.").boolean(True)

RETRY_MAX_RETRIES = conf("spark.rapids.tpu.retry.maxRetries").doc(
    "Same-size re-attempts per work item before the OOM is final (a "
    "FinalOOMError that fails the query and, when memory.oomDumpDir is "
    "set, writes a state dump). Splits reset the count — each half is a "
    "fresh item.").integer(8)

RETRY_SPLIT_FLOOR_ROWS = conf("spark.rapids.tpu.retry.splitFloorRows").doc(
    "Split-and-retry halving floor: inputs at or below this many rows are "
    "never split further (reference: the minimum batch size guard in "
    "splitSpillableInHalfByRows).").integer(1 << 10)

INJECT_OOM_MODE = conf("spark.rapids.tpu.test.injectOOM.mode").doc(
    "Deterministic OOM fault injection at the instrumented allocation "
    "sites (mirror of RmmSpark's forceRetryOOM): empty/off, 'every-N' "
    "(every Nth allocation check throws a synthetic retryable OOM), or "
    "'random' / 'random-P' (seeded probability P per check, default 0.2). "
    "Test-only: makes every retry path executable on CPU.").text("")

INJECT_OOM_SEED = conf("spark.rapids.tpu.test.injectOOM.seed").doc(
    "RNG seed for injectOOM.mode=random — the same seed replays the same "
    "injection schedule.").integer(0)

INJECT_OOM_SKIP_COUNT = conf("spark.rapids.tpu.test.injectOOM.skipCount").doc(
    "Exempt the first K allocation checks from injection, aiming the "
    "fault at a deep site (e.g. pin k of n in the exchange read "
    "loop).").integer(0)

INJECT_OOM_OOM_COUNT = conf("spark.rapids.tpu.test.injectOOM.oomCount").doc(
    "Consecutive synthetic OOMs thrown per trigger on the triggering "
    "thread (RmmSpark numOOMs): 1 exercises plain retry, >1 forces "
    "split-and-retry, > retry.maxRetries forces a final OOM + "
    "oomDumpDir report.").integer(1)

INJECT_NET_MODE = conf("spark.rapids.tpu.test.injectNet.mode").doc(
    "Deterministic network fault injection at the transport frame seam "
    "(_send_frame/_recv_frame — the NetInjector twin of injectOOM.mode): "
    "empty/off, 'every-N' (every Nth eligible frame op faults), or "
    "'random' / 'random-P' (seeded probability P per frame, default "
    "0.2). Test-only: makes every transport retry/failover path "
    "executable without real network faults.").text("")

INJECT_NET_SEED = conf("spark.rapids.tpu.test.injectNet.seed").doc(
    "RNG seed for injectNet.mode=random — the same seed replays the "
    "same fault schedule.").integer(0)

INJECT_NET_SKIP_COUNT = conf("spark.rapids.tpu.test.injectNet.skipCount").doc(
    "Exempt the first K frame checks from injection, aiming the fault "
    "at a deep site (e.g. window k of a streamed block).").integer(0)

INJECT_NET_FAULT_KIND = conf("spark.rapids.tpu.test.injectNet.faultKind").doc(
    "Fault thrown per trigger: 'drop' (connection closed mid-"
    "transaction), 'delay' (frame stalls injectNet.delayMs), 'truncate' "
    "(frame cut short then connection closed), 'corrupt' (payload bit-"
    "flip AFTER checksumming — the receiver's CRC must catch it), or "
    "'mix' (cycles through all four per trigger).").text("drop")

INJECT_NET_DELAY_MS = conf("spark.rapids.tpu.test.injectNet.delayMs").doc(
    "Stall duration of an injected 'delay' fault.").integer(20)

SERVER_MAX_SESSIONS = conf("spark.rapids.tpu.server.maxSessions").doc(
    "Bound on concurrently connected plan-server sessions; connections "
    "over the bound get a structured 'unavailable' reply with a "
    "retry-after hint instead of an unbounded handler-thread pile-up "
    "(reference: the concurrentGpuTasks admission story applied at the "
    "serving tier).").integer(32)

SERVER_QUERY_TIMEOUT_MS = conf("spark.rapids.tpu.server.queryTimeoutMs").doc(
    "Default per-query deadline enforced by the plan-server watchdog "
    "(a 'plan' header timeout_ms overrides per query; 0 = unbounded). "
    "A query over its deadline gets a structured retryable error and "
    "the connection closes instead of tying the handler thread forever."
).integer(0)

SERVER_RETRY_AFTER_MS = conf("spark.rapids.tpu.server.retryAfterMs").doc(
    "retry_after_ms hint carried on plan-server 'unavailable' replies "
    "(circuit breaker open, maxSessions exceeded).").integer(1000)

SERVER_PLAN_CACHE_ENABLED = conf(
    "spark.rapids.tpu.server.planCache.enabled").doc(
    "Memoize planning (tag/CBO outcomes + fusion/mesh eligibility) per "
    "plan-shape fingerprint, so a repeated query shape skips the planner "
    "walks; literals are parameterized out of the fingerprint under "
    "value-insensitive parents, and capacity buckets keep the rebuilt "
    "plan's jitted kernels hitting XLA's compile cache across sessions "
    "(docs/serving.md).").boolean(True)

SERVER_PLAN_CACHE_MAX_ENTRIES = conf(
    "spark.rapids.tpu.server.planCache.maxEntries").doc(
    "LRU entry bound of the planning cache.").integer(256)

SERVER_RESULT_CACHE_ENABLED = conf(
    "spark.rapids.tpu.server.resultCache.enabled").doc(
    "Serve bit-for-bit repeated queries from an LRU over serialized "
    "results, keyed on (literal-inclusive plan fingerprint, per-table "
    "content digests, conf); invalidated on drop_table/re-upload. "
    "In-memory scans key on content digests; file-backed scans key on "
    "per-file (path, mtime_ns, size) stats, so a rewrite makes the "
    "stale entry unreachable (docs/serving.md).").boolean(False)

SERVER_RESULT_CACHE_MAX_BYTES = conf(
    "spark.rapids.tpu.server.resultCache.maxBytes").doc(
    "Byte budget of the result-set cache; least-recently-used entries "
    "evict past it, and a single result larger than the budget is never "
    "stored.").bytes_(256 << 20)

SERVER_CONCURRENT_COLLECTS = conf(
    "spark.rapids.tpu.server.concurrentCollects").doc(
    "In-flight collect bound at the plan server: per-query admission "
    "(semaphore + a per-query device-memory reservation against the "
    "buffer catalog) replaces the coarse maxSessions slot as the "
    "execution throttle, so independent tenants overlap H2D/compute/D2H "
    "instead of queueing head-of-line (reference: concurrentGpuTasks "
    "applied at the serving tier).").integer(4)

SERVER_QUERY_RESERVE_BYTES = conf(
    "spark.rapids.tpu.server.queryReserveBytes").doc(
    "Device-memory reservation each admitted query takes against the "
    "buffer catalog before executing (0 = auto: the plan's logical size "
    "estimate, capped at 1/concurrentCollects of the device budget). "
    "The reservation triggers spill like any allocation and is released "
    "when the collect ends.").bytes_(0)

SERVER_TEST_COLLECT_DELAY_MS = conf(
    "spark.rapids.tpu.server.test.collectDelayMs").doc(
    "Test-only: stall each plan collect this long (in cancellable "
    "slices) so watchdog/cancellation paths are deterministic."
).internal().integer(0)

FLEET_WORKERS = conf("spark.rapids.tpu.server.fleet.workers").doc(
    "Worker-subprocess count a router starts when launched standalone "
    "(python -m spark_rapids_tpu.server.router). Each worker is a full "
    "plan-server process with its own planning cache and XLA compile "
    "cache; the router keeps repeat plan shapes pinned to the same "
    "worker so those caches stay warm (docs/serving.md).").integer(2)

FLEET_VNODES = conf("spark.rapids.tpu.server.fleet.vnodes").doc(
    "Virtual nodes per worker on the router's consistent-hash ring. "
    "More vnodes spread hash slots more evenly and shrink the slice of "
    "shapes that move when a worker drains or dies.").integer(64)

FLEET_TENANT_ID = conf("spark.rapids.tpu.server.fleet.tenantId").doc(
    "Tenant identity a client declares in its hello conf; the router's "
    "per-tenant admission (quotas + weighted fair queueing) accounts "
    "each plan against it. Empty = the 'default' tenant.").text("")

FLEET_TENANT_MAX_CONCURRENT = conf(
    "spark.rapids.tpu.server.fleet.tenant.maxConcurrent").doc(
    "Per-tenant bound on concurrently in-flight plans at the router; "
    "over it the tenant gets a structured 'unavailable' reply with "
    "retry_after_ms instead of queueing without bound (0 = no quota)."
).integer(0)

FLEET_TENANT_WEIGHTS = conf(
    "spark.rapids.tpu.server.fleet.tenant.weights").doc(
    "Weighted-fair-queueing weights as 'tenantA=3,tenantB=1'; when a "
    "worker's dispatch slots are contended, waiting tenants are served "
    "inversely to (accumulated dispatches / weight), so a heavy tenant "
    "cannot starve a light one. Unlisted tenants weigh 1.").text("")

FLEET_MAX_INFLIGHT_PER_WORKER = conf(
    "spark.rapids.tpu.server.fleet.maxInflightPerWorker").doc(
    "Router-side dispatch bound per worker — plans over it queue in the "
    "weighted-fair admission instead of piling onto the worker's own "
    "concurrentCollects semaphore (0 = inherit concurrentCollects)."
).integer(0)

FLEET_ADMISSION_TIMEOUT_MS = conf(
    "spark.rapids.tpu.server.fleet.admissionTimeoutMs").doc(
    "Bound on a plan's wait in the router's weighted-fair queue; past "
    "it the client gets a structured 'unavailable' + retry_after_ms "
    "reply (the PlanClient retry budget resubmits it).").integer(30000)

FLEET_DRAIN_TIMEOUT_MS = conf(
    "spark.rapids.tpu.server.fleet.drainTimeoutMs").doc(
    "Rolling restart: how long the router waits for a draining worker's "
    "in-flight plans to finish before replacing it anyway. A worker "
    "that DIES while draining is promoted dead immediately (the PR-11 "
    "suspect/dead discipline) — the drain never waits on a corpse."
).integer(30000)

FLEET_SPILLOVER_QUEUE_DEPTH = conf(
    "spark.rapids.tpu.server.fleet.spilloverQueueDepth").doc(
    "Bounded-load consistent hashing: when a plan's home worker already "
    "has this many plans in flight + queued, the router dispatches to "
    "the least-loaded ring candidate instead — cache affinity yields to "
    "utilization only under skew, so one hot shape cannot leave the "
    "rest of the fleet idle (0 = never spill).").integer(8)

FLEET_WORKER_RETRIES = conf(
    "spark.rapids.tpu.server.fleet.workerRetries").doc(
    "How many OTHER workers the router tries for a plan whose assigned "
    "worker failed mid-query (connection drop / worker death). Each "
    "retry replays the session's tables to the failover worker first, "
    "so the resubmit is self-contained.").integer(2)

FLEET_RESULT_STORE_PATH = conf(
    "spark.rapids.tpu.server.fleet.resultStore.path").doc(
    "Directory of the shared persistent result-cache tier. Every "
    "worker reads through to it on an in-memory miss and writes "
    "through on store, so cached results survive worker restarts and "
    "are shared across the fleet; invalidation (drop_table/re-upload) "
    "deletes entries from it too. Empty = tier disabled.").text("")

FLEET_RESULT_STORE_MAX_BYTES = conf(
    "spark.rapids.tpu.server.fleet.resultStore.maxBytes").doc(
    "Byte budget of the persistent result-store directory; past it the "
    "least-recently-touched entry files are deleted at write time."
).bytes_(1 << 30)

FLEET_COST_SYNC_PLANS = conf(
    "spark.rapids.tpu.server.fleet.costSync.everyPlans").doc(
    "Router-driven observed-cost fan-out: every N served plans the "
    "router pulls each worker's cost store, merges them "
    "(highest-observation-count entry wins, the trace-wire-op merge "
    "rule) and pushes the merged snapshot back to every worker over "
    "the costs_load op — so worker B takes cost-fed planning "
    "decisions for shapes only worker A measured. 0 = no automatic "
    "sync (Router.sync_costs() still works on demand)."
).integer(0)

SHARING_ENABLED = conf(
    "spark.rapids.tpu.server.sharing.enabled").doc(
    "Master switch for cross-query work sharing (docs/serving.md "
    "'Cross-query work sharing'): in-flight result dedup, subplan "
    "result caching and shared scan uploads. Off, the engine behaves "
    "byte-identically to a build without the feature — the sub-switches "
    "below only apply when this is on.").boolean(False)

SHARING_INFLIGHT_ENABLED = conf(
    "spark.rapids.tpu.server.sharing.inflight.enabled").doc(
    "Single-flight execution per RESULT key: a query whose result key "
    "matches one already executing waits for the leader's serialized "
    "bytes instead of executing (admission slots are NOT held while "
    "waiting). On leader failure one waiter is promoted to leader and "
    "re-executes; drop_table/re-upload invalidates parked waiters, who "
    "then re-execute against post-drop state.").boolean(True)

SHARING_WAIT_TIMEOUT_MS = conf(
    "spark.rapids.tpu.server.sharing.waitTimeoutMs").doc(
    "Upper bound a deduplicated query waits on an in-flight leader "
    "before giving up and executing on its own (a self-heal bound, not "
    "a correctness gate — results are keyed bit-for-bit)."
).integer(600000)

SHARING_SUBPLAN_ENABLED = conf(
    "spark.rapids.tpu.server.sharing.subplan.enabled").doc(
    "Cache the serialized output of aggregate-boundary subtrees under "
    "per-subtree result keys (plancache.subtree_result_key), so two "
    "queries sharing a subtree — same scan+filter, different "
    "aggregate — execute it once. Only single-partition subtrees with "
    "at least one non-scan operator participate; entries invalidate "
    "with drop_table/re-upload like full results.").boolean(True)

SHARING_SUBPLAN_MAX_BYTES = conf(
    "spark.rapids.tpu.server.sharing.subplan.maxBytes").doc(
    "Byte budget of the subplan result cache (its own LRU, separate "
    "from resultCache.maxBytes).").bytes_(128 << 20)

SHARING_SCANSHARE_ENABLED = conf(
    "spark.rapids.tpu.server.sharing.scanShare.enabled").doc(
    "Publish each in-memory scan's device-resident batches in a "
    "refcounted registry keyed on table content digest, so concurrent "
    "(and closely following) queries over the same table ride one H2D "
    "transfer; the admission layer prefers waiters whose scan digests "
    "match in-flight queries so sharable queries overlap."
).boolean(True)

SHARING_SCANSHARE_MAX_BYTES = conf(
    "spark.rapids.tpu.server.sharing.scanShare.maxBytes").doc(
    "Byte budget of unreferenced device-resident scan entries kept "
    "warm after their last query closes (refcounted entries never "
    "evict).").bytes_(256 << 20)

BRIDGE_ACCEPTED_SCHEMA_VERSIONS = conf(
    "spark.rapids.tpu.bridge.acceptedSchemaVersions").doc(
    "Comma-separated Catalyst fixture schemaVersions the Spark driver "
    "bridge accepts (server/spark_client.py). A plan document declaring "
    "any other version is rejected with an actionable error instead of "
    "being misparsed — the guard against Spark-side plan-format drift."
).text("1")

BRIDGE_DEFAULT_STRING_LEN = conf(
    "spark.rapids.tpu.bridge.defaultStringLen").doc(
    "Byte budget assigned to Spark 'string' attributes during Catalyst "
    "translation (Spark strings are unbounded; the device layout is a "
    "fixed-width padded matrix, the same policy the scan boundary "
    "applies to arrow strings).").integer(64)

BRIDGE_DEFAULT_ARRAY_ELEMS = conf(
    "spark.rapids.tpu.bridge.defaultArrayElems").doc(
    "Element budget assigned to Spark array/map attributes during "
    "Catalyst translation (fixed-budget device layout)."
).integer(256)

TRACE_ENABLED = conf("spark.rapids.tpu.trace.enabled").doc(
    "Query tracing (spark_rapids_tpu/trace.py): mint/adopt a query_id per "
    "collect and record a span timeline — admission wait, cache lookups, "
    "per-operator execution, serializer pack/unpack, per-peer transport "
    "fetches with failover/backoff sub-spans, lineage recomputes. Results "
    "are bit-for-bit identical either way; off-path overhead is one "
    "thread-local read per span site (docs/observability.md)."
).boolean(False)

TRACE_MAX_SPANS = conf("spark.rapids.tpu.trace.maxSpansPerQuery").doc(
    "Span budget per traced query; past it further spans are counted as "
    "dropped (trace.droppedSpanCount, the flight recorder's "
    "droppedSpans) instead of growing the tree without bound."
).integer(2048)

TRACE_SINK_PATH = conf("spark.rapids.tpu.trace.sink.path").doc(
    "When set, append every finished query profile as one JSON line "
    "(JSONL) to this file; tools/trace_viewer.py renders the file as "
    "Chrome/Perfetto trace-event JSON. Sink failures never fail the "
    "query. Empty = no sink.").text("")

TRACE_COST_STORE_ENABLED = conf(
    "spark.rapids.tpu.trace.costStore.enabled").doc(
    "Record per-(shape-fingerprint, operator) observed wall/rows/bytes "
    "EWMAs at collect close from the exec metric roll-up — the "
    "empirical feed for CBO/AQE re-planning. Independent of "
    "trace.enabled (the metrics exist regardless); requires a plan "
    "fingerprint (planCache.enabled) to key on.").boolean(True)

TRACE_COST_STORE_ALPHA = conf(
    "spark.rapids.tpu.trace.costStore.alpha").doc(
    "EWMA smoothing factor of the observed-cost store (new = old + "
    "alpha * (sample - old)); higher tracks load shifts faster, lower "
    "resists outliers.").floating(0.2)

TRACE_COST_STORE_MAX_FPS = conf(
    "spark.rapids.tpu.trace.costStore.maxFingerprints").doc(
    "LRU bound on distinct shape fingerprints the observed-cost store "
    "retains.").integer(1024)

SERVER_TRACE_RECORDER_ENTRIES = conf(
    "spark.rapids.tpu.server.trace.recorderEntries").doc(
    "Capacity of the plan server/router flight recorder: a bounded "
    "in-memory ring of the last N query profiles (plus a same-sized "
    "slow-query log) exposed over the 'trace' wire op and the "
    "serving_stats() trace block.").integer(128)

SERVER_TRACE_SLOW_QUERY_MS = conf(
    "spark.rapids.tpu.server.trace.slowQueryMs").doc(
    "Queries slower than this land in the flight recorder's slow-query "
    "log (and count serving_stats()['trace']['recorder']"
    "['slowQueries']). 0 disables the slow log.").integer(1000)

UDF_COMPILER_ENABLED = conf("spark.rapids.tpu.sql.udfCompiler.enabled").doc(
    "Translate Python UDF bytecode into expression trees so UDF bodies "
    "become TPU-plannable (reference: spark.rapids.sql.udfCompiler.enabled)."
).boolean(False)


class RapidsTpuConf:
    """Typed view over a plain dict of settings, with registry defaults."""

    def __init__(self, settings: Optional[Dict[str, Any]] = None):
        self._settings = dict(settings or {})
        for k in self._settings:
            if k not in _REGISTRY and not k.startswith("spark.rapids.tpu.sql.exec.") \
                    and not k.startswith("spark.rapids.tpu.sql.expression."):
                raise KeyError(f"unknown config {k}")

    def get(self, key: str) -> Any:
        entry = _REGISTRY.get(key)
        if key in self._settings:
            raw = self._settings[key]
            return entry.conv(raw) if entry and isinstance(raw, str) else raw
        if entry is None:
            raise KeyError(key)
        return entry.default

    def set(self, key: str, value: Any) -> "RapidsTpuConf":
        s = dict(self._settings)
        s[key] = value
        return RapidsTpuConf(s)

    # convenience typed accessors used throughout the engine
    @property
    def sql_enabled(self) -> bool:
        return self.get(SQL_ENABLED.key)

    @property
    def batch_row_capacity(self) -> int:
        return self.get(BATCH_ROW_CAPACITY.key)

    @property
    def batch_size_bytes(self) -> int:
        return self.get(BATCH_SIZE_BYTES.key)

    @property
    def ansi(self) -> bool:
        return self.get(ANSI_ENABLED.key)

    @property
    def incompatible_ops(self) -> bool:
        return self.get(INCOMPATIBLE_OPS.key)

    def is_op_enabled(self, op_key: str, default: bool = True) -> bool:
        """Per-op enable flags auto-created by rule registration (reference:
        spark.rapids.sql.exec.* / spark.rapids.sql.expression.*)."""
        v = self._settings.get(op_key, default)
        if isinstance(v, str):
            return v.strip().lower() in ("true", "1")
        return bool(v)


def generate_docs() -> str:
    """Render configs.md the way RapidsConf.help does in the reference."""
    lines = [
        "# spark-rapids-tpu Configuration",
        "",
        "Generated by `spark_rapids_tpu.config.generate_docs()` — do not edit.",
        "",
        "| name | default | description |",
        "|---|---|---|",
    ]
    for key in sorted(_REGISTRY):
        e = _REGISTRY[key]
        if e.internal:
            continue
        lines.append(f"| {e.key} | {e.default} | {e.doc} |")
    return "\n".join(lines) + "\n"


def registry() -> Dict[str, ConfEntry]:
    return dict(_REGISTRY)
