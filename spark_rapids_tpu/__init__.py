"""spark-rapids-tpu: a TPU-native accelerator with the capabilities of the
RAPIDS Accelerator for Apache Spark (reference: NVIDIA spark-rapids), built
on JAX/XLA/Pallas over Arrow-layout HBM batches instead of cuDF/CUDA.

Enable 64-bit mode up front: SQL engines are bigint/double-centric and Spark
semantics require true int64/float64 — jax defaults to 32-bit otherwise.
"""

import jax

jax.config.update("jax_enable_x64", True)

from . import types  # noqa: E402,F401
from .batch import ColumnarBatch, DeviceColumn, Field, Schema  # noqa: E402,F401
from .config import RapidsTpuConf  # noqa: E402,F401

__version__ = "26.08.0"
