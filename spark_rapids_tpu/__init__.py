"""spark-rapids-tpu: a TPU-native accelerator with the capabilities of the
RAPIDS Accelerator for Apache Spark (reference: NVIDIA spark-rapids), built
on JAX/XLA/Pallas over Arrow-layout HBM batches instead of cuDF/CUDA.

Enable 64-bit mode up front: SQL engines are bigint/double-centric and Spark
semantics require true int64/float64 — jax defaults to 32-bit otherwise.
"""

import os

import jax

jax.config.update("jax_enable_x64", True)

# This deployment's site config force-registers the tunneled TPU platform
# regardless of JAX_PLATFORMS (tests/conftest.py documents the same quirk),
# and module-level jnp constants would then initialize that backend at
# import. Honor an explicit CPU request here so device-less processes
# (tests, plan-server drivers, tooling) never touch the tunnel.
if os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")

from . import types  # noqa: E402,F401
from .batch import ColumnarBatch, DeviceColumn, Field, Schema  # noqa: E402,F401
from .config import RapidsTpuConf  # noqa: E402,F401

__version__ = "26.08.0"
