"""Task-scoped OOM retry: split-and-retry execution over spillable inputs.

Reference: RmmRapidsRetryIterator.scala (withRetry / withRetryNoSplit —
an operator that hits device OOM releases what it holds, lets the store
spill, and re-executes, halving its input on repeated OOM instead of
failing the query), GpuOOM/SplitAndRetryOOM classification, and RmmSpark's
deterministic OOM injection (forceRetryOOM/forceSplitAndRetryOOM) that
makes every retry path testable without a real allocator.

The TPU twin:

- ``with_retry(input, body, split=...)`` — run ``body(input)``; on a
  retryable OOM (OutOfBudgetError from the buffer catalog, or an XLA
  ``RESOURCE_EXHAUSTED`` surfaced by the runtime) release the pins the
  attempt took (catalog pin snapshot/restore), force a synchronous spill,
  back off while other semaphore holders drain, and re-run. A second OOM
  on the same input splits it in half (down to
  ``spark.rapids.tpu.retry.splitFloorRows``) and the halves re-enter the
  queue IN ORDER, so concatenated results are bit-for-bit identical to
  the no-OOM path.
- ``with_retry_no_split(body)`` — same recovery loop for bodies whose
  input cannot be halved (final merges, broadcast builds).
- ``SpillableInput`` — the handle an operator parks a batch in across a
  retry boundary: the batch lives in the spill catalog (unpinned between
  attempts → spillable under pressure), not as a raw device array.
- ``OomInjector`` — deterministic fault injection
  (``spark.rapids.tpu.test.injectOOM.{mode,seed,skipCount,oomCount}``):
  synthetic OOM thrown at the instrumented allocation sites so every
  retry path runs on CPU. ``every-N`` fires at every Nth allocation
  check; ``random`` fires with seeded probability. A trigger throws
  ``oomCount`` consecutive OOMs on the triggering thread (RmmSpark's
  numOOMs), and re-attempts inside a retry scope suppress NEW triggers so
  the recovery itself terminates.
- Final OOM (retries exhausted, split floor reached) raises
  ``FinalOOMError`` after writing a state dump to
  ``spark.rapids.tpu.memory.oomDumpDir`` when set: catalog tier
  occupancy, pinned handles, per-operator retry/split counts, semaphore
  holders.
"""

from __future__ import annotations

import os
import random
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional

from .catalog import BufferCatalog, OutOfBudgetError, SpillableBatch

#: substrings that classify a runtime error as a retryable device OOM
#: (the plugin.py failure matcher's RESOURCE_EXHAUSTED family — an XLA
#: HBM OOM is retryable here and only FATAL once retries are exhausted)
RETRYABLE_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "HBM OOM")


class InjectedOOMError(OutOfBudgetError):
    """Synthetic OOM from the fault-injection layer (test-only)."""


class FinalOOMError(MemoryError):
    """OOM that survived the retry state machine: pins were released,
    the store spilled, the input was split down to the floor, and the
    allocation still failed. Carries the oomDumpDir report path when one
    was written."""

    def __init__(self, msg: str, dump_path: Optional[str] = None):
        super().__init__(msg)
        self.dump_path = dump_path


class RetryCancelledError(RuntimeError):
    """The caller's ``cancelled`` hook fired between retry attempts —
    the body is not re-run. Cleanup already happened (the failed
    attempt's pins were restored, queued inputs closed), so the caller
    can unwind immediately; lineage recompute maps this onto the plan
    server's query-cancellation error."""


def is_retryable_oom(exc: BaseException) -> bool:
    """True when the retry state machine should handle ``exc``: a buffer
    catalog OutOfBudgetError (including injected OOM) or an XLA
    RESOURCE_EXHAUSTED surfaced through the runtime. FinalOOMError is
    NEVER retryable — it already consumed its retries."""
    if isinstance(exc, FinalOOMError):
        return False
    if isinstance(exc, OutOfBudgetError):
        return True
    msg = str(exc)
    return any(m in msg for m in RETRYABLE_OOM_MARKERS)


# ---------------------------------------------------------------------------
# retry policy knobs (session conf applied via apply_session_conf)
# ---------------------------------------------------------------------------

class _RetryPolicy:
    def __init__(self):
        self.enabled = True
        self.max_retries = 8
        self.split_floor_rows = 1 << 10
        self.dump_dir = ""


_POLICY = _RetryPolicy()
_POLICY_LOCK = threading.Lock()


# ---------------------------------------------------------------------------
# metrics (reference: the retryCount/splitAndRetryCount/retryBlockTime
# task metrics GpuTaskMetrics rolls into the Spark UI)
# ---------------------------------------------------------------------------

class RetryMetrics:
    """Process-wide retry counters; sessions report deltas between
    snapshots the way the python-semaphore wait metric does."""

    def __init__(self):
        self._lock = threading.Lock()
        self.retry_count = 0
        self.split_and_retry_count = 0
        self.retry_block_time_ns = 0
        self.spill_bytes_triggered = 0
        # adaptive skew pre-splits: inputs cut to the skew row target
        # BEFORE the first device attempt (with_retry presplit_rows) —
        # splits the OOM state machine never had to discover
        self.pre_split_count = 0
        #: per-operator {name: [retries, splits]} for the OOM dump
        self.per_op: Dict[str, List[int]] = {}

    def note_retry(self, name: str) -> None:
        with self._lock:
            self.retry_count += 1
            self.per_op.setdefault(name, [0, 0])[0] += 1

    def note_split(self, name: str) -> None:
        with self._lock:
            self.split_and_retry_count += 1
            self.per_op.setdefault(name, [0, 0])[1] += 1

    def note_presplit(self, name: str) -> None:
        with self._lock:
            self.pre_split_count += 1
            self.per_op.setdefault(name, [0, 0])[1] += 1

    def note_block(self, ns: int) -> None:
        with self._lock:
            self.retry_block_time_ns += int(ns)

    def note_spill(self, nbytes: int) -> None:
        with self._lock:
            self.spill_bytes_triggered += int(nbytes)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {
                "retryCount": self.retry_count,
                "splitAndRetryCount": self.split_and_retry_count,
                "retryBlockTime": self.retry_block_time_ns,
                "retrySpillBytes": self.spill_bytes_triggered,
                "preSplitCount": self.pre_split_count,
            }


_METRICS = RetryMetrics()


def metrics() -> RetryMetrics:
    return _METRICS


# ---------------------------------------------------------------------------
# deterministic fault injection (reference: RmmSpark.forceRetryOOM /
# forceSplitAndRetryOOM + the spark.rapids.sql.test.injectRetryOOM conf)
# ---------------------------------------------------------------------------

class OomInjector:
    """Throws InjectedOOMError at instrumented allocation sites.

    Modes: ``""`` (off), ``every-N`` (every Nth eligible check fires),
    ``random`` (seeded probability 0.2 per check; ``random-0.35`` to set
    it). ``skip_count`` exempts the first K checks (aim at a deep site);
    ``oom_count`` throws that many CONSECUTIVE OOMs per trigger on the
    triggering thread — >1 forces the split path, > maxRetries forces a
    final OOM. Checks under an active retry re-attempt (``suppressed()``)
    never start a NEW trigger, so recovery terminates; pending
    consecutive OOMs still fire there (that is the point of oom_count).
    The first check after a trigger sequence is an uncounted free pass,
    so even ``every-1`` converges at sites that re-allocate outside a
    suppressed scope.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._gen = 0
        self.configure("")

    def configure(self, mode: str, seed: int = 0, skip_count: int = 0,
                  oom_count: int = 1) -> None:
        with self._lock:
            mode = (mode or "").strip().lower()
            self._mode = mode
            self._every = 0
            self._p = 0.0
            if mode.startswith("every-"):
                self._every = max(int(mode.split("-", 1)[1]), 1)
            elif mode.startswith("random"):
                self._p = float(mode.split("-", 1)[1]) \
                    if "-" in mode else 0.2
            elif mode not in ("", "off"):
                raise ValueError(f"unknown injectOOM.mode {mode!r}")
            self._rng = random.Random(seed)
            self._skip_left = max(int(skip_count), 0)
            self._oom_count = max(int(oom_count), 1)
            self._checks = 0
            self.injected = 0
            # invalidate every thread's pending/free state WITHOUT
            # replacing self._tls: another thread may be inside
            # suppressed() right now (apply_session_conf runs at every
            # collect, concurrent with other sessions' retry loops), and
            # swapping the local out from under its finally would crash
            # the recovery path with an AttributeError
            self._gen += 1

    @property
    def enabled(self) -> bool:
        return bool(self._every or self._p)

    @contextmanager
    def suppressed(self):
        """Scope for retry re-attempts: no NEW triggers fire inside."""
        self._tls.suppress = getattr(self._tls, "suppress", 0) + 1
        try:
            yield
        finally:
            self._tls.suppress = max(
                getattr(self._tls, "suppress", 1) - 1, 0)

    def check(self, site: str) -> None:
        """Instrumented-allocation-site hook; raises InjectedOOMError when
        the schedule says this allocation fails."""
        if not self.enabled:
            return
        if getattr(self._tls, "gen", -1) != self._gen:
            # a reconfigure happened since this thread last triggered:
            # its pending/free state belongs to the old schedule
            self._tls.gen = self._gen
            self._tls.pending = 0
            self._tls.free = False
        pending = getattr(self._tls, "pending", 0)
        if pending > 0:
            self._tls.pending = pending - 1
            if self._tls.pending == 0:
                self._tls.free = True
            with self._lock:
                self.injected += 1
            raise InjectedOOMError(
                f"injected OOM at {site} (consecutive {self._oom_count - pending + 1}/"
                f"{self._oom_count})")
        if getattr(self._tls, "free", False):
            # post-trigger free pass: the first check after a trigger
            # sequence succeeds and is not counted, so retry recovery
            # makes progress even at an every-1 site that re-allocates
            # outside a suppressed() scope
            self._tls.free = False
            return
        if getattr(self._tls, "suppress", 0) > 0:
            return
        with self._lock:
            if self._skip_left > 0:
                self._skip_left -= 1
                return
            self._checks += 1
            n = self._checks
            fire = (self._every and n % self._every == 0) or \
                (self._p and self._rng.random() < self._p)
            if fire:
                self.injected += 1
        if fire:
            if self._oom_count > 1:
                self._tls.pending = self._oom_count - 1
            else:
                self._tls.free = True
            raise InjectedOOMError(f"injected OOM at {site} (check #{n})")


_INJECTOR = OomInjector()


def injector() -> OomInjector:
    return _INJECTOR


def maybe_inject(site: str) -> None:
    """Allocation-site hook (the RmmSpark injection point twin). Cheap
    no-op while injection is off."""
    _INJECTOR.check(site)


@contextmanager
def oom_injection(mode: str, seed: int = 0, skip_count: int = 0,
                  oom_count: int = 1):
    """Test helper: enable injection inside the block, restore off after."""
    _INJECTOR.configure(mode, seed, skip_count, oom_count)
    try:
        yield _INJECTOR
    finally:
        _INJECTOR.configure("")


# ---------------------------------------------------------------------------
# conf plumbing (Session.collect applies its conf before executing)
# ---------------------------------------------------------------------------

def apply_session_conf(conf) -> None:
    """Install a session's retry/injection settings process-wide (the
    executor-singleton shape of the reference: RmmSpark state is
    per-process; the last session to run configures it)."""
    from ..config import (OOM_DUMP_DIR, RETRY_ENABLED, RETRY_MAX_RETRIES,
                          RETRY_SPLIT_FLOOR_ROWS, INJECT_OOM_MODE,
                          INJECT_OOM_SEED, INJECT_OOM_SKIP_COUNT,
                          INJECT_OOM_OOM_COUNT)
    with _POLICY_LOCK:
        _POLICY.enabled = bool(conf.get(RETRY_ENABLED.key))
        _POLICY.max_retries = int(conf.get(RETRY_MAX_RETRIES.key))
        _POLICY.split_floor_rows = int(conf.get(RETRY_SPLIT_FLOOR_ROWS.key))
        _POLICY.dump_dir = str(conf.get(OOM_DUMP_DIR.key) or "")
    _INJECTOR.configure(str(conf.get(INJECT_OOM_MODE.key)),
                        int(conf.get(INJECT_OOM_SEED.key)),
                        int(conf.get(INJECT_OOM_SKIP_COUNT.key)),
                        int(conf.get(INJECT_OOM_OOM_COUNT.key)))
    # the network injector rides the same entry point (one conf-apply
    # per collect configures BOTH process-wide fault layers)
    from ..shuffle import netfault
    netfault.apply_session_conf(conf)


def set_dump_dir(path: str) -> None:
    with _POLICY_LOCK:
        _POLICY.dump_dir = path or ""


@contextmanager
def retry_policy(**overrides):
    """Test helper: temporarily override retry policy fields
    (enabled/max_retries/split_floor_rows/dump_dir)."""
    old = {k: getattr(_POLICY, k) for k in overrides}
    with _POLICY_LOCK:
        for k, v in overrides.items():
            setattr(_POLICY, k, v)
    try:
        yield
    finally:
        with _POLICY_LOCK:
            for k, v in old.items():
                setattr(_POLICY, k, v)


# ---------------------------------------------------------------------------
# spillable retry input (reference: SpillableColumnarBatch held across
# withRetry boundaries + the splitSpillableInHalfByRows split policy)
# ---------------------------------------------------------------------------

class SpillableInput:
    """A batch parked in the spill catalog while it waits for (re-)use by
    a retry body. Unpinned between attempts — under memory pressure the
    input itself spills to host/disk and unspills on the next acquire."""

    def __init__(self, sb: SpillableBatch, schema, catalog: BufferCatalog,
                 rows: int):
        self.sb = sb
        self.schema = schema
        self.catalog = catalog
        self.rows = int(rows)

    @classmethod
    def from_batch(cls, batch, schema, catalog: Optional[BufferCatalog]
                   = None) -> "SpillableInput":
        from .catalog import device_budget
        cat = catalog or device_budget()
        rows = int(batch.num_rows)
        return cls(SpillableBatch(cat, batch, schema), schema, cat, rows)

    @classmethod
    def admit(cls, batch, schema, catalog: Optional[BufferCatalog] = None,
              name: str = "admit") -> "SpillableInput":
        """from_batch under the retry loop — registration reserves budget
        and is itself an (instrumented) allocation site."""
        from .catalog import device_budget
        cat = catalog or device_budget()
        return with_retry_no_split(
            lambda: cls.from_batch(batch, schema, cat),
            catalog=cat, name=name)

    def acquire(self):
        """Materialize on device and pin; pair with release()."""
        return self.sb.get()

    def release(self) -> None:
        self.sb.done_with()

    def close(self) -> None:
        self.sb.close()

    def split(self, floor_rows: int) -> Optional[List["SpillableInput"]]:
        """Halve by rows (SplitAndRetryOOM's split policy). None when at
        the floor. Closes self on success — the halves own the rows."""
        n = self.rows
        if n <= max(int(floor_rows), 1) or n < 2:
            return None
        import jax.numpy as jnp
        from ..batch import bucket_capacity
        from ..exec.common import slice_batch
        import jax
        mid = n // 2
        b = self.acquire()
        try:
            slicer = jax.jit(slice_batch, static_argnums=3)
            left = slicer(b, jnp.int32(0), jnp.int32(mid),
                          bucket_capacity(mid))
            right = slicer(b, jnp.int32(mid), jnp.int32(n - mid),
                           bucket_capacity(n - mid))
        finally:
            self.release()
        # register the halves transactionally: each registration reserves
        # budget and runs at peak pressure — an OOM on the right half
        # must close the already-registered left half, not leak it
        left_si = SpillableInput.from_batch(left, self.schema, self.catalog)
        try:
            right_si = SpillableInput.from_batch(right, self.schema,
                                                 self.catalog)
        except BaseException:
            left_si.close()
            raise
        self.close()
        return [left_si, right_si]


def admit_all(batches, schema, catalog: Optional[BufferCatalog] = None,
              name: str = "admit") -> List[SpillableInput]:
    """``SpillableInput.admit`` over a sequence, transactionally: if a
    later admit raises (final OOM, anything non-retryable), the already-
    admitted handles are closed before the error propagates — no
    ownerless catalog entries."""
    out: List[SpillableInput] = []
    try:
        for b in batches:
            out.append(SpillableInput.admit(b, schema, catalog, name=name))
    except BaseException:
        for si in out:
            si.close()
        raise
    return out


def split_input_halves(item):
    """Default split policy for with_retry: halve a SpillableInput (or
    anything with ``.split(floor_rows)``, e.g. a host-table wrapper) down
    to spark.rapids.tpu.retry.splitFloorRows."""
    return item.split(_POLICY.split_floor_rows)


def presplit_inputs(inp, target_rows: int,
                    split: Callable = split_input_halves) -> List:
    """Adaptive pre-split: cut an input measured over ``target_rows``
    rows into in-order chunks BEFORE the first device attempt, using
    the same split policy with_retry applies on OOM. A skew re-plan
    that already measured one hot batch far over the row target should
    not have to burn OOM attempts to discover what the shuffle
    statistics already said; the split floor still bounds recursion.
    Inputs without a ``rows`` measure pass through untouched."""
    work, out = deque([inp]), []
    while work:
        item = work.popleft()
        rows = getattr(item, "rows", None)
        if rows is not None and rows > target_rows:
            halves = split(item)
            if halves:
                _METRICS.note_presplit(getattr(item, "name", "presplit"))
                for h in reversed(halves):
                    work.appendleft(h)
                continue
        out.append(item)
    return out


def split_host_table(t):
    """Split policy for host-side (pyarrow) tables at the H2D boundary:
    device_put of half the rows needs half the fresh HBM. Zero-copy
    slices; row order is preserved so the device batches concatenate
    bit-for-bit with the unsplit path."""
    n = t.num_rows
    if n <= max(_POLICY.split_floor_rows, 1) or n < 2:
        return None
    mid = n // 2
    return [t.slice(0, mid), t.slice(mid)]


# ---------------------------------------------------------------------------
# the retry state machine
# ---------------------------------------------------------------------------

def _recover(cat: BufferCatalog, pin_snapshot, attempt: int,
             semaphore) -> None:
    """Between attempts: release the pins the failed attempt took, force
    the store to spill, and back off while other semaphore holders drain
    (reference: the block/spill state transitions in RmmSpark's per-task
    state machine)."""
    from ..trace import span as _trace_span
    cat.restore_pins(pin_snapshot)
    spill0 = cat.spilled_to_host + cat.spilled_to_disk
    cat.synchronous_spill(max(cat.device_used, 1))
    spilled = cat.spilled_to_host + cat.spilled_to_disk - spill0
    _METRICS.note_spill(spilled)
    # bounded exponential backoff; release the admission semaphore across
    # the sleep so concurrent tasks can finish and free device memory.
    # The span makes retry stalls attributable on a query's timeline —
    # "14 seconds" spent here is OOM pressure, not operator work.
    delay = min(0.001 * (1 << min(attempt, 6)), 0.05)
    t0 = time.perf_counter_ns()
    with _trace_span("retry.backoff", kind="retry", attempt=attempt,
                     spillBytes=int(spilled)):
        depth = 0
        if semaphore is not None:
            depth = semaphore.held_depth()
            for _ in range(depth):
                semaphore.release_if_held()
        try:
            time.sleep(delay)
        finally:
            if semaphore is not None:
                for _ in range(depth):
                    semaphore.acquire_if_necessary()
    _METRICS.note_block(time.perf_counter_ns() - t0)


def _close_item(item) -> None:
    close = getattr(item, "close", None)
    if close is not None:
        try:
            close()
        except Exception:
            pass


def _final_oom(exc: BaseException, cat: BufferCatalog, name: str,
               semaphore, attempts: int) -> FinalOOMError:
    path = write_oom_dump(cat, semaphore=semaphore, op=name, exc=exc)
    suffix = f"; state dumped to {path}" if path else \
        " (set spark.rapids.tpu.memory.oomDumpDir for a state dump)"
    return FinalOOMError(
        f"{name}: device OOM survived {attempts} attempts (pins released, "
        f"store spilled, input at split floor): {exc}{suffix}", path)


def with_retry(inp, body: Callable, split: Optional[Callable] = None,
               *, catalog: Optional[BufferCatalog] = None, name: str = "op",
               max_retries: Optional[int] = None, semaphore=None,
               close_input: bool = True,
               cancelled: Optional[Callable[[], bool]] = None,
               presplit_rows: Optional[int] = None):
    """Generator: run ``body`` over ``inp`` and whatever ``split`` makes
    of it under OOM, yielding each result in input-row order.

    On a retryable OOM the attempt's catalog pins are released (snapshot/
    restore), the store spills, and the body re-runs; a second OOM on the
    same item invokes ``split(item)`` (halves re-enter the queue in
    order, so concatenated results are bit-for-bit the no-OOM output).
    ``body`` must be re-runnable and must undo its OWN partial side
    effects (e.g. close staged catalog handles) before letting a
    retryable OOM propagate — the framework restores pins, not arbitrary
    state. Items are closed after use when ``close_input`` (and on any
    raise), matching withRetry's ownership of its spillable input.

    ``cancelled`` (optional) is polled before every attempt: a retry
    storm must not ride out its whole backoff budget after the server
    already cancelled the query (stop()/watchdog during a lineage
    recompute) — the loop raises RetryCancelledError instead of
    re-running the body.

    ``presplit_rows`` (optional, the adaptive skew-join seam): an input
    measuring over this many rows is split through the SAME machinery
    BEFORE its first attempt, so a re-planned hot partition whose one
    giant batch the shuffle statistics already measured never has to
    OOM its way down to a workable size."""
    cat = catalog
    if cat is None:
        from .catalog import device_budget
        cat = device_budget()
    if max_retries is None:
        max_retries = _POLICY.max_retries
    if semaphore is None:
        # default to the process admission semaphore: a retrying holder
        # must drain its slot across the backoff so concurrent tasks can
        # finish and free HBM (no-op for threads that hold nothing)
        from .semaphore import global_semaphore
        semaphore = global_semaphore()
    if presplit_rows is not None and presplit_rows > 0 and \
            split is not None and _POLICY.enabled:
        work = deque(presplit_inputs(inp, presplit_rows, split))
    else:
        work = deque([inp])
    try:
        while work:
            item = work.popleft()
            attempt = 0
            while True:
                if cancelled is not None and cancelled():
                    _close_item(item)
                    raise RetryCancelledError(
                        f"{name}: cancelled before attempt "
                        f"{attempt + 1} — the query was stopped while "
                        f"its retry loop was recovering")
                snap = cat.pin_snapshot()
                try:
                    if attempt == 0 or not _POLICY.enabled:
                        result = body(item)
                    else:
                        # re-attempts never start NEW injected triggers —
                        # recovery must converge (pending consecutive
                        # OOMs from oomCount still fire)
                        with _INJECTOR.suppressed():
                            result = body(item)
                except BaseException as e:
                    # every failed attempt gives back the pins it took —
                    # also on the non-retryable path, so a body that dies
                    # mid-pin-loop cannot strand batches unspillable
                    # (restore is a no-op for pins the body released
                    # itself before raising)
                    cat.restore_pins(snap)
                    if not (_POLICY.enabled and is_retryable_oom(e)):
                        _close_item(item)
                        raise
                    attempt += 1
                    _METRICS.note_retry(name)
                    halves = None
                    if attempt >= 2 and split is not None:
                        # split() re-acquires the full batch and registers
                        # the halves — allocations at peak pressure. An
                        # OOM inside it is one more failed attempt (spill,
                        # back off, try again), NOT an escape from the
                        # state machine.
                        try:
                            with _INJECTOR.suppressed():
                                halves = split(item)
                        except BaseException as se:
                            if not is_retryable_oom(se):
                                _close_item(item)
                                raise
                        if halves:
                            _METRICS.note_split(name)
                            for h in reversed(halves):
                                work.appendleft(h)
                            break   # halves are fresh items
                    if attempt > max_retries:
                        _close_item(item)
                        raise _final_oom(e, cat, name, semaphore,
                                         attempt) from e
                    _recover(cat, snap, attempt, semaphore)
                else:
                    if close_input:
                        _close_item(item)
                    yield result
                    break
    except BaseException:
        while work:                      # free queued spillable inputs
            _close_item(work.popleft())
        raise


class _NoInput:
    """Sentinel input for with_retry_no_split (nothing to close/split)."""

    def __repr__(self):
        return "<no-input>"


_NO_INPUT = _NoInput()


def with_retry_no_split(body: Callable, *, catalog: Optional[BufferCatalog]
                        = None, name: str = "op",
                        max_retries: Optional[int] = None, semaphore=None,
                        cancelled: Optional[Callable[[], bool]] = None):
    """Run a no-argument ``body`` under the retry loop (no split policy:
    final merges, broadcast builds, single acquires). Returns the body's
    result (reference: withRetryNoSplit)."""
    return next(with_retry(_NO_INPUT, lambda _i: body(), split=None,
                           catalog=catalog, name=name,
                           max_retries=max_retries, semaphore=semaphore,
                           close_input=False, cancelled=cancelled))


def acquire_with_retry(sb: SpillableBatch, *, catalog: Optional[BufferCatalog]
                       = None, name: str = "acquire"):
    """Pin a spillable handle under the retry loop — the unspill path
    reserves device budget and can itself OOM."""
    return with_retry_no_split(sb.get, catalog=catalog or sb.catalog,
                               name=name)


def register_with_retry(batch, schema, *, catalog: Optional[BufferCatalog]
                        = None, name: str = "register",
                        priority: int = 0) -> SpillableBatch:
    """SpillableBatch registration under the retry loop — register()
    reserves budget for the new handle and can OOM under pressure."""
    cat = catalog
    if cat is None:
        from .catalog import device_budget
        cat = device_budget()
    return with_retry_no_split(
        lambda: SpillableBatch(cat, batch, schema, priority),
        catalog=cat, name=name)


# ---------------------------------------------------------------------------
# final-OOM state dump (spark.rapids.tpu.memory.oomDumpDir; reference:
# spark.rapids.memory.gpu.oomDumpDir heap/state dumps on alloc failure)
# ---------------------------------------------------------------------------

def write_oom_dump(catalog: BufferCatalog, semaphore=None,
                   op: Optional[str] = None, exc: Optional[BaseException]
                   = None, dump_dir: Optional[str] = None) -> Optional[str]:
    """Write the post-retry OOM report. Returns the path, or None when no
    dump dir is configured (or the write itself fails — a dump must never
    mask the original OOM)."""
    d = dump_dir if dump_dir is not None else _POLICY.dump_dir
    if not d:
        return None
    try:
        os.makedirs(d, exist_ok=True)
        path = os.path.join(
            d, f"oom-{os.getpid()}-{threading.get_ident()}-"
               f"{int(time.time() * 1000)}.txt")
        lines = ["=== spark-rapids-tpu OOM dump ==="]
        if op:
            lines.append(f"operator: {op}")
        if exc is not None:
            lines.append(f"error: {type(exc).__name__}: {exc}")
        lines.append("")
        lines.append("--- catalog tier occupancy ---")
        lines.append(catalog.tier_summary())
        lines.append("")
        lines.append("--- catalog entries (pinned handles marked) ---")
        lines.append(catalog.dump_state())
        lines.append("")
        lines.append("--- retry/split counts per operator ---")
        snap = _METRICS.snapshot()
        lines.append(f"total: retries={snap['retryCount']} "
                     f"splits={snap['splitAndRetryCount']} "
                     f"blockTimeNs={snap['retryBlockTime']} "
                     f"spillBytes={snap['retrySpillBytes']}")
        for nm, (r, s) in sorted(_METRICS.per_op.items()):
            lines.append(f"  {nm}: retries={r} splits={s}")
        lines.append("")
        lines.append("--- semaphore holders ---")
        if semaphore is not None:
            holders = semaphore.holders()
            lines.append(f"max_concurrent={semaphore.max_concurrent} "
                         f"wait_time_ns={semaphore.wait_time_ns}")
            for tid, depth in holders.items():
                lines.append(f"  thread {tid}: depth {depth}")
        else:
            lines.append("(no semaphore in scope)")
        with open(path, "w") as f:
            f.write("\n".join(lines) + "\n")
        return path
    except Exception:
        return None
