"""Memory runtime: admission control, HBM budgeting, tiered spill.

Reference: SURVEY.md §2.3 — GpuSemaphore.scala:115 (N concurrent device
tasks), RapidsBufferCatalog.scala:58 (handle registry), RapidsBufferStore
tiers DEVICE/HOST/DISK (RapidsBuffer.scala:53), DeviceMemoryEventHandler
(RMM alloc-failure → synchronous spill), SpillableColumnarBatch.

The TPU twist (SURVEY.md §7 hard parts): there is no RMM-style allocator
callback to trap — XLA owns HBM. So the design inverts: a RESERVATION
budget sits above the runtime; operators reserve before materializing,
and a failed reservation synchronously spills lower-priority registered
buffers device→host→disk until the reservation fits. Same catalog/tier
shape as the reference, pull- instead of push-triggered.
"""

from .semaphore import TpuSemaphore
from .catalog import (BufferCatalog, OutOfBudgetError, SpillableBatch,
                      StorageTier, device_budget)
from .retry import (FinalOOMError, InjectedOOMError, SpillableInput,
                    acquire_with_retry, admit_all, is_retryable_oom,
                    maybe_inject, oom_injection, register_with_retry,
                    split_input_halves, with_retry, with_retry_no_split)

__all__ = [n for n in dir() if not n.startswith("_")]
