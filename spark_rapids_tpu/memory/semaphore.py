"""Device-task admission control.

Reference: GpuSemaphore.scala:115 — tasks acquire before first touching the
device and release around blocking I/O, bounding concurrent HBM footprints
(spark.rapids.sql.concurrentGpuTasks=2). Same contract here for the
host-threaded parts of the engine (multi-file readers, shuffle writers):
XLA executes one program at a time per chip, but host threads staging H2D
buffers still multiply peak memory.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Dict, Optional


class TpuSemaphore:
    def __init__(self, max_concurrent: int = 2):
        self._sem = threading.BoundedSemaphore(max_concurrent)
        self._holders: Dict[int, int] = {}      # thread id -> depth
        self._lock = threading.Lock()
        self.max_concurrent = max_concurrent
        self.wait_time_ns = 0

    def acquire_if_necessary(self) -> None:
        """Re-entrant per thread (a task acquires once; reference
        GpuSemaphore.acquireIfNecessary)."""
        tid = threading.get_ident()
        with self._lock:
            if self._holders.get(tid, 0) > 0:
                self._holders[tid] += 1
                return
        import time
        t0 = time.perf_counter_ns()
        self._sem.acquire()
        self.wait_time_ns += time.perf_counter_ns() - t0
        with self._lock:
            self._holders[tid] = 1

    def release_if_held(self) -> None:
        tid = threading.get_ident()
        with self._lock:
            d = self._holders.get(tid, 0)
            if d == 0:
                return
            if d > 1:
                self._holders[tid] = d - 1
                return
            del self._holders[tid]
        self._sem.release()

    def held_depth(self) -> int:
        """The calling thread's re-entrant hold depth (0 = not a holder).
        The retry state machine releases this many times before backing
        off so other holders can drain, then re-acquires."""
        with self._lock:
            return self._holders.get(threading.get_ident(), 0)

    def holders(self) -> Dict[int, int]:
        """{thread id: depth} of current holders (oomDumpDir report)."""
        with self._lock:
            return dict(self._holders)

    @contextmanager
    def task(self):
        self.acquire_if_necessary()
        try:
            yield
        finally:
            self.release_if_held()


_GLOBAL: Optional[TpuSemaphore] = None
_GLOBAL_LOCK = threading.Lock()


def global_semaphore(max_concurrent: int = 2) -> TpuSemaphore:
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = TpuSemaphore(max_concurrent)
        return _GLOBAL
