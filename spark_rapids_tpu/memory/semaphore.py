"""Device-task admission control.

Reference: GpuSemaphore.scala:115 — tasks acquire before first touching the
device and release around blocking I/O, bounding concurrent HBM footprints
(spark.rapids.sql.concurrentGpuTasks=2). Same contract here for the
host-threaded parts of the engine (multi-file readers, shuffle writers):
XLA executes one program at a time per chip, but host threads staging H2D
buffers still multiply peak memory.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Dict, Optional


class TpuSemaphore:
    def __init__(self, max_concurrent: int = 2):
        self._sem = threading.BoundedSemaphore(max_concurrent)
        self._holders: Dict[int, int] = {}      # thread id -> depth
        self._lock = threading.Lock()
        self.max_concurrent = max_concurrent
        self.wait_time_ns = 0

    def acquire_if_necessary(self) -> None:
        """Re-entrant per thread (a task acquires once; reference
        GpuSemaphore.acquireIfNecessary)."""
        tid = threading.get_ident()
        with self._lock:
            if self._holders.get(tid, 0) > 0:
                self._holders[tid] += 1
                return
        import time
        t0 = time.perf_counter_ns()
        self._sem.acquire()
        self.wait_time_ns += time.perf_counter_ns() - t0
        with self._lock:
            self._holders[tid] = 1

    def release_if_held(self) -> None:
        tid = threading.get_ident()
        with self._lock:
            d = self._holders.get(tid, 0)
            if d == 0:
                return
            if d > 1:
                self._holders[tid] = d - 1
                return
            del self._holders[tid]
        self._sem.release()

    def held_depth(self) -> int:
        """The calling thread's re-entrant hold depth (0 = not a holder).
        The retry state machine releases this many times before backing
        off so other holders can drain, then re-acquires."""
        with self._lock:
            return self._holders.get(threading.get_ident(), 0)

    def holders(self) -> Dict[int, int]:
        """{thread id: depth} of current holders (oomDumpDir report)."""
        with self._lock:
            return dict(self._holders)

    @contextmanager
    def task(self):
        self.acquire_if_necessary()
        try:
            yield
        finally:
            self.release_if_held()


class AdmissionCancelledError(RuntimeError):
    """The caller's cancel flag fired while waiting for admission."""


class _AdmitWaiter:
    __slots__ = ("affinity", "enqueued")

    def __init__(self, affinity: frozenset):
        import time
        self.affinity = affinity
        self.enqueued = time.monotonic()


class QueryAdmission:
    """Serving-tier per-query admission (plan server): a collect-slot
    semaphore (``spark.rapids.tpu.server.concurrentCollects``) plus a
    per-query device-memory reservation against the buffer catalog.

    The slot bounds how many collects are in flight over one device so
    independent tenants overlap H2D/compute/D2H; the reservation makes a
    query's footprint visible to the catalog BEFORE it allocates, so
    admission — not the middle of a kernel — is where spill pressure is
    applied. Inside the collect the PR 7 retry machinery still owns the
    fine-grained story: on OOM it drains the process TpuSemaphore across
    its backoff and re-runs, with this query's reservation already
    counted in the budget it retries against."""

    #: a waiter with scan affinity may be admitted ahead of the queue
    #: head only while the head has waited less than this (starvation
    #: bound for the affinity preference)
    HEAD_MAX_SKIP_S = 0.5

    def __init__(self, max_concurrent: int, catalog=None):
        self.max_concurrent = max(1, int(max_concurrent))
        self._catalog = catalog
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._slots = self.max_concurrent
        self._waiters: list = []       # FIFO of _AdmitWaiter
        self._active_digests: Dict[str, int] = {}
        self.wait_time_ns = 0          # slot + reservation wait, summed
        self.admitted_count = 0
        self.in_flight = 0
        #: admissions granted while sharing ≥1 scan digest with an
        #: already-admitted query (cross-query scan-share overlap)
        self.affinity_batched = 0

    def _cat(self):
        if self._catalog is None:
            from .catalog import device_budget
            self._catalog = device_budget()
        return self._catalog

    def _pick_locked(self):
        """The next waiter a free slot goes to: the FIFO head, unless a
        later waiter shares a scan digest with an in-flight query (it
        rides the live upload — docs/serving.md scan-affinity batching)
        AND the head has not waited past the starvation bound."""
        import time
        head = self._waiters[0]
        if self._active_digests:
            if time.monotonic() - head.enqueued < self.HEAD_MAX_SKIP_S:
                for w in self._waiters:
                    if w.affinity and not \
                            w.affinity.isdisjoint(self._active_digests):
                        return w
        return head

    @contextmanager
    def admit(self, reserve_bytes: int = 0,
              cancelled: Optional[callable] = None,
              poll_s: float = 0.01, affinity=()):
        """Block until a slot AND the reservation are both held; a true
        ``cancelled()`` while waiting raises AdmissionCancelledError.
        Reservation failures (OutOfBudgetError after spilling) back off
        and retry — admission pressure queues, it does not fail the
        query. ``affinity`` (scan content digests) batches waiters next
        to in-flight queries over the same tables."""
        import time

        from ..trace import span as _trace_span
        from .catalog import OutOfBudgetError
        # an explicit reservation larger than the whole device budget
        # could never be satisfied — the wait loop would spin forever
        # holding a collect slot; cap it (the reservation is admission
        # accounting, not a guarantee of exclusive HBM)
        reserve_bytes = min(int(reserve_bytes), self._cat().device_limit)
        t0 = time.perf_counter_ns()
        # the admission wait is its own span, closed the moment the
        # query is admitted: "where did this query's time go" must
        # separate queueing behind other tenants from execution
        wait_span = _trace_span("admission.wait", kind="admission",
                                reserveBytes=int(reserve_bytes))
        wait_span.__enter__()
        wait_open = True
        reserved = 0
        acquired_slot = False
        waiter = _AdmitWaiter(frozenset(affinity or ()))
        try:
            with self._cond:
                self._waiters.append(waiter)
                while not (self._slots > 0
                           and self._pick_locked() is waiter):
                    self._cond.wait(poll_s)
                    if cancelled is not None and cancelled():
                        self._waiters.remove(waiter)
                        self._cond.notify_all()
                        self.wait_time_ns += \
                            time.perf_counter_ns() - t0
                        raise AdmissionCancelledError(
                            "cancelled while waiting for a collect slot")
                self._slots -= 1
                self._waiters.remove(waiter)
                if waiter.affinity and not waiter.affinity.isdisjoint(
                        self._active_digests):
                    self.affinity_batched += 1
                    from ..plan import sharing
                    sharing.metrics().note("affinity_batched")
                for d in waiter.affinity:
                    self._active_digests[d] = \
                        self._active_digests.get(d, 0) + 1
                self._cond.notify_all()
            acquired_slot = True
            while reserve_bytes > 0:
                if cancelled is not None and cancelled():
                    # count the aborted wait too: long waits are exactly
                    # the ones the admission-pressure metric must see
                    self._note_wait(t0)
                    raise AdmissionCancelledError(
                        "cancelled while waiting for the memory "
                        "reservation")
                try:
                    self._cat().reserve(reserve_bytes)
                    reserved = reserve_bytes
                    break
                except OutOfBudgetError:  # retry-ok: admission QUEUES on
                    # budget pressure by design — the query has not
                    # started, there are no pins to restore, and the
                    # retry framework takes over once it executes
                    time.sleep(poll_s)
            self._note_wait(t0)
            wait_span.__exit__(None, None, None)
            wait_open = False
            with self._lock:
                self.admitted_count += 1
                self.in_flight += 1
            try:
                yield
            finally:
                with self._lock:
                    self.in_flight -= 1
        finally:
            if wait_open:
                wait_span.__exit__(None, None, None)
            if reserved:
                self._cat().unreserve(reserved)
            if acquired_slot:
                with self._cond:
                    self._slots += 1
                    for d in waiter.affinity:
                        left = self._active_digests.get(d, 0) - 1
                        if left > 0:
                            self._active_digests[d] = left
                        else:
                            self._active_digests.pop(d, None)
                    self._cond.notify_all()

    def _note_wait(self, t0: int) -> None:
        import time
        with self._lock:
            self.wait_time_ns += time.perf_counter_ns() - t0


_GLOBAL: Optional[TpuSemaphore] = None
_GLOBAL_LOCK = threading.Lock()


def global_semaphore(max_concurrent: int = 2) -> TpuSemaphore:
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = TpuSemaphore(max_concurrent)
        return _GLOBAL
