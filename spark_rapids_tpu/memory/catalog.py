"""Tiered buffer catalog with reservation-triggered spill.

Reference: RapidsBufferCatalog.scala:58,352 (handle registry, tier lookup,
synchronousSpill), RapidsBufferStore.scala:42 (priority-ordered eviction),
RapidsDeviceMemoryStore/HostMemoryStore/DiskStore, SpillableColumnarBatch
(SpillableColumnarBatch.scala:28 — operators make held batches spillable
between uses). GDS tier intentionally omitted (no TPU twin; SURVEY.md §2.9).

Tiers:
  DEVICE — live jax arrays (HBM via the runtime)
  HOST   — one contiguous PackedTable per batch (memory/packed.py),
           bounded by host_limit
  DISK   — .npz files under the spill dir

Spill priority: smaller value spills FIRST (matches the reference's
convention where active-use buffers get higher priority).
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from enum import Enum
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np

from ..batch import ColumnarBatch, DeviceColumn, Schema
from .. import types as T

_maybe_inject = None


def _inject(site: str) -> None:
    """Late-bound hook into retry.maybe_inject (retry.py imports this
    module, so the reference is resolved on first use, not at import) —
    reserve/acquire are the hottest allocation paths and must not pay a
    sys.modules lookup per call."""
    global _maybe_inject
    if _maybe_inject is None:
        from .retry import maybe_inject
        _maybe_inject = maybe_inject
    _maybe_inject(site)


class StorageTier(Enum):
    DEVICE = 0
    HOST = 1
    DISK = 2


class OutOfBudgetError(MemoryError):
    pass


@dataclass
class _Entry:
    handle_id: int
    tier: StorageTier
    size: int
    priority: int
    batch: Optional[ColumnarBatch] = None          # DEVICE
    host: Optional[object] = None      # HOST: PackedTable (one buffer)
    path: Optional[str] = None                     # DISK
    schema: Optional[Schema] = None
    pinned: int = 0
    origin: Optional[str] = None                   # leak tracking: creator


class LeakError(RuntimeError):
    """Catalog handles outlived their owner (reference: cudf
    MemoryCleaner refcount leak checks + RapidsBufferStore double-free
    asserts)."""


class DoubleReleaseError(RuntimeError):
    """release() on an unpinned handle — an Arm-discipline violation the
    reference's refcounted buffers turn into a hard assert."""


class BufferCatalog:
    def __init__(self, device_limit: int = 8 << 30,
                 host_limit: int = 4 << 30,
                 spill_dir: str = "/tmp/rapids_tpu_spill",
                 track_leaks: bool = False):
        self.device_limit = device_limit
        self.host_limit = host_limit
        self.spill_dir = spill_dir
        self._entries: Dict[int, _Entry] = {}
        self._next = 0
        self._lock = threading.RLock()
        #: per-thread pin multiset {tid: {hid: count}} — the retry state
        #: machine snapshots/restores a task's pins between attempts
        #: (reference: RmmSpark per-thread state + SpillFramework pins)
        self._thread_pins: Dict[int, Dict[int, int]] = {}
        self.device_used = 0
        self.host_used = 0
        self.spilled_to_host = 0
        self.spilled_to_disk = 0
        # leak tracking (reference: MemoryCleaner): record who registered
        # each handle so leak_check can name the culprit. Off by default —
        # capturing stacks costs time on the hot path.
        self.track_leaks = track_leaks

    # ------------------------------------------------------------------
    # registration / reservation
    # ------------------------------------------------------------------

    def register(self, batch: ColumnarBatch, schema: Schema,
                 priority: int = 0) -> int:
        size = batch.size_bytes()
        origin = None
        if self.track_leaks:
            import traceback
            # the closest non-catalog frame is the owner
            for f in reversed(traceback.extract_stack(limit=8)[:-1]):
                if "memory/catalog" not in f.filename:
                    origin = f"{f.filename}:{f.lineno} in {f.name}"
                    break
        with self._lock:
            self.reserve(size)
            hid = self._next
            self._next += 1
            self._entries[hid] = _Entry(hid, StorageTier.DEVICE, size,
                                        priority, batch=batch, schema=schema,
                                        origin=origin)
            return hid

    def reserve(self, nbytes: int) -> None:
        """Ensure nbytes of device budget, spilling if necessary
        (reference: DeviceMemoryEventHandler.onAllocFailure, inverted)."""
        # deterministic fault injection: every budget reservation is an
        # instrumented allocation site (mirror of RmmSpark's injected OOM
        # at the allocator). No-op unless a test enabled injection.
        _inject("catalog.reserve")
        with self._lock:
            if self.device_used + nbytes <= self.device_limit:
                self.device_used += nbytes
                return
            need = self.device_used + nbytes - self.device_limit
            freed = self.synchronous_spill(need)
            if self.device_used + nbytes > self.device_limit:
                raise OutOfBudgetError(
                    f"cannot reserve {nbytes}b: used {self.device_used}b of "
                    f"{self.device_limit}b after spilling {freed}b")
            self.device_used += nbytes

    def unreserve(self, nbytes: int) -> None:
        with self._lock:
            self.device_used = max(0, self.device_used - nbytes)

    # ------------------------------------------------------------------
    # spill machinery
    # ------------------------------------------------------------------

    def synchronous_spill(self, need: int) -> int:
        """Spill unpinned device buffers in priority order until `need`
        bytes are freed (or no candidates remain). Returns bytes freed."""
        freed = 0
        with self._lock:
            victims = sorted(
                [e for e in self._entries.values()
                 if e.tier is StorageTier.DEVICE and e.pinned == 0],
                key=lambda e: e.priority)
            for e in victims:
                if freed >= need:
                    break
                self._spill_to_host(e)
                freed += e.size
        return freed

    def _spill_to_host(self, e: _Entry) -> None:
        from ..shuffle.serializer import batch_to_arrays
        host = batch_to_arrays(e.batch)       # struct leaves recurse
        host["n"] = np.asarray(jax.device_get(e.batch.num_rows))
        # ONE contiguous allocation per spilled batch (reference:
        # contiguous-split packed tables / MetaUtils TableMeta) — the
        # pinned-staging shape DMA wants, resliceable without reparsing
        from .packed import PackedTable
        e.host = PackedTable.pack(
            host, int(np.asarray(host["n"]).reshape(-1)[0]))
        e.batch = None
        e.tier = StorageTier.HOST
        self.device_used = max(0, self.device_used - e.size)
        self.host_used += e.size
        self.spilled_to_host += e.size
        if self.host_used > self.host_limit:
            self._overflow_host_to_disk()

    def _overflow_host_to_disk(self) -> None:
        victims = sorted(
            [e for e in self._entries.values()
             if e.tier is StorageTier.HOST and e.pinned == 0],
            key=lambda e: e.priority)
        for e in victims:
            if self.host_used <= self.host_limit:
                break
            os.makedirs(self.spill_dir, exist_ok=True)
            path = os.path.join(self.spill_dir, f"buf-{e.handle_id}.rtpu")
            # serialize-once: frame straight from the packed host buffer
            # (the pack at spill time WAS the serialization; re-flattening
            # per array here doubled the host-boundary copies)
            from ..shuffle.serializer import frame_packed
            with open(path, "wb") as f:
                f.write(frame_packed(e.host))
            e.path = path
            e.host = None
            e.tier = StorageTier.DISK
            self.host_used = max(0, self.host_used - e.size)
            self.spilled_to_disk += e.size

    # ------------------------------------------------------------------
    # access
    # ------------------------------------------------------------------

    def acquire(self, hid: int) -> ColumnarBatch:
        """Materialize a handle on device (unspilling as needed) and pin it."""
        # every pin is an instrumented allocation site too: pinning an
        # already-device buffer extends its residency (the exchange
        # pack/pin loops), and the unspill path reserves fresh budget
        _inject("catalog.acquire")
        with self._lock:
            e = self._entries[hid]
            if e.tier is not StorageTier.DEVICE:
                self.reserve(e.size)
                if e.tier is StorageTier.DISK:
                    from ..shuffle.serializer import deserialize_host
                    from .packed import PackedTable
                    with open(e.path, "rb") as f:
                        arrays, n = deserialize_host(f.read())
                    e.host = PackedTable.pack(arrays, n)
                    os.remove(e.path)
                    e.path = None
                    e.tier = StorageTier.HOST
                    self.host_used += e.size
                e.batch = self._host_to_device(e)
                self.host_used = max(0, self.host_used - e.size)
                e.host = None
                e.tier = StorageTier.DEVICE
            e.pinned += 1
            self._note_pin(hid, +1)
            return e.batch

    def _host_to_device(self, e: _Entry) -> ColumnarBatch:
        import jax.numpy as jnp
        from ..shuffle.serializer import _col_from_arrays
        host = e.host.arrays()      # zero-copy views into ONE buffer
        cols = [_col_from_arrays(f.dtype, str(i), host)
                for i, f in enumerate(e.schema)]
        return ColumnarBatch(tuple(cols),
                             jnp.asarray(host["n"], jnp.int32))

    def release(self, hid: int) -> None:
        with self._lock:
            e = self._entries[hid]
            if e.pinned <= 0:
                raise DoubleReleaseError(
                    f"handle #{hid} released while unpinned"
                    + (f" (registered at {e.origin})" if e.origin else ""))
            e.pinned -= 1
            self._note_pin(hid, -1)

    def remove(self, hid: int) -> None:
        with self._lock:
            e = self._entries.pop(hid, None)
            if e is None:
                return
            for tp in self._thread_pins.values():
                tp.pop(hid, None)
            if e.tier is StorageTier.DEVICE:
                self.device_used = max(0, self.device_used - e.size)
            elif e.tier is StorageTier.HOST:
                self.host_used = max(0, self.host_used - e.size)
            elif e.path:
                try:
                    os.remove(e.path)
                except OSError:
                    pass

    def tier_of(self, hid: int) -> StorageTier:
        return self._entries[hid].tier

    # ------------------------------------------------------------------
    # per-thread pin accounting (retry-state-machine support; reference:
    # the task-thread pin registry RmmSpark keeps so blocked/retrying
    # tasks can release everything they hold)
    # ------------------------------------------------------------------

    def _note_pin(self, hid: int, delta: int) -> None:
        """Record a pin/unpin against the calling thread (under _lock)."""
        tid = threading.get_ident()
        tp = self._thread_pins.setdefault(tid, {})
        c = tp.get(hid, 0) + delta
        if c <= 0:
            tp.pop(hid, None)
            if not tp:
                self._thread_pins.pop(tid, None)
        else:
            tp[hid] = c

    def pin_snapshot(self) -> Dict[int, int]:
        """The calling thread's current pin multiset {hid: count}."""
        with self._lock:
            return dict(self._thread_pins.get(threading.get_ident(), {}))

    def restore_pins(self, snapshot: Dict[int, int]) -> None:
        """Release every pin the calling thread took SINCE ``snapshot``
        (a failed retry attempt's pins) so held batches become spillable
        again. Pins a body already released itself are not re-released;
        handles the body removed are skipped."""
        with self._lock:
            current = dict(self._thread_pins.get(threading.get_ident(), {}))
            for hid, cnt in current.items():
                excess = cnt - snapshot.get(hid, 0)
                for _ in range(excess):
                    e = self._entries.get(hid)
                    if e is None or e.pinned <= 0:
                        break
                    e.pinned -= 1
                    self._note_pin(hid, -1)

    def total_pinned(self) -> int:
        """Sum of pin counts over all handles (0 = everything spillable;
        the invariant tests assert at session close)."""
        with self._lock:
            return sum(e.pinned for e in self._entries.values())

    def tier_summary(self) -> str:
        """One line per tier: entry count + registered bytes, plus the
        budget headroom (the oomDumpDir occupancy section)."""
        with self._lock:
            per = {t: [0, 0] for t in StorageTier}
            pinned = 0
            for e in self._entries.values():
                per[e.tier][0] += 1
                per[e.tier][1] += e.size
                if e.pinned:
                    pinned += 1
            lines = [f"device_used={self.device_used}b of "
                     f"{self.device_limit}b; host_used={self.host_used}b "
                     f"of {self.host_limit}b; pinned_handles={pinned}; "
                     f"total_pins={self.total_pinned()}"]
            for t in StorageTier:
                lines.append(f"  {t.name}: {per[t][0]} entries, "
                             f"{per[t][1]}b")
            lines.append(f"  spilled_to_host={self.spilled_to_host}b "
                         f"spilled_to_disk={self.spilled_to_disk}b")
            return "\n".join(lines)

    def host_view(self, hid: int):
        """The handle's PackedTable when it lives on the HOST tier, else
        None. Wire exporters frame spilled pieces straight from this view
        (serialize-once) instead of round-tripping them through HBM."""
        with self._lock:
            e = self._entries[hid]
            return e.host if e.tier is StorageTier.HOST else None

    # ------------------------------------------------------------------
    # leak detection (reference: cudf MemoryCleaner shutdown check +
    # Plugin.scala shutdown-hook ordering)
    # ------------------------------------------------------------------

    def leak_check(self) -> List[str]:
        """Describe every handle still registered — after a query closes
        its plan, a non-empty result is a leak."""
        with self._lock:
            return [
                f"#{e.handle_id} {e.tier.name} {e.size}b pinned={e.pinned}"
                + (f" from {e.origin}" if e.origin else "")
                for e in self._entries.values()]

    def assert_no_leaks(self) -> None:
        leaks = self.leak_check()
        if leaks:
            raise LeakError(
                f"{len(leaks)} catalog handle(s) leaked:\n  " +
                "\n  ".join(leaks))

    def dump_state(self) -> str:
        """OOM diagnostics (reference: spark.rapids.memory.gpu.oomDumpDir)."""
        with self._lock:
            lines = [f"device_used={self.device_used} "
                     f"host_used={self.host_used}"]
            for e in self._entries.values():
                lines.append(f"  #{e.handle_id} {e.tier.name} {e.size}b "
                             f"prio={e.priority} pinned={e.pinned}")
            return "\n".join(lines)


class SpillableBatch:
    """Operator-facing wrapper (reference: SpillableColumnarBatch.scala:28):
    hold between uses, get() to touch, close() when done."""

    def __init__(self, catalog: BufferCatalog, batch: ColumnarBatch,
                 schema: Schema, priority: int = 0):
        self.catalog = catalog
        self.schema = schema
        self.hid = catalog.register(batch, schema, priority)
        self._open = True

    def get(self) -> ColumnarBatch:
        assert self._open
        return self.catalog.acquire(self.hid)

    def host_view(self):
        """PackedTable view when spilled to host, else None (see
        BufferCatalog.host_view)."""
        assert self._open
        return self.catalog.host_view(self.hid)

    def done_with(self) -> None:
        """Release the pin so the batch becomes spillable again."""
        self.catalog.release(self.hid)

    def close(self) -> None:
        if self._open:
            self.catalog.remove(self.hid)
            self._open = False

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


_BUDGET: Optional[BufferCatalog] = None
_BUDGET_LOCK = threading.Lock()


def device_budget(device_limit: Optional[int] = None,
                  host_limit: Optional[int] = None,
                  spill_dir: Optional[str] = None) -> BufferCatalog:
    """Process-wide catalog (reference: RapidsBufferCatalog singleton)."""
    global _BUDGET
    with _BUDGET_LOCK:
        if _BUDGET is None:
            from ..config import (HOST_SPILL_LIMIT, RapidsTpuConf, SPILL_DIR)
            conf = RapidsTpuConf()
            _BUDGET = BufferCatalog(
                device_limit or (8 << 30),
                host_limit or conf.get(HOST_SPILL_LIMIT.key),
                spill_dir or conf.get(SPILL_DIR.key))
        return _BUDGET
