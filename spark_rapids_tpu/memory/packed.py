"""Packed host tables: one contiguous buffer + offset metadata.

Reference: the reference's contiguous-split carriers — ContiguousTable /
GpuPackedTableColumn / GpuColumnVectorFromBuffer plus the FlatBuffers
TableMeta (MetaUtils.scala) — let spill and shuffle move a whole table as
ONE buffer and reslice it without reparsing. Same design here for the
host tiers: `pack` copies a spilled batch's arrays into a single
allocation (the pinned-staging shape DMA wants), `arrays` returns
zero-copy numpy views, `split_rows` is a metadata-only contiguous split,
and `TableMeta.to_bytes` is the self-describing header a disk file or
wire frame carries next to the raw buffer.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Dict, List, Sequence, Tuple

import numpy as np

_MAGIC = b"RTPM"


@dataclass(frozen=True)
class ColumnSection:
    """One array's slot inside the packed buffer."""

    key: str                    # d{i} / v{i} / l{i} / m{i}
    dtype: str                  # numpy dtype string
    shape: Tuple[int, ...]      # rows-leading
    offset: int
    nbytes: int


@dataclass(frozen=True)
class TableMeta:
    """Self-describing layout header (the FlatBuffers TableMeta role)."""

    num_rows: int
    total_bytes: int
    sections: Tuple[ColumnSection, ...]

    def to_bytes(self) -> bytes:
        out = [_MAGIC, struct.pack("<qqI", self.num_rows, self.total_bytes,
                                   len(self.sections))]
        for s in self.sections:
            key = s.key.encode()
            dt = s.dtype.encode()
            out.append(struct.pack("<I", len(key)))
            out.append(key)
            out.append(struct.pack("<I", len(dt)))
            out.append(dt)
            out.append(struct.pack("<I", len(s.shape)))
            out.append(struct.pack(f"<{len(s.shape)}q", *s.shape))
            out.append(struct.pack("<qq", s.offset, s.nbytes))
        return b"".join(out)

    @staticmethod
    def from_bytes(data: bytes) -> "TableMeta":
        assert data[:4] == _MAGIC, "not a packed-table meta"
        num_rows, total, nsec = struct.unpack_from("<qqI", data, 4)
        pos = 4 + 20
        sections = []
        for _ in range(nsec):
            (klen,) = struct.unpack_from("<I", data, pos)
            pos += 4
            key = data[pos:pos + klen].decode()
            pos += klen
            (dlen,) = struct.unpack_from("<I", data, pos)
            pos += 4
            dt = data[pos:pos + dlen].decode()
            pos += dlen
            (ndim,) = struct.unpack_from("<I", data, pos)
            pos += 4
            shape = struct.unpack_from(f"<{ndim}q", data, pos)
            pos += 8 * ndim
            off, nb = struct.unpack_from("<qq", data, pos)
            pos += 16
            sections.append(ColumnSection(key, dt, tuple(shape), off, nb))
        return TableMeta(num_rows, total, tuple(sections))


class PackedTable:
    """Contiguous host carrier for one batch's arrays."""

    def __init__(self, meta: TableMeta, buffer):
        self.meta = meta
        self.buffer = buffer        # bytearray or memoryview-able

    @property
    def nbytes(self) -> int:
        return self.meta.total_bytes

    @classmethod
    def pack(cls, arrays: Dict[str, np.ndarray], num_rows: int
             ) -> "PackedTable":
        """Copy named arrays into ONE contiguous allocation (64-byte
        aligned sections, DMA-friendly)."""
        sections: List[ColumnSection] = []
        off = 0
        for key in sorted(arrays):
            # NOT ascontiguousarray: it promotes 0-d scalars to 1-d
            a = np.asarray(arrays[key], order="C")
            off = (off + 63) & ~63
            sections.append(ColumnSection(key, a.dtype.str, a.shape, off,
                                          a.nbytes))
            off += a.nbytes
        buf = bytearray(off)
        for s, key in zip(sections, sorted(arrays)):
            a = np.asarray(arrays[key], order="C")
            buf[s.offset:s.offset + s.nbytes] = a.tobytes()
        return cls(TableMeta(num_rows, off, tuple(sections)), buf)

    def arrays(self) -> Dict[str, np.ndarray]:
        """Zero-copy views into the shared buffer."""
        mv = memoryview(self.buffer)
        out: Dict[str, np.ndarray] = {}
        for s in self.meta.sections:
            a = np.frombuffer(mv[s.offset:s.offset + s.nbytes],
                              dtype=np.dtype(s.dtype))
            out[s.key] = a.reshape(s.shape)
        return out

    def split_rows(self, bounds: Sequence[int]) -> List["PackedTable"]:
        """Contiguous split at row bounds — METADATA ONLY, every piece
        shares this buffer (the reference's contiguousSplit handing out
        sub-tables of one device allocation). ``bounds`` are split points
        in [0, capacity]; rows-leading sections reslice by stride."""
        cuts = [0] + list(bounds) + [None]
        pieces: List[PackedTable] = []
        for lo, hi in zip(cuts, cuts[1:]):
            secs = []
            for s in self.meta.sections:
                if s.key[:1] in ("D", "e"):
                    # dictionary sections (dict strings) are CARD-leading,
                    # not rows-leading: every row piece references the
                    # whole dictionary, so replicate the section verbatim
                    secs.append(s)
                    continue
                cap = s.shape[0] if s.shape else 1
                stride = s.nbytes // max(cap, 1)
                end = hi if hi is not None else cap
                secs.append(ColumnSection(
                    s.key, s.dtype, (end - lo,) + s.shape[1:],
                    s.offset + lo * stride, (end - lo) * stride))
            rows = max(min((hi if hi is not None else self.meta.num_rows),
                           self.meta.num_rows) - lo, 0)
            pieces.append(PackedTable(
                TableMeta(rows, self.meta.total_bytes, tuple(secs)),
                self.buffer))
        return pieces
