"""Avro Object Container File reader.

Reference: sql-plugin/.../sql/rapids/GpuAvroScan.scala (1,077 LoC) +
external/avro's GpuAvroFileFormat — the reference decodes Avro blocks on
the GPU through a custom JNI parser. There is no device text/varint
parser on TPU, so the container format is decoded on the host into Arrow
(the same host-decode strategy as the CSV/JSON scans) and batches ride
the shared multi-file scan framework.

Implements the OCF spec from scratch (no avro library in the image):
header magic "Obj\\x01", metadata map (avro.schema JSON + avro.codec),
16-byte sync marker, then blocks of (row count, byte size, payload,
sync). Payload decoding covers records of null/boolean/int/long/float/
double/string/bytes/enum plus ["null", T] unions (Spark's nullable
column mapping); arrays/maps/nested records are rejected with a clear
error. Codecs: null and deflate (raw zlib).
"""

from __future__ import annotations

import io
import json
import struct
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

import pyarrow as pa

from .source import FileSource

_MAGIC = b"Obj\x01"


class AvroDecodeError(ValueError):
    pass


class _Cursor:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def read(self, n: int) -> bytes:
        b = self.buf[self.pos:self.pos + n]
        if len(b) != n:
            raise AvroDecodeError("truncated file")
        self.pos += n
        return b

    def zigzag(self) -> int:
        """Avro long: zigzag varint."""
        shift = 0
        acc = 0
        while True:
            byte = self.buf[self.pos]
            self.pos += 1
            acc |= (byte & 0x7F) << shift
            if not byte & 0x80:
                break
            shift += 7
        return (acc >> 1) ^ -(acc & 1)

    def bytes_(self) -> bytes:
        return self.read(self.zigzag())


def _read_header(cur: _Cursor) -> Tuple[dict, str, bytes]:
    if cur.read(4) != _MAGIC:
        raise AvroDecodeError("not an Avro object container file")
    meta: Dict[str, bytes] = {}
    while True:
        n = cur.zigzag()
        if n == 0:
            break
        if n < 0:       # negative count: block size follows
            n = -n
            cur.zigzag()
        for _ in range(n):
            key = cur.bytes_().decode()
            meta[key] = cur.bytes_()
    schema = json.loads(meta["avro.schema"])
    codec = meta.get("avro.codec", b"null").decode()
    sync = cur.read(16)
    return schema, codec, sync


def _field_decoder(ftype: Any) -> Tuple[Callable[[_Cursor], Any], pa.DataType]:
    """(decoder, arrow type) for one record field type."""
    if isinstance(ftype, dict):
        t = ftype.get("type")
        if t == "enum":
            symbols = ftype["symbols"]
            return (lambda c: symbols[c.zigzag()]), pa.string()
        if t in ("record", "array", "map", "fixed"):
            raise AvroDecodeError(
                f"nested Avro type {t!r} is not supported (CPU fallback "
                f"readers cannot decode it either — flatten the schema)")
        ftype = t
    if isinstance(ftype, list):        # union
        branches = [b for b in ftype if b != "null"]
        if len(ftype) != 2 or "null" not in ftype or len(branches) != 1:
            raise AvroDecodeError(f"only [null, T] unions supported: "
                                  f"{ftype}")
        inner, at = _field_decoder(branches[0])
        null_idx = ftype.index("null")

        def dec_union(c: _Cursor):
            if c.zigzag() == null_idx:
                return None
            return inner(c)
        return dec_union, at
    if ftype == "null":
        return (lambda c: None), pa.null()
    if ftype == "boolean":
        return (lambda c: c.read(1) == b"\x01"), pa.bool_()
    if ftype == "int":
        return (lambda c: c.zigzag()), pa.int32()
    if ftype == "long":
        return (lambda c: c.zigzag()), pa.int64()
    if ftype == "float":
        return (lambda c: struct.unpack("<f", c.read(4))[0]), pa.float32()
    if ftype == "double":
        return (lambda c: struct.unpack("<d", c.read(8))[0]), pa.float64()
    if ftype == "string":
        return (lambda c: c.bytes_().decode("utf-8")), pa.string()
    if ftype == "bytes":
        return (lambda c: c.bytes_()), pa.binary()
    raise AvroDecodeError(f"unsupported Avro type {ftype!r}")


def read_avro_file(path: str, columns: Optional[List[str]] = None
                   ) -> pa.Table:
    with open(path, "rb") as f:
        data = f.read()
    cur = _Cursor(data)
    schema, codec, sync = _read_header(cur)
    if schema.get("type") != "record":
        raise AvroDecodeError("top-level Avro schema must be a record")
    fields = schema["fields"]
    decoders = []
    arrow_fields = []
    for fld in fields:
        dec, at = _field_decoder(fld["type"])
        decoders.append(dec)
        arrow_fields.append(pa.field(fld["name"], at))
    names = [f["name"] for f in fields]

    cols: List[List[Any]] = [[] for _ in fields]
    while cur.pos < len(data):
        n_rows = cur.zigzag()
        n_bytes = cur.zigzag()
        payload = cur.read(n_bytes)
        if codec == "deflate":
            payload = zlib.decompress(payload, -15)
        elif codec != "null":
            raise AvroDecodeError(f"unsupported Avro codec {codec!r}")
        if cur.read(16) != sync:
            raise AvroDecodeError("sync marker mismatch (corrupt block)")
        bcur = _Cursor(payload)
        for _ in range(n_rows):
            for i, dec in enumerate(decoders):
                cols[i].append(dec(bcur))

    table = pa.table([pa.array(c, type=f.type)
                      for c, f in zip(cols, arrow_fields)], names=names)
    if columns:
        table = table.select(columns)
    return table


def write_avro_file(path: str, table: pa.Table,
                    codec: str = "null") -> None:
    """Minimal OCF writer (tests + symmetric write path). Primitive and
    nullable-primitive columns only."""
    type_map = {pa.bool_(): "boolean", pa.int32(): "int",
                pa.int64(): "long", pa.float32(): "float",
                pa.float64(): "double", pa.string(): "string",
                pa.binary(): "bytes"}
    fields = []
    for f in table.schema:
        if f.type not in type_map:
            raise AvroDecodeError(f"cannot write {f.type} to Avro")
        t = type_map[f.type]
        fields.append({"name": f.name,
                       "type": ["null", t] if f.nullable else t})
    schema = {"type": "record", "name": "topLevelRecord", "fields": fields}

    def zz(v: int) -> bytes:
        u = (v << 1) ^ (v >> 63)
        out = bytearray()
        while True:
            b = u & 0x7F
            u >>= 7
            if u:
                out.append(b | 0x80)
            else:
                out.append(b)
                return bytes(out)

    def blob(b: bytes) -> bytes:
        return zz(len(b)) + b

    out = io.BytesIO()
    out.write(_MAGIC)
    meta = {"avro.schema": json.dumps(schema).encode(),
            "avro.codec": codec.encode()}
    out.write(zz(len(meta)))
    for k, v in meta.items():
        out.write(blob(k.encode()))
        out.write(blob(v))
    out.write(zz(0))
    sync = b"\x13" * 16
    out.write(sync)

    body = io.BytesIO()
    pylists = [c.to_pylist() for c in table.columns]
    for r in range(table.num_rows):
        for ci, f in enumerate(table.schema):
            v = pylists[ci][r]
            t = type_map[f.type]
            if f.nullable:
                if v is None:
                    body.write(zz(0))
                    continue
                body.write(zz(1))
            if t == "boolean":
                body.write(b"\x01" if v else b"\x00")
            elif t in ("int", "long"):
                body.write(zz(int(v)))
            elif t == "float":
                body.write(struct.pack("<f", v))
            elif t == "double":
                body.write(struct.pack("<d", v))
            elif t == "string":
                body.write(blob(v.encode("utf-8")))
            else:
                body.write(blob(v))
    payload = body.getvalue()
    if codec == "deflate":
        comp = zlib.compressobj(wbits=-15)
        payload = comp.compress(payload) + comp.flush()
    elif codec != "null":
        raise AvroDecodeError(f"unsupported codec {codec!r}")
    out.write(zz(table.num_rows))
    out.write(zz(len(payload)))
    out.write(payload)
    out.write(sync)
    with open(path, "wb") as f:
        f.write(out.getvalue())


def read_avro_schema(path: str) -> pa.Schema:
    """Arrow schema from the OCF header only (no block decoding)."""
    with open(path, "rb") as f:
        head = f.read(1 << 16)
    try:
        cur = _Cursor(head)
        schema, _, _ = _read_header(cur)
    except (AvroDecodeError, IndexError):
        # metadata larger than the probe window: read it all
        with open(path, "rb") as f:
            cur = _Cursor(f.read())
        schema, _, _ = _read_header(cur)
    if schema.get("type") != "record":
        raise AvroDecodeError("top-level Avro schema must be a record")
    return pa.schema([pa.field(fld["name"],
                               _field_decoder(fld["type"])[1])
                      for fld in schema["fields"]])


class AvroSource(FileSource):
    format_name = "avro"

    def infer_arrow_schema(self) -> pa.Schema:
        return read_avro_schema(self.files[0])

    def read_file(self, path: str) -> pa.Table:
        t = read_avro_file(path)
        if self.predicate is not None:
            # filter BEFORE projecting: the predicate may reference
            # non-projected columns
            from .parquet import expression_to_arrow_filter
            filt = expression_to_arrow_filter(self.predicate)
            if filt is not None:
                t = t.filter(filt)
        if self.columns:
            t = t.select(self.columns)
        return t


# ---------------------------------------------------------------------------
# Generic (nested) Avro value codec — used by the Iceberg metadata layer,
# whose manifest files are Avro records containing nested records, arrays
# and maps. The TABLE scan path above stays restricted to flat records;
# this codec decodes into plain Python objects.
# ---------------------------------------------------------------------------

def _generic_decoder(ftype: Any, named: Dict[str, Any]) -> Callable:
    if isinstance(ftype, str) and ftype in named:
        ftype = named[ftype]
    if isinstance(ftype, dict):
        t = ftype.get("type")
        if t == "record":
            named[ftype.get("name", "")] = ftype
            decs = [(f["name"], _generic_decoder(f["type"], named))
                    for f in ftype["fields"]]

            def dec_rec(c: _Cursor):
                return {n: d(c) for n, d in decs}
            return dec_rec
        if t == "enum":
            named[ftype.get("name", "")] = ftype
            symbols = ftype["symbols"]
            return lambda c: symbols[c.zigzag()]
        if t == "fixed":
            named[ftype.get("name", "")] = ftype
            size = ftype["size"]
            return lambda c: c.read(size)
        if t == "array":
            item = _generic_decoder(ftype["items"], named)

            def dec_arr(c: _Cursor):
                out = []
                while True:
                    n = c.zigzag()
                    if n == 0:
                        return out
                    if n < 0:
                        c.zigzag()      # byte size, unused
                        n = -n
                    for _ in range(n):
                        out.append(item(c))
            return dec_arr
        if t == "map":
            val = _generic_decoder(ftype["values"], named)

            def dec_map(c: _Cursor):
                out = {}
                while True:
                    n = c.zigzag()
                    if n == 0:
                        return out
                    if n < 0:
                        c.zigzag()
                        n = -n
                    for _ in range(n):
                        k = c.bytes_().decode()
                        out[k] = val(c)
            return dec_map
        ftype = t       # {"type": "long", "logicalType": ...}
    if isinstance(ftype, list):
        branches = [_generic_decoder(b, named) for b in ftype]
        return lambda c: branches[c.zigzag()](c)
    if ftype == "null":
        return lambda c: None
    if ftype == "boolean":
        return lambda c: c.read(1) == b"\x01"
    if ftype in ("int", "long"):
        return lambda c: c.zigzag()
    if ftype == "float":
        return lambda c: struct.unpack("<f", c.read(4))[0]
    if ftype == "double":
        return lambda c: struct.unpack("<d", c.read(8))[0]
    if ftype == "string":
        return lambda c: c.bytes_().decode("utf-8")
    if ftype == "bytes":
        return lambda c: c.bytes_()
    raise AvroDecodeError(f"unsupported Avro type {ftype!r}")


def read_avro_records(path: str) -> List[dict]:
    """Decode a (possibly nested) OCF into a list of Python dicts."""
    with open(path, "rb") as f:
        data = f.read()
    cur = _Cursor(data)
    schema, codec, sync = _read_header(cur)
    dec = _generic_decoder(schema, {})
    out: List[dict] = []
    while cur.pos < len(data):
        n_rows = cur.zigzag()
        n_bytes = cur.zigzag()
        payload = cur.read(n_bytes)
        if codec == "deflate":
            payload = zlib.decompress(payload, -15)
        elif codec != "null":
            raise AvroDecodeError(f"unsupported Avro codec {codec!r}")
        if cur.read(16) != sync:
            raise AvroDecodeError("sync marker mismatch (corrupt block)")
        bcur = _Cursor(payload)
        for _ in range(n_rows):
            out.append(dec(bcur))
    return out


def _zz_enc(v: int) -> bytes:
    u = (v << 1) ^ (v >> 63)
    out = bytearray()
    while True:
        b = u & 0x7F
        u >>= 7
        if u:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _generic_encoder(ftype: Any, named: Dict[str, Any]) -> Callable:
    if isinstance(ftype, str) and ftype in named:
        ftype = named[ftype]
    if isinstance(ftype, dict):
        t = ftype.get("type")
        if t == "record":
            named[ftype.get("name", "")] = ftype
            encs = [(f["name"], _generic_encoder(f["type"], named))
                    for f in ftype["fields"]]

            def enc_rec(out, v):
                for n, e in encs:
                    e(out, v[n])
            return enc_rec
        if t == "array":
            item = _generic_encoder(ftype["items"], named)

            def enc_arr(out, v):
                if v:
                    out.write(_zz_enc(len(v)))
                    for x in v:
                        item(out, x)
                out.write(_zz_enc(0))
            return enc_arr
        if t == "map":
            val = _generic_encoder(ftype["values"], named)

            def enc_map(out, v):
                if v:
                    out.write(_zz_enc(len(v)))
                    for k, x in v.items():
                        kb = k.encode()
                        out.write(_zz_enc(len(kb)) + kb)
                        val(out, x)
                out.write(_zz_enc(0))
            return enc_map
        if t == "fixed":
            return lambda out, v: out.write(v)
        if t == "enum":
            symbols = ftype["symbols"]
            return lambda out, v: out.write(_zz_enc(symbols.index(v)))
        ftype = t
    if isinstance(ftype, list):
        encs = [_generic_encoder(b, named) for b in ftype]

        def branch_of(v):
            # simple runtime dispatch: null → the null branch, else the
            # first non-null branch (sufficient for iceberg manifests)
            for i, b in enumerate(ftype):
                if v is None and b == "null":
                    return i
                if v is not None and b != "null":
                    return i
            raise AvroDecodeError(f"no union branch for {v!r} in {ftype}")

        def enc_union(out, v):
            i = branch_of(v)
            out.write(_zz_enc(i))
            encs[i](out, v)
        return enc_union
    if ftype == "null":
        return lambda out, v: None
    if ftype == "boolean":
        return lambda out, v: out.write(b"\x01" if v else b"\x00")
    if ftype in ("int", "long"):
        return lambda out, v: out.write(_zz_enc(int(v)))
    if ftype == "float":
        return lambda out, v: out.write(struct.pack("<f", v))
    if ftype == "double":
        return lambda out, v: out.write(struct.pack("<d", v))
    if ftype == "string":
        return lambda out, v: out.write(
            _zz_enc(len(v.encode())) + v.encode())
    if ftype == "bytes":
        return lambda out, v: out.write(_zz_enc(len(v)) + v)
    raise AvroDecodeError(f"unsupported Avro type {ftype!r}")


def write_avro_records(path: str, schema: dict, records: List[dict],
                       codec: str = "null") -> None:
    """Encode nested records to an OCF (Iceberg manifest writer)."""
    enc = _generic_encoder(schema, {})
    out = io.BytesIO()
    out.write(_MAGIC)
    meta = {"avro.schema": json.dumps(schema).encode(),
            "avro.codec": codec.encode()}
    out.write(_zz_enc(len(meta)))
    for k, v in meta.items():
        out.write(_zz_enc(len(k)) + k.encode())
        out.write(_zz_enc(len(v)) + v)
    out.write(_zz_enc(0))
    sync = b"\x42" * 16
    out.write(sync)
    body = io.BytesIO()
    for r in records:
        enc(body, r)
    payload = body.getvalue()
    if codec == "deflate":
        comp = zlib.compressobj(wbits=-15)
        payload = comp.compress(payload) + comp.flush()
    elif codec != "null":
        raise AvroDecodeError(f"unsupported codec {codec!r}")
    out.write(_zz_enc(len(records)))
    out.write(_zz_enc(len(payload)))
    out.write(payload)
    out.write(sync)
    with open(path, "wb") as f:
        f.write(out.getvalue())
