"""JSON-lines read (reference: GpuJsonScan.scala via the same text-funnel
as CSV; see csv.py for the host-decode rationale)."""

from __future__ import annotations

from typing import Optional

import pyarrow as pa
import pyarrow.json as pajson

from .. import types as T
from ..batch import Schema
from .source import FileSource


class JsonSource(FileSource):
    format_name = "json"

    def __init__(self, paths, schema: Optional[Schema] = None, **kw):
        self._declared = schema
        super().__init__(paths, schema=None, **kw)

    def _parse_options(self):
        if self._declared is None:
            return pajson.ParseOptions()
        s = pa.schema([pa.field(f.name, T.to_arrow(f.dtype), f.nullable)
                       for f in self._declared])
        return pajson.ParseOptions(explicit_schema=s)

    def infer_arrow_schema(self) -> pa.Schema:
        return pajson.read_json(self.files[0],
                                parse_options=self._parse_options()).schema

    def read_file(self, path: str) -> pa.Table:
        t = pajson.read_json(path, parse_options=self._parse_options())
        if self.columns:
            t = t.select(self.columns)
        if self.predicate is not None:
            from .parquet import expression_to_arrow_filter
            filt = expression_to_arrow_filter(self.predicate)
            if filt is not None:
                t = t.filter(filt)
        return t
