"""Minimal ORC tail parser: stripe-level column statistics for pruning.

Reference: GpuOrcScan.scala pushes search arguments into the native ORC
reader so whole stripes are skipped on min/max stats. pyarrow's ORC
binding exposes no stripe statistics, so this module parses the file tail
itself — ORC metadata is plain protobuf wire format (postscript → footer
→ metadata sections), which a ~150-line reader covers for the stats we
need. Decode stays with pyarrow; only the SKIP decision comes from here.

Supported: UNCOMPRESSED and ZLIB (raw-deflate chunk) tails — the common
writer configs (pyarrow default = uncompressed, Spark default = zlib).
Anything else returns None and the scan keeps every stripe (pruning is an
optimization, never a semantics change).
"""

from __future__ import annotations

import struct
import zlib
from typing import Dict, List, Optional, Tuple

_MAGIC = b"ORC"

# PostScript compression enum
_NONE, _ZLIB = 0, 1


class _Pb:
    """Protobuf wire-format reader (varint / 64-bit / length-delimited /
    32-bit), bounds-checked."""

    def __init__(self, data: bytes):
        self.d = data
        self.p = 0

    def at_end(self) -> bool:
        return self.p >= len(self.d)

    def varint(self) -> int:
        v = 0
        shift = 0
        while True:
            if self.p >= len(self.d):
                raise ValueError("truncated varint")
            b = self.d[self.p]
            self.p += 1
            v |= (b & 0x7F) << shift
            if not (b & 0x80):
                return v
            shift += 7
            if shift > 70:
                raise ValueError("varint overflow")

    def key(self) -> Tuple[int, int]:
        k = self.varint()
        return k >> 3, k & 7

    def bytes_(self) -> bytes:
        n = self.varint()
        if self.p + n > len(self.d):
            raise ValueError("truncated bytes")
        out = self.d[self.p:self.p + n]
        self.p += n
        return out

    def skip(self, wt: int) -> None:
        if wt == 0:
            self.varint()
        elif wt == 1:
            self.p += 8
        elif wt == 2:
            self.bytes_()
        elif wt == 5:
            self.p += 4
        else:
            raise ValueError(f"wire type {wt}")


def _zigzag(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


def _decompress_section(data: bytes, compression: int) -> Optional[bytes]:
    """ORC compressed sections are chunked: 3-byte LE header
    ``(len << 1) | isOriginal`` then len bytes per chunk."""
    if compression == _NONE:
        return data
    if compression != _ZLIB:
        return None
    out = bytearray()
    p = 0
    while p + 3 <= len(data):
        h = data[p] | (data[p + 1] << 8) | (data[p + 2] << 16)
        p += 3
        n = h >> 1
        if p + n > len(data):
            return None
        chunk = data[p:p + n]
        p += n
        if h & 1:
            out.extend(chunk)
        else:
            try:
                out.extend(zlib.decompress(chunk, -15))
            except zlib.error:
                return None
    return bytes(out)


def _parse_column_stats(data: bytes) -> Tuple[Optional[object],
                                              Optional[object]]:
    """(min, max) of one ColumnStatistics, or (None, None)."""
    pb = _Pb(data)
    mn = mx = None
    while not pb.at_end():
        f, wt = pb.key()
        if f == 2 and wt == 2:            # intStatistics
            s = _Pb(pb.bytes_())
            while not s.at_end():
                f2, wt2 = s.key()
                if f2 == 1 and wt2 == 0:
                    mn = _zigzag(s.varint())
                elif f2 == 2 and wt2 == 0:
                    mx = _zigzag(s.varint())
                else:
                    s.skip(wt2)
        elif f == 3 and wt == 2:          # doubleStatistics
            s = _Pb(pb.bytes_())
            while not s.at_end():
                f2, wt2 = s.key()
                if f2 in (1, 2) and wt2 == 1:
                    v = struct.unpack("<d", s.d[s.p:s.p + 8])[0]
                    s.p += 8
                    if f2 == 1:
                        mn = v
                    else:
                        mx = v
                else:
                    s.skip(wt2)
        elif f == 4 and wt == 2:          # stringStatistics
            s = _Pb(pb.bytes_())
            while not s.at_end():
                f2, wt2 = s.key()
                if f2 in (1, 2) and wt2 == 2:
                    v = s.bytes_().decode("utf-8", "replace")
                    if f2 == 1:
                        mn = v
                    else:
                        mx = v
                else:
                    s.skip(wt2)
        else:
            pb.skip(wt)
    return mn, mx


def parse_stripe_stats(path: str) -> Optional[List[Dict[str, tuple]]]:
    """Per-stripe {column_name: (min, max)} for FLAT top-level columns, or
    None when the tail is outside the supported subset."""
    try:
        with open(path, "rb") as f:
            f.seek(0, 2)
            size = f.tell()
            tail_len = min(size, 256 << 10)
            f.seek(size - tail_len)
            tail = f.read(tail_len)
        ps_len = tail[-1]
        ps = _Pb(tail[-1 - ps_len:-1])
        footer_len = metadata_len = 0
        compression = _NONE
        while not ps.at_end():
            fld, wt = ps.key()
            if fld == 1 and wt == 0:
                footer_len = ps.varint()
            elif fld == 2 and wt == 0:
                compression = ps.varint()
            elif fld == 5 and wt == 0:
                metadata_len = ps.varint()
            else:
                ps.skip(wt)
        need = footer_len + metadata_len + ps_len + 1
        if need > tail_len:
            return None                   # enormous tail: skip pruning
        foot_raw = tail[-1 - ps_len - footer_len:-1 - ps_len]
        meta_raw = tail[-1 - ps_len - footer_len - metadata_len:
                        -1 - ps_len - footer_len]
        footer = _decompress_section(foot_raw, compression)
        metadata = _decompress_section(meta_raw, compression)
        if footer is None or metadata is None:
            return None
        # footer → root type's field names (flat schemas only)
        pb = _Pb(footer)
        types: List[Tuple[int, List[str]]] = []   # (kind, fieldNames)
        while not pb.at_end():
            fld, wt = pb.key()
            if fld == 4 and wt == 2:      # Type
                t = _Pb(pb.bytes_())
                kind = -1
                names: List[str] = []
                while not t.at_end():
                    f2, wt2 = t.key()
                    if f2 == 1 and wt2 == 0:
                        kind = t.varint()
                    elif f2 == 3 and wt2 == 2:
                        names.append(t.bytes_().decode("utf-8"))
                    else:
                        t.skip(wt2)
                types.append((kind, names))
            else:
                pb.skip(wt)
        if not types or types[0][0] != 12:    # root must be STRUCT
            return None
        root_names = types[0][1]
        # flat column i (1-based type id) ↔ root_names[i-1]; nested
        # subtrees would shift ids, so bail out unless every child type
        # is primitive. ORC Type.Kind: 0-7 bool..string, 8 binary,
        # 9 timestamp, 14 decimal, 15 date, 16 varchar, 17 char,
        # 18 timestamp_instant (10-13 = list/map/struct/union are nested)
        primitive = {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 14, 15, 16, 17, 18}
        if len(types) != len(root_names) + 1 or any(
                k not in primitive for k, _ in types[1:]):
            return None
        # metadata → per-stripe stats
        out: List[Dict[str, tuple]] = []
        mb = _Pb(metadata)
        while not mb.at_end():
            fld, wt = mb.key()
            if fld == 1 and wt == 2:      # StripeStatistics
                sb = _Pb(mb.bytes_())
                col_stats: List[tuple] = []
                while not sb.at_end():
                    f2, wt2 = sb.key()
                    if f2 == 1 and wt2 == 2:
                        col_stats.append(_parse_column_stats(sb.bytes_()))
                    else:
                        sb.skip(wt2)
                stripe: Dict[str, tuple] = {}
                for i, name in enumerate(root_names):
                    if i + 1 < len(col_stats):
                        mn, mx = col_stats[i + 1]
                        if mn is not None and mx is not None:
                            stripe[name] = (mn, mx)
                out.append(stripe)
            else:
                mb.skip(wt)
        return out or None
    except (ValueError, IndexError, OSError, struct.error):
        return None
