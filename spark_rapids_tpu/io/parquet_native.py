"""Native parquet column-chunk decode (ctypes over rtpu_parquet.cpp).

The decode half of the reference's native parquet path (JNI footer parse at
GpuParquetScan.scala:539-597; libcudf Table.readParquet): the C++ library
parses the thrift footer once per file and decodes PLAIN / RLE_DICTIONARY
pages (SNAPPY/ZSTD/uncompressed) straight into flat numpy buffers; this
module assembles zero-copy arrow arrays from them. Any file/column outside
the native subset returns None and the caller falls back to pyarrow — per
ROW GROUP, so mixed files still get the fast path where possible.
"""

from __future__ import annotations

import ctypes
import mmap
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np
import pyarrow as pa

from ..utils import native as _native

_MAGIC = b"PAR1"

# parquet physical types
_PT_BOOLEAN, _PT_INT32, _PT_INT64 = 0, 1, 2
_PT_FLOAT, _PT_DOUBLE, _PT_BYTE_ARRAY = 4, 5, 6
_SUPPORTED_PT = {_PT_BOOLEAN, _PT_INT32, _PT_INT64, _PT_FLOAT, _PT_DOUBLE,
                 _PT_BYTE_ARRAY}
_SUPPORTED_CODECS = {0, 1, 6}          # UNCOMPRESSED, SNAPPY, ZSTD

_FIXED_NP = {_PT_BOOLEAN: np.uint8, _PT_INT32: np.int32,
             _PT_INT64: np.int64, _PT_FLOAT: np.float32,
             _PT_DOUBLE: np.float64}


def _lib():
    lib = _native._load()
    if lib is None or not hasattr(lib, "rtpu_pq_footer_open"):
        return None
    if not getattr(lib, "_pq_typed", False):
        u8p = ctypes.POINTER(ctypes.c_uint8)
        i64p = ctypes.POINTER(ctypes.c_int64)
        lib.rtpu_pq_footer_open.restype = ctypes.c_int64
        lib.rtpu_pq_footer_open.argtypes = [u8p, ctypes.c_int64]
        lib.rtpu_pq_footer_free.argtypes = [ctypes.c_int64]
        lib.rtpu_pq_num_rows.restype = ctypes.c_int64
        lib.rtpu_pq_num_rows.argtypes = [ctypes.c_int64]
        lib.rtpu_pq_num_columns.argtypes = [ctypes.c_int64]
        lib.rtpu_pq_num_row_groups.argtypes = [ctypes.c_int64]
        lib.rtpu_pq_rg_rows.restype = ctypes.c_int64
        lib.rtpu_pq_rg_rows.argtypes = [ctypes.c_int64, ctypes.c_int32]
        lib.rtpu_pq_col_name.argtypes = [ctypes.c_int64, ctypes.c_int32,
                                         ctypes.c_char_p, ctypes.c_int32]
        lib.rtpu_pq_col_info.argtypes = [ctypes.c_int64, ctypes.c_int32,
                                         i64p]
        lib.rtpu_pq_chunk_info.argtypes = [ctypes.c_int64, ctypes.c_int32,
                                           ctypes.c_int32, i64p]
        lib.rtpu_pq_chunk_stats.argtypes = [ctypes.c_int64, ctypes.c_int32,
                                            ctypes.c_int32, u8p, u8p, i64p]
        lib.rtpu_pq_has_kv_key.argtypes = [ctypes.c_int64, ctypes.c_char_p]
        lib.rtpu_pq_decode_fixed.restype = ctypes.c_int64
        lib.rtpu_pq_decode_fixed.argtypes = [
            u8p, ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_int64, u8p, u8p]
        lib.rtpu_pq_decode_binary.restype = ctypes.c_int64
        lib.rtpu_pq_decode_binary.argtypes = [
            u8p, ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int64, ctypes.POINTER(ctypes.c_int32), u8p,
            ctypes.c_int64, u8p]
        if hasattr(lib, "rtpu_pq_decode_binary_codes"):
            # compressed-execution hand-off (older prebuilt .so files may
            # lack the symbol; the materializing decode still works)
            lib.rtpu_pq_decode_binary_codes.restype = ctypes.c_int64
            lib.rtpu_pq_decode_binary_codes.argtypes = [
                u8p, ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
                ctypes.c_int64, ctypes.POINTER(ctypes.c_int32), u8p,
                ctypes.POINTER(ctypes.c_int32), ctypes.c_int64, u8p,
                ctypes.c_int64, i64p]
        lib._pq_typed = True
    return lib


def _u8(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


class NativeParquetFile:
    """One open file: mmap + parsed native footer. Thread-safe for
    concurrent row-group decode (the C++ side only reads)."""

    def __init__(self, path: str):
        self.path = path
        lib = _lib()
        if lib is None:
            raise _Unsupported("native library unavailable")
        self._lib = lib
        f = open(path, "rb")
        try:
            self._mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
        finally:
            f.close()
        self._buf = np.frombuffer(self._mm, dtype=np.uint8)
        n = len(self._buf)
        if n < 12 or bytes(self._buf[-4:]) != _MAGIC:
            raise _Unsupported("not a parquet file")
        flen = int(np.frombuffer(self._buf[-8:-4].tobytes(),
                                 np.uint32)[0])
        if flen + 8 > n:
            raise _Unsupported("bad footer length")
        footer = self._buf[n - 8 - flen:n - 8]
        footer = np.ascontiguousarray(footer)
        h = lib.rtpu_pq_footer_open(_u8(footer), flen)
        if h < 0:
            raise _Unsupported(f"footer parse failed ({h})")
        self._h = h
        self.num_row_groups = lib.rtpu_pq_num_row_groups(h)
        self.num_rows = lib.rtpu_pq_num_rows(h)
        ncols = lib.rtpu_pq_num_columns(h)
        self.columns: Dict[str, int] = {}
        self._col_info: List[Tuple[int, int, bool]] = []
        name_buf = ctypes.create_string_buffer(1 << 16)
        info = (ctypes.c_int64 * 4)()
        for c in range(ncols):
            rc = lib.rtpu_pq_col_name(h, c, name_buf, len(name_buf))
            if rc < 0:
                raise _Unsupported("column name overflow")
            lib.rtpu_pq_col_info(h, c, info)
            # (physical type, max_def, flat, is_decimal)
            self._col_info.append((int(info[0]), int(info[1]),
                                   bool(info[2]), bool(info[3])))
            # only FLAT leaves are addressable: the footer stores bare
            # leaf names, and a nested leaf sharing a top-level column's
            # name must not shadow it (stats pruning would read the
            # wrong chunk — review finding)
            if bool(info[2]):
                self.columns[name_buf.value.decode("utf-8")] = c

    def close(self):
        if getattr(self, "_h", None) is not None:
            self._lib.rtpu_pq_footer_free(self._h)
            self._h = None
        if getattr(self, "_mm", None) is not None:
            self._buf = None
            self._mm.close()
            self._mm = None

    def __del__(self):   # handles leak-free even without explicit close
        try:
            self.close()
        except Exception:
            pass

    def rg_rows(self, rg: int) -> int:
        return self._lib.rtpu_pq_rg_rows(self._h, rg)

    def chunk_stats(self, rg: int, name: str):
        """(min_bytes|None, max_bytes|None, null_count|None) raw
        PLAIN-encoded stat payloads for predicate pruning."""
        c = self.columns.get(name)
        if c is None:
            return None, None, None
        mn = (ctypes.c_uint8 * 16)()
        mx = (ctypes.c_uint8 * 16)()
        lens = (ctypes.c_int64 * 3)()
        mask = self._lib.rtpu_pq_chunk_stats(
            self._h, rg, c, ctypes.cast(mn, ctypes.POINTER(ctypes.c_uint8)),
            ctypes.cast(mx, ctypes.POINTER(ctypes.c_uint8)), lens)
        if mask < 0:
            return None, None, None
        return (bytes(mn[:lens[0]]) if mask & 1 else None,
                bytes(mx[:lens[1]]) if mask & 2 else None,
                int(lens[2]) if mask & 4 else None)

    def has_metadata_key(self, key) -> bool:
        if isinstance(key, str):
            key = key.encode("utf-8")
        return self._lib.rtpu_pq_has_kv_key(self._h, key) == 1

    def decoded_stats(self, rg: int, name: str):
        """(min, max) as python numbers for NUMERIC leaves, else None.
        Strings are skipped (footer stats may be truncated; only
        is_*_value_exact-aware logic could use them safely)."""
        import struct
        c = self.columns.get(name)
        if c is None:
            return None
        ptype, _, _, is_decimal = self._col_info[c]
        if is_decimal:
            # decimal stats are UNSCALED ints; comparing them against
            # logical Decimal literals would wrongly prune matching
            # groups (review finding) — no native stats for decimals
            return None
        mn, mx, _ = self.chunk_stats(rg, name)
        if mn is None or mx is None:
            return None
        try:
            if ptype == _PT_INT32 and len(mn) >= 4:
                return (int.from_bytes(mn[:4], "little", signed=True),
                        int.from_bytes(mx[:4], "little", signed=True))
            if ptype == _PT_INT64 and len(mn) >= 8:
                return (int.from_bytes(mn[:8], "little", signed=True),
                        int.from_bytes(mx[:8], "little", signed=True))
            if ptype == _PT_FLOAT and len(mn) >= 4:
                return (struct.unpack("<f", mn[:4])[0],
                        struct.unpack("<f", mx[:4])[0])
            if ptype == _PT_DOUBLE and len(mn) >= 8:
                return (struct.unpack("<d", mn[:8])[0],
                        struct.unpack("<d", mx[:8])[0])
        except (struct.error, ValueError):
            return None
        return None

    def _decode_column(self, rg: int, c: int, rows: int,
                       arrow_type) -> pa.Array:
        lib = self._lib
        ptype, max_def, flat, _ = self._col_info[c]
        if not flat or ptype not in _SUPPORTED_PT:
            raise _Unsupported(f"column layout (type={ptype}, flat={flat})")
        info = (ctypes.c_int64 * 5)()
        lib.rtpu_pq_chunk_info(self._h, rg, c, info)
        codec, start, clen, _nvals, total_un = (int(x) for x in info)
        if codec not in _SUPPORTED_CODECS:
            raise _Unsupported(f"codec {codec}")
        if start < 0 or start + clen > len(self._buf):
            raise _Unsupported("chunk bounds")
        chunk = self._buf[start:start + clen]
        validity = np.empty(rows, np.uint8)
        if ptype == _PT_BYTE_ARRAY:
            offsets = np.empty(rows + 1, np.int32)
            cap = max(total_un, 1)
            for _ in range(2):
                data = np.empty(cap, np.uint8)
                rc = lib.rtpu_pq_decode_binary(
                    _u8(chunk), clen, codec, max_def, rows,
                    offsets.ctypes.data_as(
                        ctypes.POINTER(ctypes.c_int32)),
                    _u8(data), cap, _u8(validity))
                if rc == -4:          # ERR_SPACE: retry at the real size
                    cap = int(offsets[rows])
                    continue
                break
            if rc < 0:
                raise _Unsupported(f"binary decode ({rc})")
            return _binary_array(arrow_type, rows, offsets, data, validity)
        np_dt = _FIXED_NP[ptype]
        values = np.empty(rows, np_dt)
        rc = lib.rtpu_pq_decode_fixed(
            _u8(chunk), clen, ptype, codec, max_def, rows,
            values.view(np.uint8).ctypes.data_as(
                ctypes.POINTER(ctypes.c_uint8)),
            _u8(validity))
        if rc < 0:
            raise _Unsupported(f"fixed decode ({rc})")
        return _fixed_array(arrow_type, rows, ptype, values, validity)

    def _decode_column_codes(self, rg: int, c: int,
                             rows: int) -> Optional[pa.DictionaryArray]:
        """RLE_DICTIONARY chunk decode that KEEPS the page codes: per-row
        codes + the dictionary page's values become a pa.DictionaryArray
        with zero per-row byte materialization (the compressed-execution
        scan hand-off). None when the chunk is outside the codes subset
        (PLAIN fallback pages, library without the symbol) — the caller
        uses the materializing decode."""
        lib = self._lib
        if not hasattr(lib, "rtpu_pq_decode_binary_codes"):
            return None
        ptype, max_def, flat, _ = self._col_info[c]
        if not flat or ptype != _PT_BYTE_ARRAY:
            return None
        info = (ctypes.c_int64 * 5)()
        lib.rtpu_pq_chunk_info(self._h, rg, c, info)
        codec, start, clen, _nvals, total_un = (int(x) for x in info)
        if codec not in _SUPPORTED_CODECS:
            return None
        if start < 0 or start + clen > len(self._buf):
            return None
        chunk = self._buf[start:start + clen]
        codes = np.empty(rows, np.int32)
        validity = np.empty(rows, np.uint8)
        # ents_cap sized to the cardinality budget up front (offsets are
        # 4 bytes/entry): an undersized guess costs a FULL second chunk
        # decode via ERR_SPACE on exactly the mid-cardinality columns
        # this path targets
        ents_cap = int(min(1 << 16, max(rows, 1)))
        bytes_cap = max(total_un, 1)
        dinfo = (ctypes.c_int64 * 2)()
        for _ in range(2):
            offsets = np.empty(ents_cap + 1, np.int32)
            dbytes = np.empty(bytes_cap, np.uint8)
            rc = lib.rtpu_pq_decode_binary_codes(
                _u8(chunk), clen, codec, max_def, rows,
                codes.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                _u8(validity),
                offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                ents_cap, _u8(dbytes), bytes_cap, dinfo)
            if rc == -4:          # ERR_SPACE: retry at the real sizes
                ents_cap = max(int(dinfo[0]), 1)
                bytes_cap = max(int(dinfo[1]), 1)
                continue
            break
        if rc < 0:
            return None
        card = int(dinfo[0])
        values = pa.StringArray.from_buffers(
            card, pa.py_buffer(np.ascontiguousarray(
                offsets[:card + 1]).tobytes()),
            pa.py_buffer(np.ascontiguousarray(
                dbytes[:int(dinfo[1])]).tobytes()))
        indices = pa.Array.from_buffers(
            pa.int32(), rows,
            [_validity_buffer(validity), pa.py_buffer(codes)])
        return pa.DictionaryArray.from_arrays(indices, values)

    def read_row_group(self, rg: int, columns: List[str],
                       arrow_schema: pa.Schema,
                       dict_columns: Optional[set] = None) -> pa.Table:
        rows = self.rg_rows(rg)
        arrays, names = [], []
        for name in columns:
            c = self.columns.get(name)
            if c is None:
                raise _Unsupported(f"no such column {name!r}")
            at = arrow_schema.field(name).type
            if not _arrow_type_supported(at):
                raise _Unsupported(f"arrow type {at}")
            arr = None
            if dict_columns and name in dict_columns:
                arr = self._decode_column_codes(rg, c, rows)
            if arr is None:
                arr = self._decode_column(rg, c, rows, at)
            arrays.append(arr)
            names.append(name)
        return pa.table(arrays, names=names)


class _Unsupported(Exception):
    pass


def _arrow_type_supported(t) -> bool:
    return (pa.types.is_boolean(t) or pa.types.is_int32(t)
            or pa.types.is_int64(t) or pa.types.is_float32(t)
            or pa.types.is_float64(t) or pa.types.is_string(t)
            or pa.types.is_large_string(t) or pa.types.is_date32(t)
            or (pa.types.is_timestamp(t) and t.unit == "us"))


def _validity_buffer(validity: np.ndarray) -> Optional[pa.Buffer]:
    if validity.all():
        return None
    return pa.py_buffer(
        np.packbits(validity.view(bool), bitorder="little").tobytes())


def _fixed_array(arrow_type, rows: int, ptype: int, values: np.ndarray,
                 validity: np.ndarray) -> pa.Array:
    nulls = _validity_buffer(validity)
    if ptype == _PT_BOOLEAN:
        bits = pa.py_buffer(np.packbits(values.view(bool),
                                        bitorder="little").tobytes())
        return pa.Array.from_buffers(pa.bool_(), rows, [nulls, bits])
    return pa.Array.from_buffers(arrow_type, rows,
                                 [nulls, pa.py_buffer(values)])


def _binary_array(arrow_type, rows: int, offsets: np.ndarray,
                  data: np.ndarray, validity: np.ndarray) -> pa.Array:
    nulls = _validity_buffer(validity)
    used = int(offsets[rows])
    # int32 offsets force the small-string base; the cast below widens to
    # large_string when the file schema asks for it
    base = pa.string()
    arr = pa.Array.from_buffers(
        base, rows, [nulls, pa.py_buffer(offsets),
                     pa.py_buffer(np.ascontiguousarray(data[:used]))])
    if arrow_type != base:
        arr = arr.cast(arrow_type)
    return arr


# ---------------------------------------------------------------------------
# per-path file cache (footers parse once; decode is per row group)
# ---------------------------------------------------------------------------

_CACHE: Dict[str, object] = {}
_CACHE_LOCK = threading.Lock()
_FAILED: Dict[str, str] = {}
_MAX_CACHED = 64


def open_native(path: str) -> Optional[NativeParquetFile]:
    with _CACHE_LOCK:
        if path in _FAILED:
            return None
        f = _CACHE.get(path)
        if f is not None:
            return f
    try:
        f = NativeParquetFile(path)
    except _Unsupported as e:
        with _CACHE_LOCK:
            _FAILED[path] = str(e)
        return None
    with _CACHE_LOCK:
        if len(_CACHE) >= _MAX_CACHED:
            # FIFO-evict the OLDEST entry and let refcounting finalize it
            # (__del__ closes the mmap once no scan thread holds a view);
            # an eager close() here could rip the buffer out from under a
            # concurrent decode (review finding)
            _CACHE.pop(next(iter(_CACHE)), None)
        _CACHE[path] = f
    return f


def read_row_group_native(path: str, rg: int, columns: List[str],
                          arrow_schema: pa.Schema,
                          dict_columns: Optional[set] = None
                          ) -> Optional[pa.Table]:
    """Native decode of one row group, or None (caller falls back).
    ``dict_columns`` names string columns whose RLE_DICTIONARY codes
    should be preserved as pa.DictionaryArray (per-column best effort)."""
    f = open_native(path)
    if f is None:
        return None
    try:
        return f.read_row_group(rg, columns, arrow_schema, dict_columns)
    except _Unsupported:
        return None
