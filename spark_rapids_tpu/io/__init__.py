"""I/O layer: file sources, scan exec, writers.

Reference: SURVEY.md §2.7/L4 — GpuParquetScan.scala:96 (three reader
strategies PERFILE/COALESCING/MULTITHREADED with heuristic :276),
GpuMultiFileReader.scala (shared thread pool :123, cloud prefetch reader
:441), GpuOrcScan, GpuCSVScan, GpuJsonScan (text funnel
GpuTextBasedPartitionReader.scala:203), writers GpuParquetFileFormat:163 /
ColumnarOutputWriter:64.

The host decode path rides pyarrow's C++ readers (the analogue of cudf's
native file decoders — host-side here because the TPU has no device decode
path; the H2D copy is the from_arrow boundary).
"""

from .source import FileSource
from .parquet import ParquetSource, write_parquet
from .csv import CsvSource, write_csv
from .json import JsonSource
from .avro import AvroSource, read_avro_file, write_avro_file
from .iceberg import IcebergSource, IcebergTable, read_iceberg
from .scan import (FileSourceScanExec, read_avro, read_csv, read_json,
                   read_parquet)

__all__ = [n for n in dir() if not n.startswith("_")]
