"""CSV read/write.

Reference: GpuCSVScan.scala + GpuTextBasedPartitionReader.scala:203 — raw
line buffers shipped to the device and parsed by cudf's text kernels. On
TPU there is no device text parser, so decode stays on the host C++ reader
(pyarrow.csv) inside the shared multi-file thread pool; the H2D boundary
carries already-columnar data. Schema handling mirrors the reference: an
explicit schema drives typed parsing, headerless by default like Spark.
"""

from __future__ import annotations

from typing import Optional

import pyarrow as pa
import pyarrow.csv as pacsv

from .. import types as T
from ..batch import Schema
from .source import FileSource


class CsvSource(FileSource):
    format_name = "csv"

    def __init__(self, paths, schema: Optional[Schema] = None,
                 header: bool = False, sep: str = ",",
                 null_value: str = "", **kw):
        self.header = header
        self.sep = sep
        self.null_value = null_value
        self._user_schema = schema
        super().__init__(paths, schema=None, **kw)
        self._declared = schema

    def _convert_options(self, arrow_schema: Optional[pa.Schema]):
        return pacsv.ConvertOptions(
            column_types=dict(zip(arrow_schema.names, arrow_schema.types))
            if arrow_schema else None,
            null_values=[self.null_value, "null", "NULL"],
            strings_can_be_null=True)

    def _read_options(self, names):
        return pacsv.ReadOptions(
            column_names=None if self.header else names,
            autogenerate_column_names=False if (self.header or names)
            else True)

    def _arrow_schema(self) -> Optional[pa.Schema]:
        if self._declared is None:
            return None
        return pa.schema([pa.field(f.name, T.to_arrow(f.dtype), f.nullable)
                          for f in self._declared])

    def infer_arrow_schema(self) -> pa.Schema:
        s = self._arrow_schema()
        if s is not None:
            return s
        t = pacsv.read_csv(
            self.files[0],
            read_options=self._read_options(None),
            parse_options=pacsv.ParseOptions(delimiter=self.sep))
        return t.schema

    def _parse_options(self):
        return pacsv.ParseOptions(delimiter=self.sep)

    def read_file(self, path: str) -> pa.Table:
        s = self._arrow_schema()
        names = s.names if s is not None else None
        t = pacsv.read_csv(
            path,
            read_options=self._read_options(names),
            parse_options=self._parse_options(),
            convert_options=self._convert_options(s))
        if self.columns:
            t = t.select(self.columns)
        if self.predicate is not None:
            from .parquet import expression_to_arrow_filter
            filt = expression_to_arrow_filter(self.predicate)
            if filt is not None:
                t = t.filter(filt)
        return t


class HiveTextSource(CsvSource):
    """Hive delimited text (reference: GpuHiveTableScanExec — ^A-separated,
    \\N nulls, headerless, LazySimpleSerDe dialect: NO quoting/escaping,
    and ONLY the \\N marker is null — a literal "null" string is data)."""

    format_name = "hive-text"

    def __init__(self, paths, schema=None, sep: str = "\x01", **kw):
        super().__init__(paths, schema=schema, header=False, sep=sep,
                         null_value="\\N", **kw)

    def _parse_options(self):
        return pacsv.ParseOptions(delimiter=self.sep, quote_char=False,
                                  double_quote=False, escape_char=False)

    def _convert_options(self, arrow_schema):
        return pacsv.ConvertOptions(
            column_types=dict(zip(arrow_schema.names, arrow_schema.types))
            if arrow_schema else None,
            null_values=[self.null_value],
            strings_can_be_null=True,
            quoted_strings_can_be_null=False)


def read_hive_text(paths, schema, sep: str = "\x01", num_slices: int = 1,
                   **kw):
    from ..plan.logical import DataFrame, LogicalScan
    src = HiveTextSource(paths, schema=schema, sep=sep, **kw)
    return DataFrame(LogicalScan((), source=src, _schema=src.schema(),
                                 num_slices=num_slices))


def write_csv(table: pa.Table, path: str, header: bool = True) -> None:
    import os
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    pacsv.write_csv(table, path,
                    pacsv.WriteOptions(include_header=header))
