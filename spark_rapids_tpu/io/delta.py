"""Transactional table format (Delta-protocol style).

Reference: delta-lake/ (SURVEY.md §2.12, 9,721 LoC) — GPU-accelerated Delta
writes with optimistic transactions (GpuOptimisticTransaction), DELETE/
UPDATE command rewrites, per-file statistics collection. This module is the
TPU-native equivalent on the same on-disk protocol shape: a `_delta_log/`
of ordered JSON commits holding metaData/add/remove actions over parquet
data files, optimistic concurrency via O_EXCL commit-file creation, row-
level DELETE/UPDATE as copy-on-write file rewrites executed by the TPU
engine, snapshot isolation and time travel by log replay.

MERGE INTO (cardinality-checked, all WHEN clauses) and z-order clustered
writes are implemented below (GpuMergeIntoCommand / ZOrderRules analogues).
"""

from __future__ import annotations

import json
import os
import time
import uuid
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence

import pyarrow as pa
import pyarrow.parquet as pq


def _read_data_file(path, rebase_mode: str = "EXCEPTION"):
    """Parquet data-file read with the shared legacy-datetime policy:
    a hybrid-calendar file surfaced through the Delta log must not
    silently keep Julian labels (mode comes from the DeltaTable)."""
    from .parquet import rebase_legacy_datetimes
    return rebase_legacy_datetimes(pq.read_table(path), rebase_mode, path)

from ..batch import Schema
from ..expressions.base import Expression
from .. import types as T


class CommitConflict(Exception):
    """Another writer committed this version first (optimistic retry)."""


def _log_dir(path: str) -> str:
    return os.path.join(path, "_delta_log")


def _version_file(path: str, v: int) -> str:
    return os.path.join(_log_dir(path), f"{v:020d}.json")


@dataclass
class Snapshot:
    version: int
    files: List[str]
    metadata: Dict[str, Any]

    @property
    def schema_json(self):
        return self.metadata.get("schemaString")


class DeltaTable:
    def __init__(self, path: str, rebase_mode: str = "EXCEPTION"):
        self.path = path
        # parquet legacy-datetime policy for the table's data files
        # (EXCEPTION | CORRECTED | LEGACY — see io/parquet.py)
        self.rebase_mode = rebase_mode.upper()

    # ------------------------------------------------------------------
    # log replay
    # ------------------------------------------------------------------

    def latest_version(self) -> int:
        d = _log_dir(self.path)
        if not os.path.isdir(d):
            return -1
        vs = [int(f.split(".")[0]) for f in os.listdir(d)
              if f.endswith(".json")]
        return max(vs) if vs else -1

    def snapshot(self, version: Optional[int] = None) -> Snapshot:
        latest = self.latest_version()
        if latest < 0:
            raise FileNotFoundError(f"not a delta table: {self.path}")
        v = latest if version is None else version
        if v > latest:
            raise ValueError(f"version {v} > latest {latest} (time travel "
                             f"only goes backwards)")
        live: Dict[str, bool] = {}
        metadata: Dict[str, Any] = {}
        for i in range(v + 1):
            with open(_version_file(self.path, i)) as f:
                for line in f:
                    action = json.loads(line)
                    if "metaData" in action:
                        metadata = action["metaData"]
                    elif "add" in action:
                        live[action["add"]["path"]] = True
                    elif "remove" in action:
                        live.pop(action["remove"]["path"], None)
        files = [os.path.join(self.path, p) for p in sorted(live)]
        return Snapshot(v, files, metadata)

    # ------------------------------------------------------------------
    # commits (optimistic: O_EXCL create of the next version file)
    # ------------------------------------------------------------------

    def _commit(self, version: int, actions: List[Dict[str, Any]],
                op: str) -> None:
        os.makedirs(_log_dir(self.path), exist_ok=True)
        actions = actions + [{"commitInfo": {
            "timestamp": int(time.time() * 1000), "operation": op}}]
        payload = "\n".join(json.dumps(a) for a in actions) + "\n"
        target = _version_file(self.path, version)
        try:
            fd = os.open(target, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            raise CommitConflict(f"version {version} already committed")
        with os.fdopen(fd, "w") as f:
            f.write(payload)

    def _write_data_file(self, table: pa.Table) -> Dict[str, Any]:
        name = f"part-{uuid.uuid4().hex}.parquet"
        full = os.path.join(self.path, name)
        os.makedirs(self.path, exist_ok=True)
        pq.write_table(table, full, compression="snappy")
        # per-file statistics (reference: GpuStatisticsCollection during
        # the GPU write — min/max/nullCount power data skipping)
        stats = {"numRecords": table.num_rows, "minValues": {},
                 "maxValues": {}, "nullCount": {}}
        for col in table.column_names:
            c = table.column(col)
            stats["nullCount"][col] = c.null_count
            try:
                import pyarrow.compute as pc
                if table.num_rows > c.null_count:
                    mn = pc.min(c).as_py()
                    mx = pc.max(c).as_py()
                    if not isinstance(mn, (bytes,)):
                        stats["minValues"][col] = _json_safe(mn)
                        stats["maxValues"][col] = _json_safe(mx)
            except Exception:
                pass
        return {"add": {"path": name, "size": os.path.getsize(full),
                        "dataChange": True, "stats": json.dumps(stats)}}

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------

    @classmethod
    def write(cls, path: str, table: pa.Table, mode: str = "append",
              max_retries: int = 10,
              z_order_by: Optional[Sequence[str]] = None,
              files: int = 1) -> "DeltaTable":
        if z_order_by:
            # cluster rows along the space-filling curve on the engine
            # (reference: delta z-order acceleration, ZOrderRules)
            from ..plan import Session, table as df_table
            from ..exec.sort import asc
            from ..expressions.base import col
            from ..expressions.zorder import zorder_key
            key = zorder_key(*[col(c) for c in z_order_by])
            ses = Session()
            table = ses.collect(df_table(table).order_by(asc(key)))
        dt = cls(path)
        for _ in range(max_retries):
            latest = dt.latest_version()
            actions: List[Dict[str, Any]] = []
            if latest < 0:
                actions.append({"metaData": {
                    "id": uuid.uuid4().hex,
                    "format": {"provider": "parquet"},
                    "schemaString": json.dumps(
                        {"fields": [{"name": n} for n in
                                    table.column_names]}),
                    "createdTime": int(time.time() * 1000)}})
            elif mode == "overwrite":
                snap = dt.snapshot()
                for f in snap.files:
                    actions.append({"remove": {
                        "path": os.path.relpath(f, path),
                        "dataChange": True}})
            elif mode != "append":
                raise ValueError(mode)
            if files <= 1:
                actions.append(dt._write_data_file(table))
            else:
                step = -(-table.num_rows // files)
                for off in range(0, table.num_rows, step):
                    actions.append(dt._write_data_file(
                        table.slice(off, step)))
            try:
                dt._commit(latest + 1, actions,
                           "WRITE" if latest < 0 else mode.upper())
                return dt
            except CommitConflict:
                continue
        raise CommitConflict(f"gave up after {max_retries} retries")

    def to_dataframe(self, version: Optional[int] = None,
                     num_slices: int = 1):
        """Snapshot read as a DataFrame (GPU scan path)."""
        from .scan import read_parquet
        snap = self.snapshot(version)
        if not snap.files:
            raise ValueError("empty table snapshot")
        return read_parquet(snap.files, num_slices=num_slices)

    def delete(self, predicate: Expression, session=None) -> int:
        """Copy-on-write DELETE (reference: GpuDelete command). Returns the
        number of deleted rows."""
        from ..plan import Session, table as df_table
        from ..expressions.comparison import Not
        from ..expressions.boolean import And
        from ..expressions.base import lit
        ses = session or Session()
        snap = self.snapshot()
        actions: List[Dict[str, Any]] = []
        deleted = 0
        for f in snap.files:
            t = _read_data_file(f, self.rebase_mode)
            # DELETE removes rows where the predicate is TRUE; false and
            # null-valued rows stay (null OR true short-circuits in Or)
            keep_cond = Not(predicate) | _pred_null(predicate)
            kept = ses.collect(df_table(t).where(keep_cond))
            dropped = t.num_rows - kept.num_rows
            if dropped <= 0:
                continue
            deleted += dropped
            actions.append({"remove": {
                "path": os.path.relpath(f, self.path), "dataChange": True}})
            if kept.num_rows:
                actions.append(self._write_data_file(kept))
        if actions:
            self._commit(snap.version + 1, actions, "DELETE")
        return deleted

    def update(self, assignments: Dict[str, Expression],
               predicate: Expression, session=None) -> int:
        """Copy-on-write UPDATE (reference: GpuUpdate command)."""
        from ..plan import Session, table as df_table
        from ..expressions.base import col
        from ..expressions.conditional import If
        ses = session or Session()
        snap = self.snapshot()
        actions: List[Dict[str, Any]] = []
        updated = 0
        for f in snap.files:
            t = _read_data_file(f, self.rebase_mode)
            matched = ses.collect(df_table(t).where(predicate))
            if matched.num_rows == 0:
                continue
            updated += matched.num_rows
            exprs = []
            for name in t.column_names:
                if name in assignments:
                    exprs.append(If(predicate, assignments[name],
                                    col(name)).alias(name))
                else:
                    exprs.append(col(name).alias(name))
            rewritten = ses.collect(df_table(t).select(*exprs))
            actions.append({"remove": {
                "path": os.path.relpath(f, self.path), "dataChange": True}})
            actions.append(self._write_data_file(rewritten))
        if actions:
            self._commit(snap.version + 1, actions, "UPDATE")
        return updated

    def merge(self, source: pa.Table,
              on: "Tuple[List[str], List[str]]",
              matched: "List[MergeClause]" = (),
              not_matched: "List[MergeClause]" = (),
              not_matched_by_source: "List[MergeClause]" = (),
              session=None) -> Dict[str, int]:
        """MERGE INTO this table USING ``source`` ON equi-keys
        (reference: GpuMergeIntoCommand.scala — touched-file detection,
        cardinality check, per-file copy-on-write rewrite). ``on`` is
        (target_key_names, source_key_names). Clause helpers:
        when_matched_update / when_matched_delete /
        when_not_matched_insert; clause expressions reference target
        columns by name and source columns via ``src_col``."""
        return _merge_impl(self, source, on, list(matched),
                           list(not_matched), list(not_matched_by_source),
                           session)

    def history(self) -> List[Dict[str, Any]]:
        out = []
        for v in range(self.latest_version() + 1):
            with open(_version_file(self.path, v)) as f:
                for line in f:
                    a = json.loads(line)
                    if "commitInfo" in a:
                        out.append({"version": v, **a["commitInfo"]})
        return out


def _pred_null(predicate: Expression) -> Expression:
    from ..expressions.comparison import IsNull
    return IsNull(predicate)


def _json_safe(v):
    import datetime as dt
    import decimal
    if isinstance(v, (dt.date, dt.datetime)):
        return v.isoformat()
    if isinstance(v, decimal.Decimal):
        return str(v)
    if isinstance(v, float) and (v != v or v in (float("inf"),
                                                 float("-inf"))):
        return str(v)
    return v


# ---------------------------------------------------------------------------
# MERGE INTO
# ---------------------------------------------------------------------------

@dataclass
class MergeClause:
    """One WHEN clause. ``assignments=None`` on update/insert means the
    Spark ``*`` shorthand (SET/INSERT every column from the same-named
    source column). In clause conditions/assignments, reference target
    columns by name and source columns via ``src_col("name")``."""

    kind: str                                       # update | delete | insert
    condition: Optional[Expression] = None
    assignments: Optional[Dict[str, Expression]] = None


def src_col(name: str) -> Expression:
    """Reference a SOURCE column inside a merge clause expression."""
    from ..expressions.base import col
    return col(_SRC_PREFIX + name)


_SRC_PREFIX = "__src__"


class MergeCardinalityError(ValueError):
    """A target row matched multiple source rows while update/delete
    clauses exist (Delta's deterministic-merge requirement)."""


def when_matched_update(assignments=None, condition=None) -> MergeClause:
    return MergeClause("update", condition, assignments)


def when_matched_delete(condition=None) -> MergeClause:
    return MergeClause("delete", condition, None)


def when_not_matched_insert(assignments=None, condition=None) -> MergeClause:
    return MergeClause("insert", condition, assignments)


def _merge_impl(table_obj: "DeltaTable", source: pa.Table,
                on: "Tuple[List[str], List[str]]",
                matched: "List[MergeClause]",
                not_matched: "List[MergeClause]",
                not_matched_by_source: "List[MergeClause]",
                session) -> Dict[str, int]:
    """Copy-on-write MERGE (reference: GpuMergeIntoCommand.scala — there a
    two-pass touched-file detection + per-file rewrite; same shape here,
    with the join/clause evaluation running through the engine planner).
    """
    from ..expressions.base import col, lit
    from ..expressions.comparison import IsNotNull, Not
    from ..expressions.boolean import And
    from ..expressions.conditional import Coalesce, If
    from ..exec.join import JoinType
    from ..plan import Session, table as df_table

    ses = session or Session()
    tgt_keys, source_keys = on
    snap = table_obj.snapshot()

    # source with prefixed columns (so clause expressions can address both
    # sides without ambiguity)
    src = source.rename_columns([_SRC_PREFIX + c
                                 for c in source.column_names])
    src_keys = [_SRC_PREFIX + k for k in source_keys]

    has_update_delete = bool(matched) or bool(not_matched_by_source)
    tgt_names: Optional[List[str]] = None

    # ---- pass 1: touched files + cardinality check. Reads KEY COLUMNS
    # only, once — the key tables are reused for the insert anti-join;
    # insert-only merges skip the per-file join entirely
    touched: List[str] = []
    key_tables: List[pa.Table] = []
    import numpy as np
    if snap.files:
        tgt_names = pq.read_schema(snap.files[0]).names
    for f in snap.files:
        if not (has_update_delete or not_matched):
            break
        keys_t = pq.read_table(f, columns=tgt_keys)
        from .parquet import rebase_legacy_datetimes
        keys_t = rebase_legacy_datetimes(keys_t, table_obj.rebase_mode, f)
        if not_matched:
            key_tables.append(keys_t)
        if not has_update_delete:
            continue    # insert-only merges never rewrite target files
        pairs = ses.collect(
            df_table(keys_t.append_column(
                "__pos", pa.array(np.arange(keys_t.num_rows,
                                            dtype=np.int64))))
            .join(df_table(src.select(src_keys)),
                  tgt_keys, src_keys, JoinType.INNER))
        if pairs.num_rows:
            touched.append(f)
            pos = pairs.column("__pos").to_pylist()
            if len(set(pos)) != len(pos):
                raise MergeCardinalityError(
                    "a target row matched multiple source rows; MERGE "
                    "with update/delete clauses requires a unique match")

    actions: List[Dict[str, Any]] = []
    stats = {"updated": 0, "deleted": 0, "inserted": 0}
    if tgt_names is None:
        tgt_names = [c for c in source.column_names]

    def matched_flag():
        # after the left join, a non-null source key marks a match
        m = IsNotNull(col(src_keys[0]))
        for k in src_keys[1:]:
            m = And(m, IsNotNull(col(k)))
        return m

    def apply_clauses(is_matched_expr, clauses, star_from_source: bool):
        """Build (keep_cond, per-column value exprs, updated_cond) over the
        joined frame for one clause family. First-true-wins: fold REVERSED
        so earlier clauses override later ones in the nested Ifs. All
        conditions are null-safe (null → clause does not fire)."""
        keep = lit(True)
        updated = lit(False)
        values = {c: col(c) for c in tgt_names}
        for cl in reversed(clauses):
            cond = is_matched_expr
            if cl.condition is not None:
                cond = And(cond, cl.condition)
            cond = Coalesce((cond, lit(False)))
            if cl.kind == "delete":
                keep = If(cond, lit(False), keep)
                updated = If(cond, lit(False), updated)
            elif cl.kind == "update":
                if cl.assignments is not None:
                    assigns = cl.assignments
                elif star_from_source:      # UPDATE SET * shorthand
                    assigns = {c: src_col(c) for c in tgt_names}
                else:
                    assigns = {}
                for c in tgt_names:
                    if c in assigns:
                        values[c] = If(cond, assigns[c], values[c])
                keep = If(cond, lit(True), keep)
                updated = If(cond, lit(True), updated)
        return keep, values, updated

    # ---- pass 2: rewrite touched files
    needs_rewrite = bool(matched) or bool(not_matched_by_source)
    if needs_rewrite:
        rewrite_files = touched if not not_matched_by_source else \
            list(snap.files)
        for f in rewrite_files:
            t = _read_data_file(f, table_obj.rebase_mode)
            joined_df = df_table(t).join(df_table(src), tgt_keys, src_keys,
                                         JoinType.LEFT_OUTER)
            m = matched_flag()
            keep, values, upd = apply_clauses(m, matched, True)
            if not_matched_by_source:
                nm = Coalesce((Not(m), lit(True)))
                keep2, values2, upd2 = apply_clauses(
                    nm, not_matched_by_source, False)
                # compose: matched rows take the matched family, others nmbs
                for c in tgt_names:
                    values[c] = If(m, values[c], values2[c])
                keep = If(m, keep, keep2)
                upd = If(m, upd, upd2)
            # ONE pass: the update flag rides along as an extra column and
            # is counted host-side (re-collecting the join would double the
            # most expensive work of the merge)
            out = ses.collect(
                joined_df.where(keep)
                .select(*([values[c].alias(c) for c in tgt_names] +
                          [Coalesce((upd, lit(False))).alias("__upd")])))
            stats["updated"] += sum(
                1 for u in out.column("__upd").to_pylist() if u)
            out = out.drop_columns(["__upd"])
            before = t.num_rows
            # row accounting: deletes shrink, updates keep count
            stats["deleted"] += max(0, before - out.num_rows)
            actions.append({"remove": {
                "path": os.path.relpath(f, table_obj.path),
                "dataChange": True}})
            if out.num_rows:
                actions.append(table_obj._write_data_file(
                    out.cast(t.schema)))

    # ---- inserts: source rows matched by NO target row (global anti join)
    if not_matched:
        whole = pa.concat_tables(key_tables) if key_tables else None
        if whole is None:
            unmatched = src
        else:
            unmatched = ses.collect(
                df_table(src).join(df_table(whole), src_keys, tgt_keys,
                                   JoinType.LEFT_ANTI))
        if unmatched.num_rows:
            udf = df_table(unmatched)
            keep = lit(False)
            values = {}
            for cl in reversed(not_matched):
                cond = lit(True) if cl.condition is None else cl.condition
                assigns = cl.assignments or \
                    {c: src_col(c) for c in tgt_names}
                for c in tgt_names:
                    if c not in values:
                        values[c] = lit(None)
                    if c in assigns:
                        values[c] = If(cond, assigns[c], values[c])
                keep = If(cond, lit(True), keep)
            ins = ses.collect(udf.where(keep).select(
                *[values[c].alias(c) for c in tgt_names]))
            if ins.num_rows:
                # align insert dtypes with the target schema
                tgt_schema = pq.read_schema(snap.files[0]) \
                    if snap.files else None
                if tgt_schema is not None:
                    ins = ins.cast(tgt_schema)
                stats["inserted"] = ins.num_rows
                actions.append(table_obj._write_data_file(ins))

    if actions:
        table_obj._commit(snap.version + 1, actions, "MERGE")
    return stats
