"""Transactional table format (Delta-protocol style).

Reference: delta-lake/ (SURVEY.md §2.12, 9,721 LoC) — GPU-accelerated Delta
writes with optimistic transactions (GpuOptimisticTransaction), DELETE/
UPDATE command rewrites, per-file statistics collection. This module is the
TPU-native equivalent on the same on-disk protocol shape: a `_delta_log/`
of ordered JSON commits holding metaData/add/remove actions over parquet
data files, optimistic concurrency via O_EXCL commit-file creation, row-
level DELETE/UPDATE as copy-on-write file rewrites executed by the TPU
engine, snapshot isolation and time travel by log replay.

(MERGE INTO and z-ordered layout land in a later round; the log protocol
here already carries what they need.)
"""

from __future__ import annotations

import json
import os
import time
import uuid
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence

import pyarrow as pa
import pyarrow.parquet as pq

from ..batch import Schema
from ..expressions.base import Expression
from .. import types as T


class CommitConflict(Exception):
    """Another writer committed this version first (optimistic retry)."""


def _log_dir(path: str) -> str:
    return os.path.join(path, "_delta_log")


def _version_file(path: str, v: int) -> str:
    return os.path.join(_log_dir(path), f"{v:020d}.json")


@dataclass
class Snapshot:
    version: int
    files: List[str]
    metadata: Dict[str, Any]

    @property
    def schema_json(self):
        return self.metadata.get("schemaString")


class DeltaTable:
    def __init__(self, path: str):
        self.path = path

    # ------------------------------------------------------------------
    # log replay
    # ------------------------------------------------------------------

    def latest_version(self) -> int:
        d = _log_dir(self.path)
        if not os.path.isdir(d):
            return -1
        vs = [int(f.split(".")[0]) for f in os.listdir(d)
              if f.endswith(".json")]
        return max(vs) if vs else -1

    def snapshot(self, version: Optional[int] = None) -> Snapshot:
        latest = self.latest_version()
        if latest < 0:
            raise FileNotFoundError(f"not a delta table: {self.path}")
        v = latest if version is None else version
        if v > latest:
            raise ValueError(f"version {v} > latest {latest} (time travel "
                             f"only goes backwards)")
        live: Dict[str, bool] = {}
        metadata: Dict[str, Any] = {}
        for i in range(v + 1):
            with open(_version_file(self.path, i)) as f:
                for line in f:
                    action = json.loads(line)
                    if "metaData" in action:
                        metadata = action["metaData"]
                    elif "add" in action:
                        live[action["add"]["path"]] = True
                    elif "remove" in action:
                        live.pop(action["remove"]["path"], None)
        files = [os.path.join(self.path, p) for p in sorted(live)]
        return Snapshot(v, files, metadata)

    # ------------------------------------------------------------------
    # commits (optimistic: O_EXCL create of the next version file)
    # ------------------------------------------------------------------

    def _commit(self, version: int, actions: List[Dict[str, Any]],
                op: str) -> None:
        os.makedirs(_log_dir(self.path), exist_ok=True)
        actions = actions + [{"commitInfo": {
            "timestamp": int(time.time() * 1000), "operation": op}}]
        payload = "\n".join(json.dumps(a) for a in actions) + "\n"
        target = _version_file(self.path, version)
        try:
            fd = os.open(target, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            raise CommitConflict(f"version {version} already committed")
        with os.fdopen(fd, "w") as f:
            f.write(payload)

    def _write_data_file(self, table: pa.Table) -> Dict[str, Any]:
        name = f"part-{uuid.uuid4().hex}.parquet"
        full = os.path.join(self.path, name)
        os.makedirs(self.path, exist_ok=True)
        pq.write_table(table, full, compression="snappy")
        # per-file statistics (reference: GpuStatisticsCollection during
        # the GPU write — min/max/nullCount power data skipping)
        stats = {"numRecords": table.num_rows, "minValues": {},
                 "maxValues": {}, "nullCount": {}}
        for col in table.column_names:
            c = table.column(col)
            stats["nullCount"][col] = c.null_count
            try:
                import pyarrow.compute as pc
                if table.num_rows > c.null_count:
                    mn = pc.min(c).as_py()
                    mx = pc.max(c).as_py()
                    if not isinstance(mn, (bytes,)):
                        stats["minValues"][col] = _json_safe(mn)
                        stats["maxValues"][col] = _json_safe(mx)
            except Exception:
                pass
        return {"add": {"path": name, "size": os.path.getsize(full),
                        "dataChange": True, "stats": json.dumps(stats)}}

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------

    @classmethod
    def write(cls, path: str, table: pa.Table, mode: str = "append",
              max_retries: int = 10,
              z_order_by: Optional[Sequence[str]] = None,
              files: int = 1) -> "DeltaTable":
        if z_order_by:
            # cluster rows along the space-filling curve on the engine
            # (reference: delta z-order acceleration, ZOrderRules)
            from ..plan import Session, table as df_table
            from ..exec.sort import asc
            from ..expressions.base import col
            from ..expressions.zorder import zorder_key
            key = zorder_key(*[col(c) for c in z_order_by])
            ses = Session()
            table = ses.collect(df_table(table).order_by(asc(key)))
        dt = cls(path)
        for _ in range(max_retries):
            latest = dt.latest_version()
            actions: List[Dict[str, Any]] = []
            if latest < 0:
                actions.append({"metaData": {
                    "id": uuid.uuid4().hex,
                    "format": {"provider": "parquet"},
                    "schemaString": json.dumps(
                        {"fields": [{"name": n} for n in
                                    table.column_names]}),
                    "createdTime": int(time.time() * 1000)}})
            elif mode == "overwrite":
                snap = dt.snapshot()
                for f in snap.files:
                    actions.append({"remove": {
                        "path": os.path.relpath(f, path),
                        "dataChange": True}})
            elif mode != "append":
                raise ValueError(mode)
            if files <= 1:
                actions.append(dt._write_data_file(table))
            else:
                step = -(-table.num_rows // files)
                for off in range(0, table.num_rows, step):
                    actions.append(dt._write_data_file(
                        table.slice(off, step)))
            try:
                dt._commit(latest + 1, actions,
                           "WRITE" if latest < 0 else mode.upper())
                return dt
            except CommitConflict:
                continue
        raise CommitConflict(f"gave up after {max_retries} retries")

    def to_dataframe(self, version: Optional[int] = None,
                     num_slices: int = 1):
        """Snapshot read as a DataFrame (GPU scan path)."""
        from .scan import read_parquet
        snap = self.snapshot(version)
        if not snap.files:
            raise ValueError("empty table snapshot")
        return read_parquet(snap.files, num_slices=num_slices)

    def delete(self, predicate: Expression, session=None) -> int:
        """Copy-on-write DELETE (reference: GpuDelete command). Returns the
        number of deleted rows."""
        from ..plan import Session, table as df_table
        from ..expressions.comparison import Not
        from ..expressions.boolean import And
        from ..expressions.base import lit
        ses = session or Session()
        snap = self.snapshot()
        actions: List[Dict[str, Any]] = []
        deleted = 0
        for f in snap.files:
            t = pq.read_table(f)
            # DELETE removes rows where the predicate is TRUE; false and
            # null-valued rows stay (null OR true short-circuits in Or)
            keep_cond = Not(predicate) | _pred_null(predicate)
            kept = ses.collect(df_table(t).where(keep_cond))
            dropped = t.num_rows - kept.num_rows
            if dropped <= 0:
                continue
            deleted += dropped
            actions.append({"remove": {
                "path": os.path.relpath(f, self.path), "dataChange": True}})
            if kept.num_rows:
                actions.append(self._write_data_file(kept))
        if actions:
            self._commit(snap.version + 1, actions, "DELETE")
        return deleted

    def update(self, assignments: Dict[str, Expression],
               predicate: Expression, session=None) -> int:
        """Copy-on-write UPDATE (reference: GpuUpdate command)."""
        from ..plan import Session, table as df_table
        from ..expressions.base import col
        from ..expressions.conditional import If
        ses = session or Session()
        snap = self.snapshot()
        actions: List[Dict[str, Any]] = []
        updated = 0
        for f in snap.files:
            t = pq.read_table(f)
            matched = ses.collect(df_table(t).where(predicate))
            if matched.num_rows == 0:
                continue
            updated += matched.num_rows
            exprs = []
            for name in t.column_names:
                if name in assignments:
                    exprs.append(If(predicate, assignments[name],
                                    col(name)).alias(name))
                else:
                    exprs.append(col(name).alias(name))
            rewritten = ses.collect(df_table(t).select(*exprs))
            actions.append({"remove": {
                "path": os.path.relpath(f, self.path), "dataChange": True}})
            actions.append(self._write_data_file(rewritten))
        if actions:
            self._commit(snap.version + 1, actions, "UPDATE")
        return updated

    def history(self) -> List[Dict[str, Any]]:
        out = []
        for v in range(self.latest_version() + 1):
            with open(_version_file(self.path, v)) as f:
                for line in f:
                    a = json.loads(line)
                    if "commitInfo" in a:
                        out.append({"version": v, **a["commitInfo"]})
        return out


def _pred_null(predicate: Expression) -> Expression:
    from ..expressions.comparison import IsNull
    return IsNull(predicate)


def _json_safe(v):
    import datetime as dt
    import decimal
    if isinstance(v, (dt.date, dt.datetime)):
        return v.isoformat()
    if isinstance(v, decimal.Decimal):
        return str(v)
    if isinstance(v, float) and (v != v or v in (float("inf"),
                                                 float("-inf"))):
        return str(v)
    return v
