"""Per-task columnar writers with job stats.

Reference: GpuFileFormatDataWriter.scala (SingleDirectoryDataWriter /
DynamicPartitionDataWriter / bucketing) + GpuWriteJobStatsTracker — the
reference writes each task's batches straight from the device through a
per-task columnar writer, recording rows/bytes/files; round 1 instead
collected the WHOLE query to the driver and wrote one file
(VERDICT r1 weak #11). This module restores the reference shape:

- each plan partition is a write TASK producing its own part files,
- batches stream through an open writer (no whole-result materialization),
- hive partitioning splits each batch by partition values,
- bucketed writes route rows with the same bit-exact murmur3-pmod used by
  the shuffle (so bucket files line up with hash-exchange partitions),
- a WriteStats tracker aggregates rows/bytes/files/partitions per job.
"""

from __future__ import annotations

import os
import uuid
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import pyarrow as pa
import pyarrow.parquet as pq

from ..batch import ColumnarBatch, Schema, to_arrow


@dataclass
class WriteStats:
    """GpuWriteJobStatsTracker analogue."""

    num_files: int = 0
    num_rows: int = 0
    num_bytes: int = 0
    num_tasks: int = 0
    files: List[str] = field(default_factory=list)
    partition_keys: set = field(default_factory=set)

    @property
    def num_partitions(self) -> int:
        """Distinct hive partition dirs across the whole job."""
        return len(self.partition_keys)

    def describe(self) -> str:
        return (f"{self.num_rows} rows in {self.num_files} files "
                f"({self.num_bytes} bytes) across {self.num_tasks} tasks"
                + (f", {self.num_partitions} partitions"
                   if self.num_partitions else ""))


class _FormatWriter:
    """One open output file."""

    def __init__(self, path: str, schema: pa.Schema, fmt: str,
                 compression: str, header: bool = True):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self.path = path
        self.fmt = fmt
        if fmt == "parquet":
            self._w = pq.ParquetWriter(path, schema,
                                       compression=compression)
        elif fmt == "orc":
            import pyarrow.orc as paorc
            self._w = paorc.ORCWriter(path)
        elif fmt == "csv":
            import pyarrow.csv as pacsv
            self._w = pacsv.CSVWriter(
                path, schema,
                write_options=pacsv.WriteOptions(include_header=header))
        else:
            raise ValueError(f"unknown write format {fmt!r}")

    def write(self, table: pa.Table) -> None:
        if self.fmt == "orc":
            self._w.write(table)
        else:
            self._w.write_table(table)

    def close(self) -> int:
        self._w.close()
        return os.path.getsize(self.path)


class ColumnarWriteTask:
    """Writes one plan partition's stream of batches (the reference's
    per-task GpuFileFormatDataWriter)."""

    def __init__(self, task_id: int, base: str, fmt: str,
                 compression: str, schema: Schema,
                 partition_by: Sequence[str] = (),
                 bucket_spec: Optional[Tuple[List[str], int]] = None,
                 header: bool = True):
        self.task_id = task_id
        self.base = base
        self.fmt = fmt
        self.compression = compression
        self.header = header
        self.schema = schema
        self.partition_by = list(partition_by)
        self.bucket_spec = bucket_spec
        self.out_names = [f.name for f in schema
                          if f.name not in self.partition_by]
        self._writers: Dict[Tuple, _FormatWriter] = {}
        self._uuid = uuid.uuid4().hex[:8]
        self.rows = 0
        self._bucket_ids = None
        if bucket_spec is not None:
            from ..expressions.base import col
            from ..shuffle.partitioning import HashPartitioning
            cols, n = bucket_spec
            part = HashPartitioning([col(c) for c in cols], n).bind(schema)
            self._bucket_ids = jax.jit(lambda b: part.partition_ids(b))

    def _target(self, part_key: Tuple, bucket: Optional[int]) -> str:
        name = f"part-{self.task_id:05d}-{self._uuid}"
        if bucket is not None:
            name += f"_{bucket:05d}"    # Spark bucket file suffix
        name += f".{self.fmt}"
        sub = "/".join(f"{c}={v}" for c, v in
                       zip(self.partition_by, part_key))
        return os.path.join(self.base, sub, name) if sub else \
            os.path.join(self.base, name)

    def _writer(self, part_key: Tuple, bucket: Optional[int],
                arrow_schema: pa.Schema) -> _FormatWriter:
        key = (part_key, bucket)
        w = self._writers.get(key)
        if w is None:
            w = _FormatWriter(self._target(part_key, bucket), arrow_schema,
                              self.fmt, self.compression, self.header)
            self._writers[key] = w
        return w

    def write_batch(self, batch: ColumnarBatch) -> None:
        import numpy as np
        table = to_arrow(batch, self.schema)
        if table.num_rows == 0:
            return
        self.rows += table.num_rows
        buckets = None
        if self._bucket_ids is not None:
            buckets = np.asarray(
                self._bucket_ids(batch))[:table.num_rows]
        out_table = table.select(self.out_names)
        if not self.partition_by and buckets is None:
            self._writer((), None, out_table.schema).write(out_table)
            return
        # split by (partition values, bucket id) with vectorized key
        # codes — a per-row Python loop would serialize the write path
        codes = np.zeros(table.num_rows, np.int64)
        uniques: List[np.ndarray] = []
        for c in self.partition_by:
            vals = np.asarray(table.column(c).to_pandas())
            u, inv = np.unique(vals, return_inverse=True)
            codes = codes * (len(u) + 1) + inv
            uniques.append(u)
        if buckets is not None:
            codes = codes * (int(buckets.max(initial=0)) + 2) + buckets
        order = np.argsort(codes, kind="stable")
        sorted_codes = codes[order]
        starts = np.flatnonzero(
            np.r_[True, sorted_codes[1:] != sorted_codes[:-1]])
        bounds = np.r_[starts, len(sorted_codes)]
        pcols = [table.column(c).to_pylist() for c in self.partition_by]
        for a, b in zip(bounds[:-1], bounds[1:]):
            idxs = order[a:b]
            i0 = int(idxs[0])
            pk = tuple(pc[i0] for pc in pcols)
            bk = int(buckets[i0]) if buckets is not None else None
            piece = out_table.take(pa.array(idxs, pa.int64()))
            self._writer(pk, bk, piece.schema).write(piece)

    def abort(self) -> None:
        """Close and delete this task's partial outputs after a failure
        (footer-less files would poison readers of the directory)."""
        for w in self._writers.values():
            try:
                w.close()
            except Exception:
                pass
            try:
                os.remove(w.path)
            except OSError:
                pass
        self._writers.clear()

    def close(self, stats: WriteStats) -> None:
        for (pk, _), w in self._writers.items():
            size = w.close()
            stats.num_files += 1
            stats.num_bytes += size
            stats.files.append(w.path)
            if pk:
                stats.partition_keys.add(pk)
        stats.num_rows += self.rows
        stats.num_tasks += 1


def write_plan(plan, path: str, fmt: str = "parquet",
               compression: str = "snappy",
               partition_by: Sequence[str] = (),
               bucket_by: Optional[Tuple[List[str], int]] = None,
               header: bool = True) -> WriteStats:
    """Execute a physical plan and write it task-by-task (the reference's
    GpuInsertIntoHadoopFsRelationCommand shape — no driver-side collect)."""
    stats = WriteStats()
    schema = plan.output_schema
    os.makedirs(path, exist_ok=True)
    task = None
    try:
        for p in range(plan.num_partitions):
            task = ColumnarWriteTask(p, path, fmt, compression, schema,
                                     partition_by, bucket_by, header)
            for batch in plan.execute_partition(p):
                task.write_batch(batch)
            task.close(stats)
            task = None
    finally:
        if task is not None:        # a batch raised mid-task: close the
            task.abort()            # open writers, drop partial files
        plan.close()
    return stats
