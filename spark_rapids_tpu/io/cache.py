"""DataFrame cache serializer.

Reference: ParquetCachedBatchSerializer.scala:260 — df.cache() stores
compressed Parquet blobs on the host instead of Spark's row-based
DefaultCachedBatchSerializer, so re-reads decode straight to columnar.
Same design: cached partitions live as in-memory Parquet buffers (snappy),
rebuilt into device batches on demand.
"""

from __future__ import annotations

import io
from typing import Dict, List, Optional

import pyarrow as pa
import pyarrow.parquet as pq

from ..batch import ColumnarBatch, Schema, from_arrow, to_arrow
from ..exec.base import Exec, LeafExec


class CachedRelation:
    """Materialized, parquet-compressed cache of a plan's output."""

    def __init__(self, schema: Schema, partitions: List[bytes]):
        self.schema = schema
        self._partitions = partitions

    @classmethod
    def build(cls, plan: Exec) -> "CachedRelation":
        schema = plan.output_schema
        parts: List[bytes] = []
        for p in range(plan.num_partitions):
            tables = [to_arrow(b, schema) for b in plan.execute_partition(p)]
            buf = io.BytesIO()
            if tables:
                pq.write_table(pa.concat_tables(tables), buf,
                               compression="snappy")
            parts.append(buf.getvalue())
        return cls(schema, parts)

    def size_bytes(self) -> int:
        return sum(len(p) for p in self._partitions)

    @property
    def num_partitions(self) -> int:
        return len(self._partitions)

    def read_all(self) -> pa.Table:
        """Interpreter-side access (LogicalScan.source duck type)."""
        tabs = [self.read_partition(p) for p in range(self.num_partitions)]
        tabs = [t for t in tabs if t is not None]
        if not tabs:
            from .. import types as T
            return pa.table({f.name: pa.array([], T.to_arrow(f.dtype))
                             for f in self.schema})
        return pa.concat_tables(tabs)

    def read_partition(self, p: int) -> Optional[pa.Table]:
        blob = self._partitions[p]
        if not blob:
            return None
        return pq.read_table(io.BytesIO(blob))


class InMemoryRelationExec(LeafExec):
    """Scan over a CachedRelation (reference: GpuInMemoryTableScanExec)."""

    def __init__(self, cached: CachedRelation):
        super().__init__()
        self.cached = cached

    @property
    def output_schema(self) -> Schema:
        return self.cached.schema

    @property
    def num_partitions(self) -> int:
        return self.cached.num_partitions

    def do_execute_partition(self, p: int):
        t = self.cached.read_partition(p)
        if t is None or t.num_rows == 0:
            return
        batch, _ = from_arrow(t, schema=self.cached.schema)
        yield batch
