"""Parquet read/write with predicate + projection pushdown.

Reference: GpuParquetScan.scala:96 (footer parse + row-group filtering via
JNI :539-597, rebase handling), GpuParquetFileFormat.scala:163 (writer).
pyarrow.parquet plays the libcudf-decoder role; predicate pushdown converts
our Expression tree to a pyarrow dataset filter so row groups are pruned in
the C++ reader (the same row-group statistics filtering the reference's
footer JNI does).
"""

from __future__ import annotations

from typing import List, Optional

import pyarrow as pa
import pyarrow.parquet as pq

from ..expressions import base as EB
from ..expressions import comparison as EC
from ..expressions import boolean as EBOOL
from ..expressions.base import Expression
from .source import FileSource


def expression_to_arrow_filter(e: Expression):
    """Best-effort conversion of a predicate to a pyarrow compute
    expression; returns None when any part is unconvertible (the scan then
    filters post-read — pushdown is an optimization, never a semantics
    change, same contract as the reference's footer filter)."""
    import pyarrow.compute as pc
    try:
        return _convert(e, pc)
    except (NotImplementedError, AttributeError):
        return None


def _convert(e: Expression, pc):
    if isinstance(e, EB.UnresolvedColumn):
        return pc.field(e.name)
    if isinstance(e, EB.BoundReference):
        return pc.field(e.name)
    if isinstance(e, EB.Literal):
        return pc.scalar(e.value)
    if isinstance(e, EC.EqualTo):
        return _convert(e.children[0], pc) == _convert(e.children[1], pc)
    if isinstance(e, EC.LessThan):
        return _convert(e.children[0], pc) < _convert(e.children[1], pc)
    if isinstance(e, EC.LessThanOrEqual):
        return _convert(e.children[0], pc) <= _convert(e.children[1], pc)
    if isinstance(e, EC.GreaterThan):
        return _convert(e.children[0], pc) > _convert(e.children[1], pc)
    if isinstance(e, EC.GreaterThanOrEqual):
        return _convert(e.children[0], pc) >= _convert(e.children[1], pc)
    if isinstance(e, EC.Not):
        return ~_convert(e.children[0], pc)
    if isinstance(e, EC.IsNull):
        return _convert(e.children[0], pc).is_null()
    if isinstance(e, EC.IsNotNull):
        return ~_convert(e.children[0], pc).is_null()
    if isinstance(e, EBOOL.And):
        return _convert(e.children[0], pc) & _convert(e.children[1], pc)
    if isinstance(e, EBOOL.Or):
        return _convert(e.children[0], pc) | _convert(e.children[1], pc)
    if isinstance(e, EC.In):
        col = _convert(e.children[0], pc)
        vals = [c.value for c in e.children[1:]
                if isinstance(c, EB.Literal)]
        if len(vals) != len(e.children) - 1:
            raise NotImplementedError
        return col.isin(vals)
    raise NotImplementedError(type(e).__name__)


def predicate_mask(e: Expression, t: pa.Table):
    """Evaluate a pushed-down predicate DIRECTLY with pyarrow compute
    kernels (returns a boolean array), bypassing the acero expression
    engine — measurably faster on the post-decode filter hot path.
    Returns None when any node is outside the pushdown dialect (caller
    keeps the acero expression filter). Null semantics match acero's
    filter: Kleene and/or, comparisons yield null for null inputs, and
    Table.filter drops null-mask rows."""
    import pyarrow.compute as pc

    def val(x):
        if isinstance(x, (EB.UnresolvedColumn, EB.BoundReference)):
            return t.column(x.name)
        if isinstance(x, EB.Literal):
            return x.value
        raise NotImplementedError(type(x).__name__)

    def m(x):
        if isinstance(x, EBOOL.And):
            return pc.and_kleene(m(x.children[0]), m(x.children[1]))
        if isinstance(x, EBOOL.Or):
            return pc.or_kleene(m(x.children[0]), m(x.children[1]))
        if isinstance(x, EC.Not):
            return pc.invert(m(x.children[0]))
        if isinstance(x, EC.IsNull):
            return pc.is_null(val(x.children[0]))
        if isinstance(x, EC.IsNotNull):
            return pc.is_valid(val(x.children[0]))
        ops = {EC.EqualTo: pc.equal, EC.LessThan: pc.less,
               EC.LessThanOrEqual: pc.less_equal,
               EC.GreaterThan: pc.greater,
               EC.GreaterThanOrEqual: pc.greater_equal}
        fn = ops.get(type(x))
        if fn is not None:
            return fn(val(x.children[0]), val(x.children[1]))
        if isinstance(x, EC.In):
            col = val(x.children[0])
            vals = [c.value for c in x.children[1:]
                    if isinstance(c, EB.Literal)]
            if len(vals) != len(x.children) - 1:
                raise NotImplementedError
            return pc.is_in(col, value_set=pa.array(vals))
        raise NotImplementedError(type(x).__name__)

    try:
        return m(e)
    except (NotImplementedError, AttributeError, KeyError, pa.ArrowInvalid,
            pa.ArrowNotImplementedError, TypeError):
        return None


#: first proleptic-Gregorian day (1582-10-15) as days-since-epoch; values
#: below this in a legacy-Spark file carry hybrid-Julian calendar labels
GREGORIAN_CUTOVER_DAYS = -141427
#: footer key legacy Spark (2.x / 3.x LEGACY writes) stamps on files whose
#: datetimes use the hybrid calendar
LEGACY_DATETIME_KEY = b"org.apache.spark.legacyDateTime"
_US_PER_DAY = 86_400_000_000


class DatetimeRebaseError(ValueError):
    """EXCEPTION rebase mode hit an ancient datetime in a legacy file
    (Spark's SparkUpgradeException for parquet rebase)."""


def _julian_civil_from_days(z):
    """Julian-calendar (y, m, d) label for days-since-epoch (numpy)."""
    import numpy as np
    j = z.astype(np.int64) + 2440588          # julian day number at noon
    c = j + 32082
    d = (4 * c + 3) // 1461
    e = c - (1461 * d) // 4
    m = (5 * e + 2) // 153
    day = e - (153 * m + 2) // 5 + 1
    month = m + 3 - 12 * (m // 10)
    year = d - 4800 + m // 10
    return year, month, day


def _gregorian_days_from_civil(y, m, d):
    """Proleptic-Gregorian days-since-epoch for (y, m, d) (numpy; the
    vectorized Hinnant algorithm, same as expressions/datetime.py)."""
    import numpy as np
    y = y - (m <= 2)
    era = np.floor_divide(y, 400)
    yoe = y - era * 400
    mp = np.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return (era * 146097 + doe - 719468).astype(np.int64)


def rebase_julian_to_gregorian_days(days):
    """Spark's LEGACY read rebase: keep the CALENDAR LABEL a legacy writer
    recorded (hybrid-Julian before the cutover) and re-encode it as
    proleptic-Gregorian days (reference: GpuParquetScan rebase handling /
    DateTimeRebaseUtils)."""
    import numpy as np
    days = np.asarray(days)
    ancient = days < GREGORIAN_CUTOVER_DAYS
    if not ancient.any():
        return days
    y, m, d = _julian_civil_from_days(days)
    return np.where(ancient, _gregorian_days_from_civil(y, m, d), days)


def _referenced_columns(e: Expression) -> List[str]:
    """Column names a predicate reads (order-preserving, deduped)."""
    from ..expressions import base as EB
    out: List[str] = []

    def walk(x):
        if isinstance(x, (EB.UnresolvedColumn, EB.BoundReference)):
            if x.name not in out:
                out.append(x.name)
        for c in x.children:
            walk(c)
    walk(e)
    return out


def _rg_can_match(rg_md, names, pred, stats_for=None) -> bool:
    """Conservative footer min/max check: False ONLY when the predicate
    provably excludes every row of the group (reference:
    ParquetFileFilterHandler filterRowGroups). Anything unrecognized —
    computed operands, missing stats, cross-type comparisons — keeps the
    group. ``stats_for`` overrides the pyarrow metadata lookup (the
    native-footer path supplies its own)."""
    from ..expressions import base as EB
    from ..expressions import boolean as EBOOL
    from ..expressions import comparison as EC

    def _pyarrow_stats(name):
        try:
            j = names.index(name)
        except ValueError:
            return None
        st = rg_md.column(j).statistics
        if st is None or not st.has_min_max:
            return None
        return st.min, st.max

    stats_for = stats_for or _pyarrow_stats

    def check(e) -> bool:
        if isinstance(e, EBOOL.And):
            return check(e.children[0]) and check(e.children[1])
        if isinstance(e, EBOOL.Or):
            return check(e.children[0]) or check(e.children[1])
        if isinstance(e, (EC.EqualTo, EC.LessThan, EC.LessThanOrEqual,
                          EC.GreaterThan, EC.GreaterThanOrEqual)):
            l, r = e.children
            flip = False
            if isinstance(l, EB.Literal):
                l, r, flip = r, l, True
            if not (isinstance(l, (EB.UnresolvedColumn, EB.BoundReference))
                    and isinstance(r, EB.Literal)) or r.value is None:
                return True
            mm = stats_for(l.name)
            if mm is None:
                return True
            mn, mx = mm
            v = r.value
            try:
                if isinstance(e, EC.EqualTo):
                    return mn <= v <= mx
                lt = isinstance(e, EC.LessThan)
                le = isinstance(e, EC.LessThanOrEqual)
                gt = isinstance(e, EC.GreaterThan)
                if flip:   # lit OP col  ⇔  col (inverse OP) lit
                    lt, le, gt = gt, isinstance(e, EC.GreaterThanOrEqual), lt
                if lt:
                    return mn < v
                if le:
                    return mn <= v
                if gt:
                    return mx > v
                return mx >= v
            except TypeError:
                return True
        return True

    return check(pred)


class ParquetSource(FileSource):
    format_name = "parquet"

    def __init__(self, *a, rebase_mode: str = "EXCEPTION", **kw):
        # EXCEPTION (Spark's default) | CORRECTED | LEGACY — what to do
        # with pre-1582 dates/timestamps in files stamped with the legacy
        # hybrid-calendar footer key
        super().__init__(*a, **kw)
        #: row groups skipped by footer min/max stats vs the predicate
        self.row_groups_pruned = 0
        #: native C++ chunk decode (rtpu_parquet.cpp); per-row-group
        #: pyarrow fallback for anything outside the native subset
        self._native = True
        self._arrow_schemas: dict = {}
        self.rebase_mode = rebase_mode.upper()
        if self.rebase_mode not in ("EXCEPTION", "CORRECTED", "LEGACY"):
            raise ValueError(
                f"rebase_mode must be EXCEPTION, CORRECTED or LEGACY, "
                f"got {rebase_mode!r}")

    def apply_conf(self, conf) -> None:
        super().apply_conf(conf)
        from ..config import PARQUET_NATIVE_DECODE
        self._native = bool(conf.get(PARQUET_NATIVE_DECODE.key))

    def _native_read(self, path: str, rg: int, read_cols):
        if not self._native:
            return None
        from .parquet_native import open_native
        nf = open_native(path)
        if nf is None:
            return None
        if self.rebase_mode != "CORRECTED" and \
                nf.has_metadata_key(LEGACY_DATETIME_KEY):
            # legacy hybrid-calendar files: the rebase pass keys off the
            # footer marker in the table's schema metadata, which the
            # native decode does not attach — take the pyarrow path
            return None
        schema = self._arrow_schemas.get(path)
        if schema is None:
            schema = pq.read_schema(path)
            self._arrow_schemas[path] = schema
        cols = list(read_cols) if read_cols is not None else \
            list(schema.names)
        if any(c not in schema.names for c in cols):
            return None      # partition/virtual columns: pyarrow path
        try:
            # _dict_read_columns is empty on predicate-bearing or
            # dict-disabled scans — it owns the fallback conditions
            dict_cols = set(self._dict_read_columns(path)) or None
            return nf.read_row_group(rg, cols, schema, dict_cols)
        except Exception:
            return None      # outside the native subset: pyarrow fallback

    def infer_arrow_schema(self) -> pa.Schema:
        return pq.read_schema(self.files[0])

    def _dict_read_columns(self, path: str) -> List[str]:
        """Top-level string columns to read as dictionary (codes kept
        through decode — the pyarrow half of the RLE_DICTIONARY hand-off;
        the native C++ half is read_row_group_dict). Empty when the scan
        conf disables it OR a predicate is present: host predicate
        evaluation (predicate_mask / acero filters) over dictionary
        arrays is not guaranteed across pyarrow versions."""
        if not getattr(self, "_dict_scan", None) or \
                self.predicate is not None:
            return []
        schema = self._arrow_schemas.get(path)
        if schema is None:
            try:
                schema = pq.read_schema(path)
            except Exception:
                return []
            self._arrow_schemas[path] = schema
        return [f.name for f in schema
                if pa.types.is_string(f.type)
                or pa.types.is_large_string(f.type)]

    def read_file(self, path: str) -> pa.Table:
        t = self._native_read_file(path)
        if t is not None:
            return t
        filt = expression_to_arrow_filter(self.predicate) \
            if self.predicate is not None else None
        if filt is not None:
            import pyarrow.dataset as ds
            # no codes hand-off under a pushed-down filter: acero
            # predicate evaluation over dictionary arrays is not
            # guaranteed across pyarrow versions (same guard as the
            # native path's predicate check in _native_read_row_group)
            dataset = ds.dataset(path, format="parquet")
            t = dataset.to_table(columns=self.columns, filter=filt)
        else:
            t = pq.read_table(path, columns=self.columns,
                              read_dictionary=self._dict_read_columns(path))
        return rebase_legacy_datetimes(t, self.rebase_mode, path)

    def _native_read_file(self, path: str) -> Optional[pa.Table]:
        """Whole-file native decode for the PERFILE/COALESCING readers:
        every row group through the C++ decoder, predicate applied as a
        compute mask. None → pyarrow path."""
        from .parquet_native import open_native
        if not self._native:
            return None
        nf = open_native(path)
        if nf is None or nf.num_row_groups == 0:
            return None
        # the predicate may reference columns outside the projection:
        # read them for the filter, drop them after (dataset-path parity)
        read_cols = self.columns
        if self.predicate is not None and self.columns is not None:
            extra = [c for c in _referenced_columns(self.predicate)
                     if c not in self.columns]
            if extra:
                read_cols = list(self.columns) + extra
        schema = self._arrow_schemas.get(path)
        if schema is None:
            schema = pq.read_schema(path)
            self._arrow_schemas[path] = schema
        if read_cols is not None and \
                any(c not in schema.names for c in read_cols):
            return None      # partition/virtual columns: pyarrow path
        tables = []
        names = list(nf.columns.keys())
        pruned = 0           # applied to the metric only on SUCCESS — a
        # later native-subset fallback re-reads everything via pyarrow
        for rg in range(nf.num_row_groups):
            if self.predicate is not None and not _rg_can_match(
                    None, names, self.predicate,
                    stats_for=lambda n, rg=rg: nf.decoded_stats(rg, n)):
                pruned += 1
                continue
            t = self._native_read(path, rg, read_cols)
            if t is None:
                return None
            tables.append(t)
        self.row_groups_pruned += pruned
        if not tables:
            keep = read_cols if read_cols is not None else schema.names
            t = pa.table({c: pa.array([], type=schema.field(c).type)
                          for c in keep})
        else:
            # per-row-group best effort can leave SOME row groups
            # dictionary-encoded (codes hand-off) and others plain
            # (writer fell back to PLAIN pages mid-file): normalize
            # to plain before the concat
            from .source import _concat_normalized
            t = _concat_normalized(tables)
        if self.predicate is not None:
            mask = predicate_mask(self.predicate, t)
            if mask is not None:
                t = t.filter(mask)
            else:
                filt = expression_to_arrow_filter(self.predicate)
                if filt is not None:
                    t = t.filter(filt)
        if read_cols is not self.columns and self.columns is not None:
            t = t.select(self.columns)
        return t

    def row_group_counts(self, path: str) -> List[int]:
        f = pq.ParquetFile(path)
        return [f.metadata.row_group(i).num_rows
                for i in range(f.metadata.num_row_groups)]

    # ------------------------------------------------------------------
    # Row-group-parallel decode (reference: GpuParquetScan footer
    # filterRowGroups + MultiFileCloudParquetPartitionReader). Whole-file
    # ds.to_table tasks oversubscribe the pool with their own internal
    # fan-out; one single-threaded task per ROW GROUP measured 64 ms →
    # 47 ms on the 8×256K-row bench split (tools/profile_round4 notes).
    # ------------------------------------------------------------------

    def decode_tasks(self, files):
        filt = expression_to_arrow_filter(self.predicate) \
            if self.predicate is not None else None
        # the dataset path filters BEFORE projection: a predicate column
        # outside the projection must be read for the filter and dropped
        # after it
        read_cols = self.columns
        if filt is not None and self.columns is not None:
            extra = [c for c in _referenced_columns(self.predicate)
                     if c not in self.columns]
            if extra:
                read_cols = list(self.columns) + extra
        # footers fetched through the shared pool so slow storage doesn't
        # serialize N footer round trips before the first decode. With the
        # native decoder on, the C++ thrift footer parse replaces pyarrow
        # metadata entirely (reference: the JNI footer parse,
        # GpuParquetScan.scala:539-597); files the native parser cannot
        # handle fall back to pyarrow metadata per file.
        from .source import reader_pool
        pool = reader_pool(self.num_threads)

        def footer_of(p):
            if self._native:
                from .parquet_native import open_native
                nf = open_native(p)
                if nf is not None:
                    return nf
            return pq.ParquetFile(p, memory_map=True).metadata

        footers = list(pool.map(footer_of, files))
        tasks = []
        for path, md in zip(files, footers):
            native = not isinstance(md, pq.FileMetaData)
            if native:
                names = list(md.columns.keys())
                kvm_has_legacy = md.has_metadata_key(LEGACY_DATETIME_KEY)
                n_rgs = md.num_row_groups
            else:
                names = [md.schema.column(j).path
                         for j in range(md.num_columns)]
                kvm_has_legacy = LEGACY_DATETIME_KEY in (md.metadata or {})
                n_rgs = md.num_row_groups
            # legacy-rebase files: footer stats carry HYBRID-calendar
            # day/micro values while the decode path re-encodes them
            # proleptic-Gregorian (LEGACY mode) — raw stats vs rebased
            # literals would wrongly prune MATCHING groups (data loss),
            # so stats pruning is disabled for such files
            legacy = kvm_has_legacy and self.rebase_mode != "CORRECTED"
            for i in range(n_rgs):
                if self.predicate is not None and not legacy:
                    if native:
                        keep = _rg_can_match(
                            None, names, self.predicate,
                            stats_for=lambda n, md=md, i=i:
                            md.decoded_stats(i, n))
                    else:
                        keep = _rg_can_match(md.row_group(i), names,
                                             self.predicate)
                    if not keep:
                        self.row_groups_pruned += 1
                        continue
                tasks.append((path, lambda path=path, i=i:
                              self._decode_row_group(path, i, filt,
                                                     read_cols)))
        return tasks

    def _decode_row_group(self, path: str, rg: int, filt,
                          read_cols) -> pa.Table:
        t = self._native_read(path, rg, read_cols)
        if t is None:
            # fresh reader per task: pq.ParquetFile is not documented
            # thread-safe for concurrent row-group reads; mmap open is cheap
            pf = pq.ParquetFile(path, memory_map=True)
            t = pf.read_row_group(rg, columns=read_cols, use_threads=False)
        t = rebase_legacy_datetimes(t, self.rebase_mode, path)
        if filt is not None:
            mask = predicate_mask(self.predicate, t)
            t = t.filter(filt if mask is None else mask)
            if read_cols is not self.columns:
                t = t.select(self.columns)
        # unconvertible predicates fall back to the engine's own
        # post-scan FilterExec (planner keeps it in the plan)
        return t


def rebase_legacy_datetimes(t: pa.Table, rebase_mode: str,
                            path: str = "<table>") -> pa.Table:
    """Apply Spark's parquet datetime-rebase policy to a read table.
    Shared by EVERY parquet decode path (scan, Delta, Iceberg, cache) —
    the legacy footer key travels in the table's schema metadata, so no
    second footer parse is needed."""
    if rebase_mode == "CORRECTED":
        return t
    has_datetime = any(
        pa.types.is_date(f.type) or pa.types.is_timestamp(f.type)
        for f in t.schema)
    if not has_datetime:
        return t
    if LEGACY_DATETIME_KEY not in (t.schema.metadata or {}):
        return t        # modern writer: labels already proleptic
    import numpy as np
    import pyarrow.compute as pc
    cols = []
    changed = False
    for i, f in enumerate(t.schema):
        col = t.column(i)
        # fill_null BEFORE to_numpy: a nullable chunked array would
        # otherwise come back as float64, which both fails the cast
        # back and cannot hold pre-1582 microseconds exactly (> 2^53)
        if pa.types.is_date(f.type):
            mask = np.asarray(col.is_null())
            days = np.asarray(pc.fill_null(
                col.cast(pa.int32()).combine_chunks(), 0))
            ancient = (days < GREGORIAN_CUTOVER_DAYS) & ~mask
            if ancient.any():
                if rebase_mode == "EXCEPTION":
                    raise DatetimeRebaseError(
                        f"{path}: column {f.name} holds pre-1582 "
                        f"dates written by a legacy hybrid-calendar "
                        f"Spark; set rebase_mode to LEGACY (rebase) "
                        f"or CORRECTED (read as-is)")
                days = rebase_julian_to_gregorian_days(days)
                col = pa.chunked_array([pa.Array.from_pandas(
                    days.astype("int32"), mask=mask).cast(f.type)])
                changed = True
        elif pa.types.is_timestamp(f.type):
            mask = np.asarray(col.is_null())
            us = np.asarray(pc.fill_null(
                col.cast(pa.timestamp("us", tz=f.type.tz))
                .cast(pa.int64()).combine_chunks(), 0))
            day = np.floor_divide(us, _US_PER_DAY)
            ancient = (day < GREGORIAN_CUTOVER_DAYS) & ~mask
            if ancient.any():
                if rebase_mode == "EXCEPTION":
                    raise DatetimeRebaseError(
                        f"{path}: column {f.name} holds pre-1582 "
                        f"timestamps written by a legacy "
                        f"hybrid-calendar Spark; set rebase_mode to "
                        f"LEGACY or CORRECTED")
                tod = us - day * _US_PER_DAY
                day2 = rebase_julian_to_gregorian_days(day)
                us = day2 * _US_PER_DAY + tod
                # round-trip through us, then back to the ORIGINAL
                # field type (tz and unit preserved)
                col = pa.chunked_array([pa.Array.from_pandas(
                    us, mask=mask).cast(pa.timestamp(
                        "us", tz=f.type.tz)).cast(f.type)])
                changed = True
        cols.append(col)
    if not changed:
        return t
    # untouched columns keep their exact types: reuse the schema
    return pa.table(cols, schema=t.schema)


def write_parquet(table: pa.Table, path: str,
                  compression: str = "snappy",
                  row_group_rows: int = 1 << 20,
                  partition_by: Optional[List[str]] = None) -> List[str]:
    """Write a table (reference: GpuParquetFileFormat + partitioned
    GpuFileFormatDataWriter). Returns written file paths."""
    import os
    if partition_by:
        import pyarrow.dataset as ds
        ds.write_dataset(table, path, format="parquet",
                         partitioning=ds.partitioning(
                             pa.schema([table.schema.field(c)
                                        for c in partition_by]),
                             flavor="hive"),
                         existing_data_behavior="overwrite_or_ignore")
        return [os.path.join(dp, f) for dp, _, fs in os.walk(path)
                for f in fs if f.endswith(".parquet")]
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    pq.write_table(table, path, compression=compression,
                   row_group_size=row_group_rows)
    return [path]
