"""Parquet read/write with predicate + projection pushdown.

Reference: GpuParquetScan.scala:96 (footer parse + row-group filtering via
JNI :539-597, rebase handling), GpuParquetFileFormat.scala:163 (writer).
pyarrow.parquet plays the libcudf-decoder role; predicate pushdown converts
our Expression tree to a pyarrow dataset filter so row groups are pruned in
the C++ reader (the same row-group statistics filtering the reference's
footer JNI does).
"""

from __future__ import annotations

from typing import List, Optional

import pyarrow as pa
import pyarrow.parquet as pq

from ..expressions import base as EB
from ..expressions import comparison as EC
from ..expressions import boolean as EBOOL
from ..expressions.base import Expression
from .source import FileSource


def expression_to_arrow_filter(e: Expression):
    """Best-effort conversion of a predicate to a pyarrow compute
    expression; returns None when any part is unconvertible (the scan then
    filters post-read — pushdown is an optimization, never a semantics
    change, same contract as the reference's footer filter)."""
    import pyarrow.compute as pc
    try:
        return _convert(e, pc)
    except (NotImplementedError, AttributeError):
        return None


def _convert(e: Expression, pc):
    if isinstance(e, EB.UnresolvedColumn):
        return pc.field(e.name)
    if isinstance(e, EB.BoundReference):
        return pc.field(e.name)
    if isinstance(e, EB.Literal):
        return pc.scalar(e.value)
    if isinstance(e, EC.EqualTo):
        return _convert(e.children[0], pc) == _convert(e.children[1], pc)
    if isinstance(e, EC.LessThan):
        return _convert(e.children[0], pc) < _convert(e.children[1], pc)
    if isinstance(e, EC.LessThanOrEqual):
        return _convert(e.children[0], pc) <= _convert(e.children[1], pc)
    if isinstance(e, EC.GreaterThan):
        return _convert(e.children[0], pc) > _convert(e.children[1], pc)
    if isinstance(e, EC.GreaterThanOrEqual):
        return _convert(e.children[0], pc) >= _convert(e.children[1], pc)
    if isinstance(e, EC.Not):
        return ~_convert(e.children[0], pc)
    if isinstance(e, EC.IsNull):
        return _convert(e.children[0], pc).is_null()
    if isinstance(e, EC.IsNotNull):
        return ~_convert(e.children[0], pc).is_null()
    if isinstance(e, EBOOL.And):
        return _convert(e.children[0], pc) & _convert(e.children[1], pc)
    if isinstance(e, EBOOL.Or):
        return _convert(e.children[0], pc) | _convert(e.children[1], pc)
    if isinstance(e, EC.In):
        col = _convert(e.children[0], pc)
        vals = [c.value for c in e.children[1:]
                if isinstance(c, EB.Literal)]
        if len(vals) != len(e.children) - 1:
            raise NotImplementedError
        return col.isin(vals)
    raise NotImplementedError(type(e).__name__)


class ParquetSource(FileSource):
    format_name = "parquet"

    def infer_arrow_schema(self) -> pa.Schema:
        return pq.read_schema(self.files[0])

    def read_file(self, path: str) -> pa.Table:
        filt = expression_to_arrow_filter(self.predicate) \
            if self.predicate is not None else None
        if filt is not None:
            import pyarrow.dataset as ds
            dataset = ds.dataset(path, format="parquet")
            return dataset.to_table(columns=self.columns, filter=filt)
        return pq.read_table(path, columns=self.columns)

    def row_group_counts(self, path: str) -> List[int]:
        f = pq.ParquetFile(path)
        return [f.metadata.row_group(i).num_rows
                for i in range(f.metadata.num_row_groups)]


def write_parquet(table: pa.Table, path: str,
                  compression: str = "snappy",
                  row_group_rows: int = 1 << 20,
                  partition_by: Optional[List[str]] = None) -> List[str]:
    """Write a table (reference: GpuParquetFileFormat + partitioned
    GpuFileFormatDataWriter). Returns written file paths."""
    import os
    if partition_by:
        import pyarrow.dataset as ds
        ds.write_dataset(table, path, format="parquet",
                         partitioning=ds.partitioning(
                             pa.schema([table.schema.field(c)
                                        for c in partition_by]),
                             flavor="hive"),
                         existing_data_behavior="overwrite_or_ignore")
        return [os.path.join(dp, f) for dp, _, fs in os.walk(path)
                for f in fs if f.endswith(".parquet")]
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    pq.write_table(table, path, compression=compression,
                   row_group_size=row_group_rows)
    return [path]
