"""Iceberg table reads: metadata JSON → manifest list → manifests → scan.

Reference: sql-plugin/src/main/java/com/nvidia/spark/rapids/iceberg/
(~5.9k LoC — Spark/Iceberg glue + a GPU parquet reader bridge). The
metadata layer here is implemented directly against the Iceberg spec
(v1/v2): the table directory holds `metadata/v<N>.metadata.json` (plus
`version-hint.text`), each snapshot points to an Avro manifest LIST,
each manifest is an Avro file of data/delete file entries, and data
files are parquet read through the existing multi-file scan framework.

Supported: snapshot selection (current / by id / as-of timestamp — time
travel), identity-transform partition pruning against the scan
predicate, v2 POSITIONAL delete files, and v2 EQUALITY delete files
(anti-join semantics applied per data file at read time). Nested table
schemas fall back like every other scan (TypeSig gates them).
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

import pyarrow as pa
import pyarrow.parquet as pq

from .avro import read_avro_records
from .source import FileSource, rewrite_path


class IcebergError(ValueError):
    pass


_ICE_TO_ARROW = {
    "boolean": pa.bool_(), "int": pa.int32(), "long": pa.int64(),
    "float": pa.float32(), "double": pa.float64(), "date": pa.date32(),
    "string": pa.string(), "binary": pa.binary(),
    "timestamp": pa.timestamp("us"),
    "timestamptz": pa.timestamp("us", tz="UTC"),
}


def _ice_type_to_arrow(t: Any) -> pa.DataType:
    if isinstance(t, str):
        if t in _ICE_TO_ARROW:
            return _ICE_TO_ARROW[t]
        if t.startswith("decimal("):
            p, s = t[len("decimal("):-1].split(",")
            return pa.decimal128(int(p), int(s))
        raise IcebergError(f"unsupported iceberg type {t!r}")
    if isinstance(t, dict):
        k = t.get("type")
        if k == "list":
            return pa.list_(_ice_type_to_arrow(t["element"]))
        if k == "map":
            return pa.map_(_ice_type_to_arrow(t["key"]),
                           _ice_type_to_arrow(t["value"]))
        if k == "struct":
            return pa.struct([
                pa.field(f["name"], _ice_type_to_arrow(f["type"]),
                         not f.get("required", False))
                for f in t["fields"]])
    raise IcebergError(f"unsupported iceberg type {t!r}")


class IcebergTable:
    """Reader for an Iceberg table directory."""

    def __init__(self, path: str, rebase_mode: str = "EXCEPTION"):
        self.path = rewrite_path(path)
        # parquet legacy-datetime policy for data + delete files
        self.rebase_mode = rebase_mode.upper()
        self.meta = self._load_metadata()

    # ---- metadata resolution ----
    def _load_metadata(self) -> dict:
        mdir = os.path.join(self.path, "metadata")
        hint = os.path.join(mdir, "version-hint.text")
        meta_file = None
        if os.path.exists(hint):
            with open(hint) as f:
                v = f.read().strip()
            for pat in (f"v{v}.metadata.json", f"{v}.metadata.json"):
                cand = os.path.join(mdir, pat)
                if os.path.exists(cand):
                    meta_file = cand
                    break
        if meta_file is None:
            cands = [f for f in os.listdir(mdir)
                     if f.endswith(".metadata.json")]
            if not cands:
                raise IcebergError(f"no metadata.json under {mdir}")
            # highest version number wins
            def ver(name):
                head = name.split(".")[0].lstrip("v")
                try:
                    return int(head.split("-")[0])
                except ValueError:
                    return -1
            meta_file = os.path.join(mdir, max(cands, key=ver))
        with open(meta_file) as f:
            return json.load(f)

    def schema_json(self) -> dict:
        m = self.meta
        if "schemas" in m:
            sid = m.get("current-schema-id", 0)
            for s in m["schemas"]:
                if s.get("schema-id") == sid:
                    return s
            return m["schemas"][0]
        return m["schema"]

    def arrow_schema(self) -> pa.Schema:
        return pa.schema([
            pa.field(f["name"], _ice_type_to_arrow(f["type"]),
                     not f.get("required", False))
            for f in self.schema_json()["fields"]])

    def partition_field_names(self) -> List[str]:
        """Identity-transform partition source column names."""
        specs = self.meta.get("partition-specs") or []
        spec_id = self.meta.get("default-spec-id", 0)
        fields = []
        for s in specs:
            if s.get("spec-id") == spec_id:
                fields = s.get("fields", [])
        id_to_name = {f["id"]: f["name"]
                      for f in self.schema_json()["fields"]}
        return [id_to_name.get(f["source-id"], f.get("name"))
                for f in fields if f.get("transform") == "identity"]

    # ---- snapshots ----
    def snapshots(self) -> List[dict]:
        return self.meta.get("snapshots", [])

    def snapshot(self, snapshot_id: Optional[int] = None,
                 as_of_timestamp_ms: Optional[int] = None) -> dict:
        snaps = self.snapshots()
        if not snaps:
            raise IcebergError("table has no snapshots")
        if snapshot_id is not None:
            for s in snaps:
                if s["snapshot-id"] == snapshot_id:
                    return s
            raise IcebergError(f"snapshot {snapshot_id} not found")
        if as_of_timestamp_ms is not None:
            eligible = [s for s in snaps
                        if s["timestamp-ms"] <= as_of_timestamp_ms]
            if not eligible:
                raise IcebergError(
                    f"no snapshot at or before {as_of_timestamp_ms}")
            return max(eligible, key=lambda s: s["timestamp-ms"])
        cur = self.meta.get("current-snapshot-id")
        for s in snaps:
            if s["snapshot-id"] == cur:
                return s
        return snaps[-1]

    def _resolve(self, p: str) -> str:
        """Manifest/data paths may be absolute or table-relative."""
        if os.path.exists(p):
            return p
        tail = p.split(self.path.rstrip("/").split("/")[-1] + "/", 1)
        if len(tail) == 2:
            return os.path.join(self.path, tail[1])
        return os.path.join(self.path, p.lstrip("/"))

    def _manifests(self, snap: dict) -> List[dict]:
        if "manifest-list" in snap:
            return read_avro_records(self._resolve(snap["manifest-list"]))
        # v1 inline manifests list
        return [{"manifest_path": m, "content": 0}
                for m in snap.get("manifests", [])]

    def plan_files(self, snapshot_id: Optional[int] = None,
                   as_of_timestamp_ms: Optional[int] = None,
                   prune: Optional[Dict[str, Any]] = None
                   ) -> Tuple[List[dict], List[dict]]:
        """(data file entries, delete file entries) for a snapshot, with
        identity-partition pruning against `prune` ({col: required value}).
        """
        snap = self.snapshot(snapshot_id, as_of_timestamp_ms)
        part_names = self.partition_field_names()
        data: List[dict] = []
        deletes: List[dict] = []
        for m in self._manifests(snap):
            entries = read_avro_records(self._resolve(m["manifest_path"]))
            for e in entries:
                if e.get("status") == 2:        # DELETED entry
                    continue
                df = dict(e["data_file"])
                # v2 delete scoping: a delete file applies only to data
                # files with a lower (equality) / not-higher (positional)
                # data sequence number
                df["_seq"] = e.get("sequence_number") or \
                    m.get("sequence_number") or 0
                content = df.get("content", 0)
                part = df.get("partition") or {}
                if content == 0 and prune:
                    skip = False
                    for name in part_names:
                        if name in prune and part.get(name) is not None \
                                and part[name] != prune[name]:
                            skip = True
                            break
                    if skip:
                        continue
                (data if content == 0 else deletes).append(df)
        return data, deletes

    # ---- scan ----
    def to_dataframe(self, columns=None, predicate=None,
                     snapshot_id: Optional[int] = None,
                     as_of_timestamp_ms: Optional[int] = None,
                     num_slices: int = 1):
        from ..plan.logical import DataFrame, LogicalScan
        prune = _identity_equalities(predicate)
        data, deletes = self.plan_files(snapshot_id, as_of_timestamp_ms,
                                        prune)
        if not data:
            raise IcebergError("snapshot selects no data files")
        src = IcebergSource(
            [self._resolve(d["file_path"]) for d in data],
            table=self, delete_entries=deletes,
            data_seqs={self._resolve(d["file_path"]): d["_seq"]
                       for d in data},
            columns=columns, predicate=predicate)
        return DataFrame(LogicalScan((), source=src, _schema=src.schema(),
                                     num_slices=num_slices))


def _identity_equalities(predicate) -> Dict[str, Any]:
    """col == literal conjuncts usable for partition pruning."""
    out: Dict[str, Any] = {}
    if predicate is None:
        return out
    from ..expressions.base import Literal, UnresolvedColumn
    from ..expressions.boolean import And
    from ..expressions.comparison import EqualTo

    def walk(e):
        if isinstance(e, And):
            walk(e.children[0])
            walk(e.children[1])
        elif isinstance(e, EqualTo):
            l, r = e.left, e.right
            if isinstance(l, UnresolvedColumn) and isinstance(r, Literal):
                out[l.name] = r.value
            elif isinstance(r, UnresolvedColumn) and isinstance(l, Literal):
                out[r.name] = l.value
    walk(predicate)
    return out


class IcebergSource(FileSource):
    """Parquet data files + row-level deletes applied at read time."""

    format_name = "iceberg"

    def __init__(self, paths, table: IcebergTable,
                 delete_entries: List[dict],
                 data_seqs: Optional[Dict[str, int]] = None, **kw):
        self.table = table
        self.delete_entries = delete_entries
        self.data_seqs = data_seqs or {}
        self._pos_deletes: Optional[Dict[str, List[Tuple[int, int]]]] = None
        self._eq_deletes: Optional[
            List[Tuple[int, List[str], pa.Table]]] = None
        self._del_lock = threading.Lock()
        super().__init__(paths, **kw)

    def infer_arrow_schema(self) -> pa.Schema:
        return self.table.arrow_schema()

    def _load_deletes(self) -> None:
        # the multithreaded reader calls read_file concurrently
        with self._del_lock:
            if self._pos_deletes is not None:
                return
            pos: Dict[str, List[Tuple[int, int]]] = {}
            eq: List[Tuple[int, List[str], pa.Table]] = []
            id_to_name = {f["id"]: f["name"]
                          for f in self.table.schema_json()["fields"]}
            for d in self.delete_entries:
                p = self.table._resolve(d["file_path"])
                from .parquet import rebase_legacy_datetimes
                t = rebase_legacy_datetimes(
                    pq.read_table(p), self.table.rebase_mode, p)
                seq = d.get("_seq", 0)
                if d.get("content", 1) == 1:      # positional
                    for fp, r in zip(t.column("file_path").to_pylist(),
                                     t.column("pos").to_pylist()):
                        # key on the RESOLVED path — basenames collide
                        # across partition directories
                        pos.setdefault(self.table._resolve(fp),
                                       []).append((seq, r))
                else:                              # equality
                    names = [id_to_name[i] for i in d["equality_ids"]]
                    eq.append((seq, names, t.select(names)))
            self._eq_deletes = eq
            self._pos_deletes = pos

    def read_file(self, path: str) -> pa.Table:
        import numpy as np
        self._load_deletes()
        from .parquet import rebase_legacy_datetimes
        t = rebase_legacy_datetimes(
            pq.read_table(path), self.table.rebase_mode, path)
        my_seq = self.data_seqs.get(path, 0)
        # positional deletes target this file at a not-lower sequence
        drops = [r for seq, r in self._pos_deletes.get(path, [])
                 if seq >= my_seq]
        if drops:
            keep = np.ones(t.num_rows, bool)
            keep[drops] = False
            t = t.filter(pa.array(keep))
        # equality deletes: anti-join, STRICTLY newer than this data file
        # (a row re-inserted after the delete must survive — v2 scoping)
        for seq, names, dt in self._eq_deletes:
            if dt.num_rows == 0 or seq <= my_seq:
                continue
            key = set(map(tuple, zip(*[dt.column(n).to_pylist()
                                       for n in names])))
            rows = list(zip(*[t.column(n).to_pylist() for n in names]))
            keep = np.array([r not in key for r in rows], bool) \
                if rows else np.ones(0, bool)
            t = t.filter(pa.array(keep))
        if self.predicate is not None:
            # filter BEFORE projecting: the predicate may reference
            # non-projected columns
            from .parquet import expression_to_arrow_filter
            filt = expression_to_arrow_filter(self.predicate)
            if filt is not None:
                t = t.filter(filt)
        if self.columns:
            t = t.select(self.columns)
        return t


def read_iceberg(path, columns=None, predicate=None,
                 snapshot_id: Optional[int] = None,
                 as_of_timestamp_ms: Optional[int] = None,
                 num_slices: int = 1):
    return IcebergTable(path).to_dataframe(
        columns=columns, predicate=predicate, snapshot_id=snapshot_id,
        as_of_timestamp_ms=as_of_timestamp_ms, num_slices=num_slices)
