"""File scan exec + user-facing read helpers.

Reference: GpuFileSourceScanExec.scala:67 — files are split across
partitions, each partition's reader streams host tables through the chosen
strategy and lands device batches at the H2D boundary.
"""

from __future__ import annotations

from typing import Iterator, List, Optional

from ..batch import ColumnarBatch, Schema, from_arrow
from ..exec.base import LeafExec
from .source import FileSource


class FileSourceScanExec(LeafExec):
    def __init__(self, source: FileSource, num_slices: int = 1,
                 share: Optional[tuple] = None):
        super().__init__()
        from ..exec.base import DEBUG, MODERATE, Metric
        # prefetch pipeline visibility (reference: the multi-file reader's
        # bufferTime/filterTime metric split): overlapTime = decode work
        # hidden behind this exec's device_put/compute
        self.metrics["overlapTime"] = Metric("overlapTime", MODERATE)
        self.metrics["prefetchWaitTime"] = Metric("prefetchWaitTime", DEBUG)
        # (ScanShareRegistry, max_bytes) when cross-query scan sharing
        # is on: single-partition file scans publish their decoded +
        # uploaded device batches refcounted under the source's
        # stat-keyed share_key, so repeat queries ride one decode+H2D
        self._share = share
        self._share_entry = None
        self.source = source
        #: per-PLAN file list: DPP prunes THIS copy, never the shared
        #: FileSource (a pruned source would corrupt later queries)
        self.files = list(source.files)
        self.files_pruned = 0
        self._num_slices = max(1, min(num_slices, len(source.files)))
        self._schema = source.schema()

    def prune_partitions(self, name: str, allowed) -> int:
        """DPP entry: drop this plan's files whose hive partition value
        cannot join (reference: GpuSubqueryBroadcastExec feeding the
        scan's partition filters)."""
        values = getattr(self.source, "_pvalues", {}).get(name)
        if not values:
            return 0
        before = len(self.files)
        keep = [f for f in self.files if values[f] in allowed]
        self.files = keep or self.files[:1]
        pruned = before - len(self.files)
        self.files_pruned += pruned
        # surface the stat on the source for observability/tests
        self.source.files_pruned = getattr(
            self.source, "files_pruned", 0) + pruned
        self._num_slices = max(1, min(self._num_slices, len(self.files)))
        return pruned

    @property
    def name(self):
        return f"FileSourceScanExec[{self.source.format_name}]"

    @property
    def output_schema(self) -> Schema:
        return self._schema

    @property
    def num_partitions(self) -> int:
        return self._num_slices

    def _files_for(self, p: int) -> List[str]:
        return [f for i, f in enumerate(self.files)
                if i % self._num_slices == p]

    def do_execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        if self._share is not None and self._num_slices == 1:
            yield from self._shared_batches()
            return
        yield from self._stream_batches(p)

    def _shared_batches(self) -> Iterator[ColumnarBatch]:
        """Single-partition path through the scan-share registry: the
        first query decodes + uploads and publishes; concurrent and
        following queries over unchanged files replay the refcounted
        device batches (released in do_close)."""
        from ..plan import sharing
        registry, max_bytes = self._share
        key, digest = self.source.share_key(self.files)
        entry, uploader = registry.acquire(key, digest,
                                           max_bytes=max_bytes)
        if uploader:
            try:
                batches = list(self._stream_batches(0))
            except BaseException:
                registry.abort(entry)
                raise
            nbytes = sum(getattr(b, "nbytes", 0) or 0 for b in batches) \
                or (self.source.estimated_bytes() or 0)
            registry.publish(entry, batches, nbytes)
            sharing.metrics().note("scan_share_uploads")
        else:
            sharing.metrics().note("scan_share_hits")
        self._share_entry = entry
        yield from list(entry.batches)

    def do_close(self) -> None:
        entry = self._share_entry
        if entry is not None:
            self._share_entry = None
            self._share[0].release(entry)

    def _stream_batches(self, p: int) -> Iterator[ColumnarBatch]:
        from ..pipeline import close_iterator
        it = self.source.read_split(self._files_for(p),
                                    metrics=self.metrics)
        from ..memory.retry import (maybe_inject, split_host_table,
                                    with_retry)
        try:
            dict_conf = getattr(self.source, "_dict_conf", None)

            def h2d(tbl):
                # dictionary-typed columns (RLE_DICTIONARY scan hand-off)
                # land as codes + dictionary; everything else pads as
                # before. dict_conf carries the session's cardinality
                # thresholds to the fallback decision.
                maybe_inject("scan.h2d")
                batch, _ = from_arrow(tbl, schema=self._schema,
                                      dict_conf=dict_conf)
                return batch

            for host_table in it:
                self.metrics["numOutputRows"].add(host_table.num_rows)
                # H2D under the retry loop: an OOM staging this table
                # halves it (host-side slice) and device_puts the halves —
                # downstream coalesce re-assembles them bit-for-bit
                yield from with_retry(host_table, h2d,
                                      split=split_host_table,
                                      name=self.name)
        finally:
            # consumer abort (limit early-exit) must cancel the prefetch
            # producer promptly — no decode running past the query
            close_iterator(it)


# ---------------------------------------------------------------------------
# read API (session.read.parquet(...) analogue)
# ---------------------------------------------------------------------------

def read_parquet(paths, columns=None, predicate=None, num_slices: int = 1,
                 **kw):
    from ..plan.logical import DataFrame, LogicalScan
    from .parquet import ParquetSource
    src = ParquetSource(paths, columns=columns, predicate=predicate, **kw)
    return DataFrame(LogicalScan((), source=src, _schema=src.schema(),
                                 num_slices=num_slices))


def read_csv(paths, schema=None, header: bool = False, sep: str = ",",
             num_slices: int = 1, **kw):
    from ..plan.logical import DataFrame, LogicalScan
    from .csv import CsvSource
    src = CsvSource(paths, schema=schema, header=header, sep=sep, **kw)
    return DataFrame(LogicalScan((), source=src, _schema=src.schema(),
                                 num_slices=num_slices))


def read_json(paths, schema=None, num_slices: int = 1, **kw):
    from ..plan.logical import DataFrame, LogicalScan
    from .json import JsonSource
    src = JsonSource(paths, schema=schema, **kw)
    return DataFrame(LogicalScan((), source=src, _schema=src.schema(),
                                 num_slices=num_slices))


def read_avro(paths, columns=None, predicate=None, num_slices: int = 1,
              **kw):
    from ..plan.logical import DataFrame, LogicalScan
    from .avro import AvroSource
    src = AvroSource(paths, columns=columns, predicate=predicate, **kw)
    return DataFrame(LogicalScan((), source=src, _schema=src.schema(),
                                 num_slices=num_slices))
