"""FileSource base: file listing, projection/predicate pushdown, reader
strategies (reference: GpuMultiFileReader.scala / PartitionReaderFactory)."""

from __future__ import annotations

import concurrent.futures as cf
import enum
import glob
import os
import threading
from dataclasses import dataclass
from typing import Iterator, List, Optional, Sequence

import pyarrow as pa

from ..batch import Schema, schema_from_arrow
from ..expressions.base import Expression


class ReaderType(enum.Enum):
    PERFILE = "PERFILE"
    COALESCING = "COALESCING"
    MULTITHREADED = "MULTITHREADED"
    AUTO = "AUTO"


# Shared host decode pool (reference: MultiFileReaderThreadPool:123 — one
# pool per executor shared by all multi-file readers).
_POOL: Optional[cf.ThreadPoolExecutor] = None
_POOL_SIZE = 0
_POOL_LOCK = threading.Lock()


def bounded_map(pool, items, fn, window: int, force_parallel: bool = False):
    """Submit ``fn(item)`` over the pool keeping at most ``window`` tasks
    outstanding; yields (item, result) in input order — decoded output
    stays bounded on many-file scans.

    Single-core hosts run CPU-bound work inline: a thread pool cannot
    overlap anything there, and futures + GIL handoff measurably tax the
    decode hot loop (the reference sizes its multi-file pool to the
    executor's cores the same way). ``force_parallel`` keeps the pool for
    I/O-bound work (network fetches overlap even on one core)."""
    if not force_parallel and (
            window <= 1 or (os.cpu_count() or 1) <= 1):
        for item in items:
            yield item, fn(item)
        return
    from collections import deque
    pending = deque()
    it = iter(items)
    exhausted = False
    while pending or not exhausted:
        while not exhausted and len(pending) < window:
            try:
                item = next(it)
            except StopIteration:
                exhausted = True
                break
            pending.append((item, pool.submit(fn, item)))
        if pending:
            item, fut = pending.popleft()
            yield item, fut.result()


def undictionary_table(t: pa.Table) -> pa.Table:
    """Cast dictionary-typed columns back to their value type (the
    compressed-scan hand-off is per-file/per-row-group best effort, so
    concat sites normalize when pieces disagree on dictionary-ness)."""
    cols, changed = [], False
    for i, f in enumerate(t.schema):
        col = t.column(i)
        if pa.types.is_dictionary(f.type):
            col = col.cast(f.type.value_type)
            changed = True
        cols.append(col)
    return pa.table(cols, names=t.column_names) if changed else t


def _concat_normalized(tabs: List[pa.Table]) -> pa.Table:
    """pa.concat_tables, decoding dictionary columns first when the
    pieces' schemas disagree (file A kept RLE_DICTIONARY codes, file B's
    writer fell back to PLAIN pages — otherwise concat raises)."""
    if len(tabs) > 1 and any(t.schema != tabs[0].schema for t in tabs[1:]):
        tabs = [undictionary_table(t) for t in tabs]
    return pa.concat_tables(tabs)


def reader_pool(num_threads: int = 8) -> cf.ThreadPoolExecutor:
    """Shared executor-wide decode pool; grows (never shrinks) when a
    session asks for more width — the old pool finishes its queue and is
    collected."""
    global _POOL, _POOL_SIZE
    with _POOL_LOCK:
        if _POOL is None or num_threads > _POOL_SIZE:
            _POOL = cf.ThreadPoolExecutor(
                max_workers=max(num_threads, _POOL_SIZE),
                thread_name_prefix="multifile-read")
            _POOL_SIZE = max(num_threads, _POOL_SIZE)
        return _POOL


# ---------------------------------------------------------------------------
# Transparent path rewriting (reference: AlluxioUtils.scala:73 — s3:// paths
# rewritten to an alluxio:// cache cluster, with automount). Register
# prefix rules once; every scan then reads through the cache tier.
# ---------------------------------------------------------------------------

_PATH_RULES: List[tuple] = []


def register_path_rewrite(src_prefix: str, dst_prefix: str) -> None:
    _PATH_RULES.append((src_prefix, dst_prefix))


def clear_path_rewrites() -> None:
    _PATH_RULES.clear()


def rewrite_path(p: str) -> str:
    for src, dst in _PATH_RULES:
        if p.startswith(src):
            return dst + p[len(src):]
    return p


def expand_paths(paths) -> List[str]:
    if isinstance(paths, (str, os.PathLike)):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        p = rewrite_path(str(p))
        if os.path.isdir(p):
            for root, _, files in os.walk(p):
                out.extend(os.path.join(root, f) for f in sorted(files)
                           if not f.startswith((".", "_")))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(glob.glob(p)))
        else:
            out.append(p)
    return out


#: hive default-partition marker (null partition value)
_HIVE_NULL = "__HIVE_DEFAULT_PARTITION__"


def _is_int(v: str) -> bool:
    try:
        int(v)
        return True
    except (TypeError, ValueError):
        return False


def hive_partition_values(path: str) -> dict:
    """`key=value` directory components of a path (hive layout). Values
    are %XX-unescaped (hive/Spark escape special chars when writing)."""
    from urllib.parse import unquote
    out = {}
    for comp in os.path.dirname(path).split(os.sep):
        if "=" in comp:
            k, _, v = comp.partition("=")
            if k:
                out[k] = None if v == _HIVE_NULL else unquote(v)
    return out


class FileSource:
    """A format + file list + pushed-down projection/predicate."""

    format_name = "file"

    #: synthetic column name for Spark's input_file_name() expression
    FILE_NAME_COL = "_input_file_name"

    def __init__(self, paths, schema: Optional[Schema] = None,
                 columns: Optional[List[str]] = None,
                 predicate: Optional[Expression] = None,
                 reader_type: ReaderType = ReaderType.AUTO,
                 batch_rows: Optional[int] = None,
                 num_threads: Optional[int] = None,
                 with_file_name: bool = False,
                 hive_partitions: bool = True):
        self.files = expand_paths(paths)
        if not self.files:
            raise FileNotFoundError(f"no files match {paths}")
        self.columns = columns
        self._requested_columns = columns
        self.predicate = predicate
        self.reader_type = reader_type
        # None = defaulted (a later apply_conf may override); an explicit
        # constructor argument always wins over session conf
        self._explicit_batch_rows = batch_rows is not None
        self._explicit_threads = num_threads is not None
        self.batch_rows = batch_rows if batch_rows is not None else 1 << 20
        self.num_threads = num_threads if num_threads is not None else 8
        self.with_file_name = with_file_name
        self._schema = schema
        # hive-layout partition columns (reference: partition-values
        # handling in GpuFileSourceScanExec): key=value path components
        # become constant columns; files_pruned counts DPP removals
        self.partition_schema: List[tuple] = []
        self._pvalues: dict = {}
        self.files_pruned = 0
        #: session-conf overrides (apply_conf); None = registry defaults
        self._mt_max_tasks: Optional[int] = None
        self._coalesce_par: Optional[int] = None
        self._prefetch_depth: Optional[int] = None
        self._dict_conf: Optional[tuple] = None
        self._dict_scan: Optional[bool] = None
        if hive_partitions:
            self._discover_hive_partitions()
            if self.columns and self.partition_schema:
                pnames = {nm for nm, _ in self.partition_schema}
                # file-level projection excludes partition columns (they
                # come from paths); appended partition fields honor the
                # request
                self.partition_schema = [
                    (nm, kind) for nm, kind in self.partition_schema
                    if nm in self.columns]
                self.columns = [c for c in self.columns
                                if c not in pnames] or None

    def _discover_hive_partitions(self) -> None:
        per_file = [hive_partition_values(f) for f in self.files]
        if not per_file or not per_file[0]:
            return
        names = [k for k in per_file[0]
                 if all(k in pv for pv in per_file)]
        for name in names:
            vals = [pv[name] for pv in per_file]
            typed = vals
            if all(v is None or _is_int(v) for v in vals):
                typed = [None if v is None else int(v) for v in vals]
                # Spark's partition inference yields IntegerType when every
                # value fits int32 (widening to int64 otherwise); matching
                # it keeps round-tripped schemas and join key dtypes stable
                kind = "int" if all(
                    v is None or -(1 << 31) <= v < (1 << 31)
                    for v in typed) else "int64"
            else:
                kind = "string"
            self.partition_schema.append((name, kind))
            self._pvalues[name] = dict(zip(self.files, typed))

    def apply_conf(self, conf) -> None:
        """Planner hook: honor the session's reader confs (thread count,
        batch rows, in-flight bounds) on this source."""
        from ..config import (COALESCING_PARALLEL_FILES,
                              MT_READER_MAX_TASKS,
                              MULTITHREADED_READ_THREADS,
                              PREFETCH_DEPTH, PREFETCH_ENABLED,
                              READER_BATCH_ROWS)
        if not self._explicit_threads:
            self.num_threads = int(conf.get(MULTITHREADED_READ_THREADS.key))
        if not self._explicit_batch_rows:
            self.batch_rows = int(conf.get(READER_BATCH_ROWS.key))
        self._mt_max_tasks = int(conf.get(MT_READER_MAX_TASKS.key))
        self._coalesce_par = int(conf.get(COALESCING_PARALLEL_FILES.key))
        self._prefetch_depth = int(conf.get(PREFETCH_DEPTH.key)) \
            if conf.get(PREFETCH_ENABLED.key) else 0
        from ..config import (DICT_ENCODING_ENABLED, DICT_MAX_CARDINALITY,
                              DICT_MAX_CARD_FRACTION, DICT_SCAN_ENABLED)
        # (enabled, maxCardinality, maxCardinalityFraction) threaded to the
        # H2D boundary (batch.from_arrow) by the scan exec
        self._dict_conf = (bool(conf.get(DICT_ENCODING_ENABLED.key)),
                           int(conf.get(DICT_MAX_CARDINALITY.key)),
                           float(conf.get(DICT_MAX_CARD_FRACTION.key)))
        self._dict_scan = (self._dict_conf[0]
                           and bool(conf.get(DICT_SCAN_ENABLED.key)))

    def partition_value(self, name: str, path: str):
        return self._pvalues[name][path]

    def _decorate(self, t: pa.Table, path: str) -> pa.Table:
        """Attach partition-value and source-path columns (reference:
        partition values + GpuInputFileName resolved from the split),
        then restore the REQUESTED column order."""
        for name, kind in self.partition_schema:
            v = self._pvalues[name][path]
            typ = (pa.int32() if kind == "int" else
                   pa.int64() if kind == "int64" else pa.string())
            t = t.append_column(name, pa.array([v] * t.num_rows, typ))
        if self.with_file_name:
            t = t.append_column(
                self.FILE_NAME_COL,
                pa.array([path] * t.num_rows, pa.string()))
        if self._requested_columns:
            order = [c for c in self._requested_columns
                     if c in t.column_names]
            order += [c for c in t.column_names if c not in order]
            t = t.select(order)
        return t

    def estimated_bytes(self) -> Optional[int]:
        """On-disk size (planner build-side selection input)."""
        try:
            return sum(os.path.getsize(f) for f in self.files)
        except OSError:
            return None

    def share_key(self, files=None):
        """(registry key, invalidation digest) identifying this source's
        decoded + uploaded device batches for the cross-query scan-share
        registry (plan/sharing.py): per-file (path, mtime_ns, size)
        stats — a rewritten file changes its stats, so the stale entry
        is unreachable and ages out of the byte budget — plus every knob
        that changes what lands on the device (projection, predicate,
        batch slicing, dict-encoding conf, decoration columns)."""
        import hashlib
        import json
        stats = []
        for p in (self.files if files is None else files):
            try:
                st = os.stat(p)
                stats.append((str(p), st.st_mtime_ns, st.st_size))
            except OSError:
                stats.append((str(p), -1, -1))
        payload = json.dumps(
            [self.format_name, stats, self.columns,
             str(self.predicate), self.batch_rows, self._dict_conf,
             self._dict_scan, self.with_file_name,
             self.partition_schema], default=str, sort_keys=True)
        digest = hashlib.blake2b(payload.encode("utf-8"),
                                 digest_size=16).hexdigest()
        return ("file", digest), digest

    # ---- format hooks ----
    def infer_arrow_schema(self) -> pa.Schema:
        raise NotImplementedError

    def read_file(self, path: str) -> pa.Table:
        """Decode one file with pushdown applied."""
        raise NotImplementedError

    # ---- shared machinery ----
    def schema(self) -> Schema:
        if self._schema is None:
            s = self.infer_arrow_schema()
            if self.columns:
                s = pa.schema([s.field(c) for c in self.columns])
            for name, kind in self.partition_schema:
                s = s.append(pa.field(
                    name,
                    pa.int32() if kind == "int" else
                    pa.int64() if kind == "int64" else pa.string()))
            if self._requested_columns:
                names = [f.name for f in s]
                order = [c for c in self._requested_columns if c in names]
                order += [c for c in names if c not in order]
                s = pa.schema([s.field(c) for c in order])
            if self.with_file_name:
                # widen ONLY the synthetic path column, not every string
                from .. import types as T
                from ..batch import Field
                ml = max((len(f.encode()) for f in self.files), default=64)
                base = schema_from_arrow(s)
                from ..batch import Schema as _Schema
                self._schema = _Schema(
                    list(base.fields) +
                    [Field(self.FILE_NAME_COL, T.string(max(ml, 64)),
                           False)])
                return self._schema
            self._schema = schema_from_arrow(s)
        return self._schema

    def effective_reader(self) -> ReaderType:
        if self.reader_type is not ReaderType.AUTO:
            return self.reader_type
        # heuristic (reference GpuParquetScan.scala:276): many small files →
        # multithreaded prefetch; few files → coalescing
        return ReaderType.MULTITHREADED if len(self.files) > 2 \
            else ReaderType.COALESCING

    def read_all(self) -> pa.Table:
        tables = [self._decorate(self.read_file(f), f)
                  for f in self.files]
        return _concat_normalized(tables) if tables else None

    def prefetch_depth(self) -> int:
        """Effective prefetch look-ahead: session conf via apply_conf,
        registry defaults otherwise (0 = synchronous)."""
        if self._prefetch_depth is not None:
            return self._prefetch_depth
        from ..config import PREFETCH_DEPTH, PREFETCH_ENABLED, _REGISTRY
        if not _REGISTRY[PREFETCH_ENABLED.key].default:
            return 0
        return int(_REGISTRY[PREFETCH_DEPTH.key].default)

    def read_split(self, files: Sequence[str],
                   metrics=None) -> Iterator[pa.Table]:
        """Host-side table stream for a subset of files, by strategy,
        produced ``prefetch.depth`` batches ahead of the consumer on a
        background thread (reference: GpuMultiFileReader.scala:441
        prefetch) so decode overlaps the consumer's device_put/compute.
        ``metrics`` (an exec's metric dict) receives overlapTime /
        prefetchWaitTime when present. depth=0 (or a single-core host)
        yields the decode generator itself — the synchronous path.

        MULTITHREADED skips the extra stage: its bounded_map window IS a
        decode-ahead pipeline (futures stay in flight between pulls), and
        measurement shows a second handoff stage only costs there
        (docs/profiling.md "prefetch pipeline"). PERFILE/COALESCING
        decode/concat on the consumer thread, which is exactly the serial
        work the prefetch stage hides."""
        it = self._decode_split(files)
        if self.effective_reader() is ReaderType.MULTITHREADED:
            return it
        from ..pipeline import prefetched
        # dedicated thread, NOT the shared reader pool: the producer holds
        # its worker for the whole scan, and the decode tasks it drives
        # submit into that same pool (pool-of-producers deadlock)
        return prefetched(it, self.prefetch_depth(),
                          metrics=metrics, name=f"{self.format_name}-scan")

    def _decode_split(self, files: Sequence[str]) -> Iterator[pa.Table]:
        """The undecorated decode stream (strategy dispatch)."""
        mode = self.effective_reader()
        if mode is ReaderType.PERFILE:
            for f in files:
                yield self._decorate(self.read_file(f), f)
        elif mode is ReaderType.COALESCING:
            # decode the split's files through the shared pool (bounded by
            # coalescing.numFilesParallel), concat, re-chunk to batch_rows
            # (reference: coalescing reader assembles row groups before H2D)
            from ..config import COALESCING_PARALLEL_FILES, _REGISTRY
            par = max(self._coalesce_par or
                      int(_REGISTRY[COALESCING_PARALLEL_FILES.key].default),
                      1)
            pool = reader_pool(self.num_threads)
            tabs = [self._decorate(t, f)
                    for f, t in bounded_map(pool, files, self.read_file,
                                            par)]
            if not tabs:
                return
            t = _concat_normalized(tabs)
            for off in range(0, max(t.num_rows, 1), self.batch_rows):
                yield t.slice(off, self.batch_rows)
                if t.num_rows == 0:
                    break
        else:  # MULTITHREADED: pipelined background decode
            pool = reader_pool(self.num_threads)
            tasks = self.decode_tasks(files)
            if tasks is None:
                tasks = [(f, (lambda f=f: self.read_file(f)))
                         for f in files]
            # windowed submission: maxTasksInFlight bounds queued decode
            # output so a many-file scan cannot hold the whole dataset in
            # host memory at once
            from ..config import MT_READER_MAX_TASKS, _REGISTRY
            win = max(self._mt_max_tasks or
                      int(_REGISTRY[MT_READER_MAX_TASKS.key].default), 1)
            for (f, _fn), raw in bounded_map(
                    pool, tasks, lambda task: task[1](), win):
                t = self._decorate(raw, f)
                for off in range(0, max(t.num_rows, 1), self.batch_rows):
                    yield t.slice(off, self.batch_rows)
                    if t.num_rows == 0:
                        break

    def decode_tasks(self, files: Sequence[str]):
        """Optional finer-than-file decode units for the MULTITHREADED
        reader: a list of (path, thunk) pairs, each thunk decoding ONE
        unit single-threaded (a parquet row group). None = per-file
        decode. Sub-file units keep the shared pool saturated without
        oversubscribing it with per-task thread fan-out (reference:
        MultiFileCloudParquetPartitionReader chunked reads)."""
        return None
