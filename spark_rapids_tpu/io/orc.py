"""ORC read/write (reference: GpuOrcScan.scala, 2,219 LoC — same shape as
the Parquet scan; the host C++ ORC reader plays libcudf's decoder role)."""

from __future__ import annotations

import pyarrow as pa
import pyarrow.orc as paorc

from .source import FileSource


class OrcSource(FileSource):
    format_name = "orc"

    def infer_arrow_schema(self) -> pa.Schema:
        return paorc.ORCFile(self.files[0]).schema

    def read_file(self, path: str) -> pa.Table:
        t = paorc.ORCFile(path).read(columns=self.columns)
        if self.predicate is not None:
            from .parquet import expression_to_arrow_filter
            filt = expression_to_arrow_filter(self.predicate)
            if filt is not None:
                t = t.filter(filt)
        return t


def write_orc(table: pa.Table, path: str) -> None:
    import os
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    paorc.write_table(table, path)


def read_orc(paths, columns=None, predicate=None, num_slices: int = 1, **kw):
    from ..plan.logical import DataFrame, LogicalScan
    src = OrcSource(paths, columns=columns, predicate=predicate, **kw)
    return DataFrame(LogicalScan((), source=src, _schema=src.schema(),
                                 num_slices=num_slices))
