"""ORC read/write (reference: GpuOrcScan.scala, 2,219 LoC — same shape as
the Parquet scan; the host C++ ORC reader plays libcudf's decoder role).

The round-1 reader materialized the WHOLE file and then filtered
(VERDICT r1 weak #10). It now decodes STRIPE BY STRIPE: each stripe reads
only the needed columns (projection ∪ predicate columns), the predicate
drops rows before the next stripe is touched, and the projection is
applied last — peak memory is one stripe plus survivors. pyarrow exposes
no stripe statistics, so stat-based stripe SKIPPING (the reference's
searchArgument pushdown) comes from this package's OWN ORC tail parser
(orc_meta.py — the metadata section is plain protobuf): stripes whose
min/max provably exclude the predicate are never decoded.
"""

from __future__ import annotations

from typing import List, Optional, Set

import pyarrow as pa
import pyarrow.orc as paorc

from .source import FileSource


def _pred_columns(e) -> Set[str]:
    from ..expressions.base import UnresolvedColumn
    out: Set[str] = set()

    def walk(x):
        if isinstance(x, UnresolvedColumn):
            out.add(x.name)
        for c in x.children:
            walk(c)
    walk(e)
    return out


class OrcSource(FileSource):
    format_name = "orc"

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        #: stripes skipped on footer min/max stats (the reference's
        #: searchArgument stripe pushdown, GpuOrcScan.scala)
        self.stripes_pruned = 0

    def infer_arrow_schema(self) -> pa.Schema:
        return paorc.ORCFile(self.files[0]).schema

    def read_file(self, path: str) -> pa.Table:
        f = paorc.ORCFile(path)
        filt = None
        read_cols: Optional[List[str]] = self.columns
        if self.predicate is not None:
            from .parquet import expression_to_arrow_filter
            filt = expression_to_arrow_filter(self.predicate)
            if filt is not None and read_cols is not None:
                need = set(read_cols) | _pred_columns(self.predicate)
                read_cols = [c for c in f.schema.names if c in need]
        stripe_stats = None
        if self.predicate is not None:
            from .orc_meta import parse_stripe_stats
            stripe_stats = parse_stripe_stats(path)
            if stripe_stats is not None and \
                    len(stripe_stats) != f.nstripes:
                stripe_stats = None       # tail mismatch: never prune
        pieces = []
        for s in range(f.nstripes):
            if stripe_stats is not None:
                from .parquet import _rg_can_match
                stats = stripe_stats[s]
                if not _rg_can_match(None, list(stats), self.predicate,
                                     stats_for=stats.get):
                    self.stripes_pruned += 1
                    continue
            t = f.read_stripe(s, columns=read_cols)
            if isinstance(t, pa.RecordBatch):
                t = pa.Table.from_batches([t])
            if filt is not None:
                t = t.filter(filt)
            if t.num_rows:
                pieces.append(t)
        if pieces:
            t = pa.concat_tables(pieces)
        else:
            # no surviving rows: empty table straight from the file schema
            # (never re-decode a stripe just for its schema)
            fields = [f.schema.field(c) for c in read_cols] \
                if read_cols else list(f.schema)
            t = pa.table({fld.name: pa.array([], type=fld.type)
                          for fld in fields})
        if self.columns:
            t = t.select(self.columns)
        return t


def write_orc(table: pa.Table, path: str) -> None:
    import os
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    paorc.write_table(table, path)


def read_orc(paths, columns=None, predicate=None, num_slices: int = 1, **kw):
    from ..plan.logical import DataFrame, LogicalScan
    src = OrcSource(paths, columns=columns, predicate=predicate, **kw)
    return DataFrame(LogicalScan((), source=src, _schema=src.schema(),
                                 num_slices=num_slices))
