"""Query tracing: end-to-end span timelines, a flight recorder, and
observed per-operator costs.

The engine counts everything (per-collect ``retry.*``/``net.*``/
``lineage.*``/``cache.*`` deltas, ``serving_stats()`` at the fleet tier)
but until this layer it could not answer "where did *this* query's time
go": there was no query identity stitched across client → router →
worker → shuffle peers, and no per-operator timeline. Theseus
(PAPERS.md) argues a distributed query platform lives or dies on knowing
where data movement and compute overlap — you cannot tune overlap you
cannot see — and the GPU-offloading cost models in PAPERS.md need
*measured*, not modeled, per-operator costs. Three surfaces:

1. **Span tree per collect** — a ``query_id`` minted at the client (or
   at query open) and propagated through the plan/router wire headers,
   recompute closures, and replicated-fetch peers. Spans wrap admission
   wait, cache lookups, per-operator execution, serializer pack/unpack,
   per-peer transport fetches (with failover/backoff sub-spans), and
   lineage recomputes. ``span()`` is a no-op costing one thread-local
   read when no trace is active, so the off path stays untouched;
   tracing NEVER changes results (the differential suite proves
   bit-for-bit equality with it on).

2. **Flight recorder** — a bounded ring of the last N query profiles
   plus a slow-query log (``server.trace.slowQueryMs``), held by the
   plan server / router and exposed over the ``trace`` wire op; plus a
   conf-gated JSONL sink (``trace.sink.path``) that
   ``tools/trace_viewer.py`` renders as Chrome/Perfetto trace-event
   JSON — a fleet query becomes one stitched timeline.

3. **Observed-cost store** — per-(shape-fingerprint, operator)
   wall/rows/bytes EWMAs recorded at collect close from the existing
   exec metric hooks, living next to the PR-10 planning cache. This is
   the empirical feed the AQE/CBO re-planning loop (ROADMAP item 3)
   consumes: speedup scores become measured, not modeled.

Clock model: every span carries a wall-clock ``tsUs`` (time.time_ns at
open) and a monotonic ``durUs`` (perf_counter delta). Stitching across
processes relies on a shared host clock; cross-host skew shifts whole
process tracks, never distorts durations (docs/observability.md).
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from collections import OrderedDict, deque
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# metrics (process-wide; Session.metrics() reports `trace.*` deltas the
# way the retry/net/lineage/cache groups do)
# ---------------------------------------------------------------------------


class TraceMetrics:
    """Process-wide tracing counters; sessions report deltas."""

    def __init__(self):
        self._lock = threading.Lock()
        self.span_count = 0
        self.dropped_span_count = 0
        self.profile_count = 0
        self.slow_query_count = 0
        self.cost_observation_count = 0

    def note(self, name: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, name, getattr(self, name) + n)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {
                "spanCount": self.span_count,
                "droppedSpanCount": self.dropped_span_count,
                "profileCount": self.profile_count,
                "slowQueryCount": self.slow_query_count,
                "costObservationCount": self.cost_observation_count,
            }


_METRICS = TraceMetrics()


def metrics() -> TraceMetrics:
    return _METRICS


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------


class Span:
    """One timed section of a query. Durations are monotonic
    (perf_counter); ``ts_us`` is the wall-clock open instant used to
    stitch process tracks together."""

    __slots__ = ("span_id", "parent_id", "name", "kind", "ts_us",
                 "t0_ns", "dur_us", "attrs")

    def __init__(self, span_id: int, parent_id: Optional[int], name: str,
                 kind: str, attrs: Dict[str, Any]):
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.kind = kind
        self.ts_us = time.time_ns() // 1000
        self.t0_ns = time.perf_counter_ns()
        self.dur_us: Optional[int] = None    # None while open
        self.attrs = attrs

    def to_dict(self) -> dict:
        d = {"id": self.span_id, "parent": self.parent_id,
             "name": self.name, "kind": self.kind, "tsUs": self.ts_us,
             "durUs": self.dur_us if self.dur_us is not None else 0}
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        return d


class QueryTrace:
    """Thread-safe span tree of one query. Span ids are allocated under
    a lock so producer threads (writer pools, fetch pools, recompute)
    append concurrently; the per-thread parent chain lives in the
    activation thread-local, not here. Span count is bounded
    (``trace.maxSpansPerQuery``): past the cap spans are counted as
    dropped instead of growing without bound."""

    def __init__(self, query_id: str, component: str = "engine",
                 max_spans: int = 2048):
        self.query_id = query_id
        self.component = component
        self.max_spans = max(1, int(max_spans))
        self._lock = threading.Lock()
        self._spans: List[Span] = []
        self._open: Dict[int, Span] = {}
        self._next_id = 1
        self.dropped = 0
        self.ts_us = time.time_ns() // 1000
        self._t0_ns = time.perf_counter_ns()
        self.dur_us = 0

    def open_span(self, name: str, kind: str, parent_id: Optional[int],
                  attrs: Dict[str, Any]) -> Optional[Span]:
        with self._lock:
            if len(self._spans) >= self.max_spans:
                self.dropped += 1
                _METRICS.note("dropped_span_count")
                return None
            s = Span(self._next_id, parent_id, name, kind, attrs)
            self._next_id += 1
            self._spans.append(s)
            self._open[s.span_id] = s
        _METRICS.note("span_count")
        return s

    def close_span(self, s: Span) -> None:
        dur = (time.perf_counter_ns() - s.t0_ns) // 1000
        with self._lock:
            if self._open.pop(s.span_id, None) is not None:
                s.dur_us = dur

    def finish(self) -> dict:
        """Close every still-open span (an abandoned iterator never
        exhausts its operator span) and return the profile dict."""
        end = time.perf_counter_ns()
        with self._lock:
            for s in self._open.values():
                s.dur_us = (end - s.t0_ns) // 1000
            self._open.clear()
            self.dur_us = (end - self._t0_ns) // 1000
            return self.profile_locked()

    def profile(self) -> dict:
        with self._lock:
            return self.profile_locked()

    def profile_locked(self) -> dict:
        return {
            "queryId": self.query_id,
            "component": self.component,
            "tsUs": self.ts_us,
            "durUs": self.dur_us or
            (time.perf_counter_ns() - self._t0_ns) // 1000,
            "droppedSpans": self.dropped,
            "spans": [s.to_dict() for s in self._spans],
        }

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)


# ---------------------------------------------------------------------------
# thread-local activation + cross-thread propagation
# ---------------------------------------------------------------------------

_TLS = threading.local()


def mint_query_id() -> str:
    """A fresh query identity — minted at the client and propagated in
    the wire headers, so every process a query touches logs the same
    id."""
    return uuid.uuid4().hex[:16]


def active() -> bool:
    return getattr(_TLS, "trace", None) is not None


def current_trace() -> Optional[QueryTrace]:
    return getattr(_TLS, "trace", None)


def current_query_id() -> Optional[str]:
    tr = getattr(_TLS, "trace", None)
    return tr.query_id if tr is not None else None


class _Noop:
    """Shared reusable no-op context manager: the whole cost of a span
    site with tracing off is one thread-local read + this return."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NOOP = _Noop()


class _SpanCm:
    __slots__ = ("_trace", "_span", "_name", "_kind", "_attrs")

    def __init__(self, trace: QueryTrace, name: str, kind: str,
                 attrs: Dict[str, Any]):
        self._trace = trace
        self._name = name
        self._kind = kind
        self._attrs = attrs
        self._span = None

    def __enter__(self):
        stack = getattr(_TLS, "stack", None)
        parent = stack[-1] if stack else None
        s = self._trace.open_span(self._name, self._kind, parent,
                                  self._attrs)
        self._span = s
        if s is not None:
            if stack is None:
                stack = _TLS.stack = []
            stack.append(s.span_id)
        return s

    def __exit__(self, *exc):
        s = self._span
        if s is not None:
            stack = getattr(_TLS, "stack", None)
            if stack and stack[-1] == s.span_id:
                stack.pop()
            elif stack is not None:
                try:                        # out-of-order close (rare:
                    stack.remove(s.span_id)  # interleaved generators)
                except ValueError:
                    pass
            self._trace.close_span(s)
        return False


def span(name: str, kind: str = "span", **attrs):
    """Open a child span of the calling thread's current span. With no
    active trace this is a shared no-op — safe on every hot path."""
    tr = getattr(_TLS, "trace", None)
    if tr is None:
        return _NOOP
    return _SpanCm(tr, name, kind, attrs)


def capture() -> Optional[Tuple[QueryTrace, Optional[int]]]:
    """Snapshot (trace, current span id) for handoff to a pool thread;
    None with no active trace."""
    tr = getattr(_TLS, "trace", None)
    if tr is None:
        return None
    stack = getattr(_TLS, "stack", None)
    return (tr, stack[-1] if stack else None)


@contextmanager
def attached(token: Optional[Tuple[QueryTrace, Optional[int]]]):
    """Activate a captured trace context on THIS thread (writer pools,
    fetch pools, recompute runners) so their spans land in the right
    tree under the right parent. No-op for a None token."""
    if token is None:
        yield
        return
    prev_tr = getattr(_TLS, "trace", None)
    prev_stack = getattr(_TLS, "stack", None)
    _TLS.trace = token[0]
    _TLS.stack = [token[1]] if token[1] is not None else []
    try:
        yield
    finally:
        _TLS.trace = prev_tr
        _TLS.stack = prev_stack


def call_attached(token, fn: Callable, *args, **kwargs):
    """Run ``fn`` under ``attached(token)`` — the pool.submit shim."""
    with attached(token):
        return fn(*args, **kwargs)


@contextmanager
def query_trace(query_id: Optional[str] = None,
                component: str = "engine",
                max_spans: int = 2048,
                recorder: Optional["FlightRecorder"] = None,
                sink_path: str = ""):
    """Open (and activate) a trace for one query on this thread; on
    exit, finish it and hand the profile to ``recorder`` and the JSONL
    ``sink_path`` when given. Yields the QueryTrace."""
    tr = QueryTrace(query_id or mint_query_id(), component=component,
                    max_spans=max_spans)
    prev_tr = getattr(_TLS, "trace", None)
    prev_stack = getattr(_TLS, "stack", None)
    _TLS.trace = tr
    _TLS.stack = []
    try:
        with span("query", kind="query"):
            yield tr
    finally:
        _TLS.trace = prev_tr
        _TLS.stack = prev_stack
        profile = tr.finish()
        _METRICS.note("profile_count")
        if recorder is not None:
            recorder.record(profile)
        if sink_path:
            sink_profile(sink_path, profile)


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


class FlightRecorder:
    """Bounded in-memory ring of the last N query profiles plus a
    slow-query log (queries over ``slow_query_ms``). The plan server and
    the router each own one; the process singleton serves in-process
    sessions and tools. ``stats()`` is the ``serving_stats()`` trace
    block."""

    def __init__(self, capacity: int = 128, slow_query_ms: int = 1000):
        self._lock = threading.Lock()
        self.capacity = max(1, int(capacity))
        self.slow_query_ms = int(slow_query_ms)
        self._ring: "deque[dict]" = deque(maxlen=self.capacity)
        self._slow: "deque[dict]" = deque(maxlen=self.capacity)
        self.recorded = 0
        self.slow_queries = 0
        self.dropped_spans = 0

    def record(self, profile: dict) -> None:
        with self._lock:
            self._ring.append(profile)
            self.recorded += 1
            self.dropped_spans += int(profile.get("droppedSpans", 0))
            if self.slow_query_ms > 0 and \
                    profile.get("durUs", 0) >= self.slow_query_ms * 1000:
                self._slow.append(profile)
                self.slow_queries += 1
                _METRICS.note("slow_query_count")

    def profiles(self, query_id: Optional[str] = None,
                 last: int = 0) -> List[dict]:
        """Profiles for one query id, or the most recent ``last`` (0 =
        all) in arrival order."""
        with self._lock:
            if query_id is not None:
                return [p for p in self._ring
                        if p.get("queryId") == query_id]
            out = list(self._ring)
        return out[-last:] if last > 0 else out

    def slow(self) -> List[dict]:
        with self._lock:
            return list(self._slow)

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._ring),
                    "capacity": self.capacity,
                    "recorded": self.recorded,
                    "slowQueries": self.slow_queries,
                    "slowQueryMs": self.slow_query_ms,
                    "droppedSpans": self.dropped_spans}

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._slow.clear()


_RECORDER: Optional[FlightRecorder] = None
_SINGLETON_LOCK = threading.Lock()


def flight_recorder() -> FlightRecorder:
    """The process-wide recorder (in-process sessions and tools record
    here; a PlanServer/Router owns its own instance)."""
    global _RECORDER
    with _SINGLETON_LOCK:
        if _RECORDER is None:
            _RECORDER = FlightRecorder()
        return _RECORDER


# ---------------------------------------------------------------------------
# JSONL sink
# ---------------------------------------------------------------------------

_SINK_LOCK = threading.Lock()


def sink_profile(path: str, profile: dict) -> None:
    """Append one profile as a JSON line (``trace.sink.path``). Sink
    failures never fail the query — tracing is observability, not the
    data path."""
    try:
        line = json.dumps(profile, separators=(",", ":"),
                          default=str) + "\n"
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with _SINK_LOCK:
            with open(path, "a", encoding="utf-8") as f:
                f.write(line)
    except OSError:  # robust-ok: best-effort sink, documented contract
        pass


# ---------------------------------------------------------------------------
# observed-cost store (the AQE feed, next to the PR-10 planning cache)
# ---------------------------------------------------------------------------


class ObservedCostStore:
    """Per-(shape-fingerprint, operator) EWMAs of observed wall time,
    rows, and bytes — recorded at collect close from the exec metric
    roll-up, so the CBO's speedup scores (ROADMAP item 3) can consult
    measured reality instead of its static model. LRU-bounded by
    fingerprint; an entry's ``count`` says how many collects fed it."""

    def __init__(self, max_fingerprints: int = 1024, alpha: float = 0.2):
        self._lock = threading.Lock()
        self.max_fingerprints = max(1, int(max_fingerprints))
        self.alpha = float(alpha)
        #: fp -> {op: {"wallNs","rows","bytes","count"}}
        self._fps: "OrderedDict[str, Dict[str, dict]]" = OrderedDict()

    def observe(self, fingerprint: str, op: str, wall_ns: int,
                rows: int = 0, nbytes: int = 0,
                alpha: Optional[float] = None) -> None:
        a = self.alpha if alpha is None else float(alpha)
        with self._lock:
            ops = self._fps.get(fingerprint)
            if ops is None:
                ops = self._fps[fingerprint] = {}
            self._fps.move_to_end(fingerprint)
            e = ops.get(op)
            if e is None:
                ops[op] = {"wallNs": float(wall_ns), "rows": float(rows),
                           "bytes": float(nbytes), "count": 1}
            else:
                e["wallNs"] += a * (wall_ns - e["wallNs"])
                e["rows"] += a * (rows - e["rows"])
                e["bytes"] += a * (nbytes - e["bytes"])
                e["count"] += 1
            while len(self._fps) > self.max_fingerprints:
                self._fps.popitem(last=False)
        _METRICS.note("cost_observation_count")

    def get(self, fingerprint: str) -> Dict[str, dict]:
        """{op: {"wallNs","rows","bytes","count"}} — empty when this
        fingerprint was never observed."""
        with self._lock:
            ops = self._fps.get(fingerprint)
            return {op: dict(e) for op, e in ops.items()} if ops else {}

    def fingerprints(self) -> List[str]:
        with self._lock:
            return list(self._fps)

    def snapshot(self) -> Dict[str, Dict[str, dict]]:
        with self._lock:
            return {fp: {op: dict(e) for op, e in ops.items()}
                    for fp, ops in self._fps.items()}

    def merge_snapshot(self, snap: Dict[str, Dict[str, dict]]) -> int:
        """Fold another store's snapshot into this one — the fleet
        cost-sharing op (router sync / costs_load wire op). Per (fp,
        op), the entry with the HIGHER observation count wins (same
        rule the router's trace-op merge applies): a better-measured
        EWMA beats a fresher-but-thinner one, and re-merging the same
        snapshot is idempotent. Returns entries adopted."""
        adopted = 0
        with self._lock:
            for fp, ops in snap.items():
                if not isinstance(ops, dict):
                    continue
                mine = self._fps.get(fp)
                if mine is None:
                    mine = self._fps[fp] = {}
                self._fps.move_to_end(fp)
                for op, e in ops.items():
                    try:
                        entry = {"wallNs": float(e["wallNs"]),
                                 "rows": float(e.get("rows", 0)),
                                 "bytes": float(e.get("bytes", 0)),
                                 "count": int(e["count"])}
                    except (KeyError, TypeError, ValueError):
                        continue     # malformed peer entry: skip, not fail
                    cur = mine.get(op)
                    if cur is None or entry["count"] > cur["count"]:
                        mine[op] = entry
                        adopted += 1
            while len(self._fps) > self.max_fingerprints:
                self._fps.popitem(last=False)
        return adopted

    def clear(self) -> None:
        with self._lock:
            self._fps.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._fps)


_COSTS: Optional[ObservedCostStore] = None


def observed_costs() -> ObservedCostStore:
    global _COSTS
    with _SINGLETON_LOCK:
        if _COSTS is None:
            _COSTS = ObservedCostStore()
        return _COSTS


def note_operator_costs(fingerprint: Optional[str], plan,
                        alpha: Optional[float] = None) -> None:
    """Fold one executed plan's per-operator metrics into the store:
    wall from ``opTime`` (the NS_TIMING convention: time inside the
    operator's iterator), rows from ``numOutputRows``, bytes from any
    declared ``*Bytes`` metric the exec emitted. The walk includes
    ``child_execs`` refs (exchange inputs, CPU-fallback islands) that
    ``collect_metrics``'s plain-children walk misses — a CPU-topped
    plan's measured host costs are exactly the comparison point an
    offload-decision CBO needs. No fingerprint (plan cache off /
    uncacheable) → nothing to key on, skip."""
    if fingerprint is None or plan is None:
        return
    agg: Dict[str, Dict[str, int]] = {}
    stack, seen = [plan], set()
    while stack:
        node = stack.pop()
        if id(node) in seen:
            continue
        seen.add(id(node))
        stack.extend(getattr(node, "children", ()) or ())
        stack.extend(getattr(node, "child_execs", ()) or ())
        mdict = getattr(node, "metrics", None)
        if not isinstance(mdict, dict):
            continue
        e = agg.setdefault(getattr(node, "name", type(node).__name__),
                           {"wallNs": 0, "rows": 0, "bytes": 0})
        for mname, m in mdict.items():
            total = getattr(m, "total", None)
            if total is None:
                continue
            v = int(total())
            if mname == "opTime":
                e["wallNs"] += v
            elif mname == "numOutputRows":
                e["rows"] += v
            elif mname.endswith("Bytes") or mname.endswith("bytes"):
                e["bytes"] += v
    store = observed_costs()
    for op, e in agg.items():
        if e["wallNs"] or e["rows"] or e["bytes"]:
            store.observe(fingerprint, op, e["wallNs"], e["rows"],
                          e["bytes"], alpha=alpha)
