"""Out-of-core sort: spilled sorted runs + bounded chunked merge.

Reference: GpuSortExec.scala:246 GpuOutOfCoreSortIterator — sort each input
batch, spill the runs, then merge with a priority queue of spilled chunks
so device memory stays bounded. Same algorithm here with device-friendly
primitives: the "priority queue" becomes a pairwise CHUNKED MERGE TREE —
two sorted runs merge chunk-at-a-time (concat 2 chunks → one lax.sort →
emit only rows ≤ the smaller of the two chunk maxima, which are provably
globally placed), so peak device memory per merge is 4 chunks regardless
of run size. log2(runs) passes; every intermediate run lives in the spill
catalog.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..batch import ColumnarBatch, Schema, bucket_capacity
from ..memory import (BufferCatalog, SpillableBatch, acquire_with_retry,
                      register_with_retry)
from .common import compact, concat_batches, slice_batch, sort_operands
from .sort import SortOrder, sort_batch


class _Run:
    """A sorted run stored as spillable fixed-size chunks."""

    def __init__(self, catalog: BufferCatalog, schema: Schema):
        self.catalog = catalog
        self.schema = schema
        self.chunks: List[SpillableBatch] = []

    def append(self, batch: ColumnarBatch) -> None:
        # register() leaves the handle unpinned (spillable) already; the
        # registration reserve runs under the OOM retry loop
        self.chunks.append(register_with_retry(
            batch, self.schema, catalog=self.catalog, name="ooc_sort.run"))

    def close(self) -> None:
        for c in self.chunks:
            c.close()
        self.chunks = []


class OutOfCoreSorter:
    """Merges any number of rows through a bounded device footprint."""

    def __init__(self, orders: Sequence[SortOrder], schema: Schema,
                 catalog: BufferCatalog, chunk_rows: int = 1 << 16):
        self.orders = orders
        self.schema = schema
        self.catalog = catalog
        self.chunk_rows = chunk_rows
        self._sort_jit = jax.jit(lambda b: sort_batch(b, self.orders))
        self._split_jit = jax.jit(self._split_kernel, static_argnums=(2,))
        self._slice_jit = jax.jit(slice_batch, static_argnums=(3,))

    # ------------------------------------------------------------------

    def _key_rank_last(self, batch: ColumnarBatch):
        """uint operands of the LAST live row (a chunk's max key)."""
        # evaluate order keys; rows are already sorted, take row num_rows-1
        last = jnp.maximum(batch.num_rows - 1, 0)
        cols = [o.child.eval(batch) for o in self.orders]
        ops = sort_operands(cols, [o.descending for o in self.orders],
                            [o.effective_nulls_first for o in self.orders],
                            batch.row_mask())[1:]   # drop liveness operand
        return [op[last] for op in ops]

    def _split_kernel(self, merged: ColumnarBatch, bound_words, cap: int):
        """Emit rows whose key ≤ bound (they are globally placed); keep the
        rest. Returns (emit_batch, keep_batch)."""
        cols = [o.child.eval(merged) for o in self.orders]
        ops = sort_operands(cols, [o.descending for o in self.orders],
                            [o.effective_nulls_first for o in self.orders],
                            merged.row_mask())[1:]
        le = jnp.zeros(merged.capacity, bool)
        gt = jnp.zeros(merged.capacity, bool)
        decided = jnp.zeros(merged.capacity, bool)
        for op, bw in zip(ops, bound_words):
            gt = gt | (~decided & (op > bw))
            decided = decided | (op != bw)
        le = ~gt
        live = merged.row_mask()
        emit = compact(merged, le & live)
        keep = compact(merged, ~le & live)
        return emit, keep

    # ------------------------------------------------------------------

    def _append_chunked(self, run: _Run, batch: ColumnarBatch) -> None:
        """Re-chunk to chunk_rows so merge working sets stay bounded at
        every tree level (otherwise output chunks double per pass)."""
        cap = bucket_capacity(self.chunk_rows)
        if batch.capacity <= cap:
            run.append(batch)
            return
        n = int(batch.num_rows)
        off = 0
        while off < max(n, 1):
            piece = self._slice_jit(batch, jnp.int32(off),
                                    jnp.int32(cap), cap)
            if int(piece.num_rows) > 0 or n == 0:
                run.append(piece)
            off += cap
            if n == 0:
                break

    def make_run(self, batches: Iterator[ColumnarBatch]) -> List[_Run]:
        """Phase 1: per-batch device sort, spill each sorted run."""
        runs: List[_Run] = []
        for b in batches:
            run = _Run(self.catalog, self.schema)
            self._append_chunked(run, self._sort_jit(b))
            runs.append(run)
        return runs

    def merge_two(self, a: _Run, b: _Run) -> _Run:
        """Chunked 2-way merge with bounded device residency."""
        out = _Run(self.catalog, self.schema)
        ai = bi = 0
        buf: Optional[ColumnarBatch] = None   # carried unsafe remainder
        while ai < len(a.chunks) or bi < len(b.chunks):
            pieces = [buf] if buf is not None else []
            bounds = []
            if ai < len(a.chunks):
                ca = acquire_with_retry(a.chunks[ai], name="ooc_sort.merge")
                a.chunks[ai].done_with()
                ai += 1
                pieces.append(ca)
                bounds.append((self._key_rank_last(ca), ai >= len(a.chunks)))
            if bi < len(b.chunks):
                cb = acquire_with_retry(b.chunks[bi], name="ooc_sort.merge")
                b.chunks[bi].done_with()
                bi += 1
                pieces.append(cb)
                bounds.append((self._key_rank_last(cb), bi >= len(b.chunks)))
            cap = bucket_capacity(sum(p.capacity for p in pieces))
            merged = self._sort_jit(concat_batches(pieces, cap)) \
                if len(pieces) > 1 else self._sort_jit(pieces[0])
            a_done = ai >= len(a.chunks)
            b_done = bi >= len(b.chunks)
            if a_done and b_done:
                self._append_chunked(out, merged)
                buf = None
                break
            # safe bound: the smaller chunk-max among runs that still have
            # unloaded data — rows ≤ it cannot be displaced later
            exhausted_sides = []
            live_bounds = []
            if not a_done or not b_done:
                # bound of the run we just loaded from decides safety; use
                # the minimum of loaded-chunk maxima of NON-exhausted runs
                for words, exhausted in bounds:
                    if not exhausted:
                        live_bounds.append(words)
            if not live_bounds:
                self._append_chunked(out, merged)
                buf = None
                continue
            bound = live_bounds[0]
            for w in live_bounds[1:]:
                bound = _lex_min(bound, w)
            emit, keep = self._split_jit(merged, bound, merged.capacity)
            if int(emit.num_rows) > 0:
                self._append_chunked(out, emit)
            buf = keep if int(keep.num_rows) > 0 else None
        if buf is not None and int(buf.num_rows) > 0:
            self._append_chunked(out, buf)
        a.close()
        b.close()
        return out

    def sort(self, batches: Iterator[ColumnarBatch]
             ) -> Iterator[ColumnarBatch]:
        runs = self.make_run(batches)
        if not runs:
            return
        while len(runs) > 1:
            nxt: List[_Run] = []
            for i in range(0, len(runs) - 1, 2):
                nxt.append(self.merge_two(runs[i], runs[i + 1]))
            if len(runs) % 2:
                nxt.append(runs[-1])
            runs = nxt
        final = runs[0]
        for sb in final.chunks:
            yield acquire_with_retry(sb, name="ooc_sort.emit")
            sb.done_with()
        final.close()


def _lex_min(a, b):
    """Lexicographic min of two key-word tuples (traced)."""
    out = []
    a_lt = jnp.zeros((), bool)
    decided = jnp.zeros((), bool)
    for x, y in zip(a, b):
        a_lt = a_lt | (~decided & (x < y))
        decided = decided | (x != y)
    for x, y in zip(a, b):
        out.append(jnp.where(a_lt, x, y))
    return out
