"""Batch coalescing.

Reference: sql-plugin/.../GpuCoalesceBatches.scala (GpuCoalesceBatches:656,
AbstractGpuCoalesceIterator:237, CoalesceGoal hierarchy :156-228 —
TargetSize / RequireSingleBatch). Small batches starve the MXU/VPU exactly
the way they starve a GPU, so operators declare a goal and the planner
inserts this exec to meet it. Concatenation is the scatter kernel in
exec/common (cudf Table.concatenate analogue).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator, List, Optional

import jax.numpy as jnp

from ..batch import ColumnarBatch, Schema, bucket_capacity
from .base import Exec, UnaryExec
from .common import concat_batches


@dataclass(frozen=True)
class CoalesceGoal:
    pass


@dataclass(frozen=True)
class TargetSize(CoalesceGoal):
    """Accumulate up to this many bytes per output batch (reference:
    TargetSize(spark.rapids.sql.batchSizeBytes))."""

    bytes: int = 512 << 20


@dataclass(frozen=True)
class RequireSingleBatch(CoalesceGoal):
    """The consumer needs all rows in one batch (global sort, build side of
    a broadcast join…)."""


class CoalesceBatchesExec(UnaryExec):
    def __init__(self, child: Exec, goal: CoalesceGoal = TargetSize(),
                 max_rows: int = 1 << 22):
        super().__init__(child)
        self.goal = goal
        self.max_rows = max_rows
        self.metrics["numInputBatches"] = type(self.metrics["opTime"])(
            "numInputBatches")

    @property
    def output_schema(self) -> Schema:
        return self.child.output_schema

    def _flush(self, pending: List[ColumnarBatch]) -> ColumnarBatch:
        if len(pending) == 1:
            return pending[0]
        cap = bucket_capacity(sum(b.capacity for b in pending))
        # eager boundary: unify per-batch string dictionaries (device
        # code-remap) so the coalesce keeps the encoded form instead of
        # decoding to padded bytes at the first concat
        from ..dictenc import unify_dict_batches
        return concat_batches(unify_dict_batches(pending), cap)

    @property
    def produces_single_batch(self) -> bool:
        return isinstance(self.goal, RequireSingleBatch) \
            or self.child.produces_single_batch

    def do_execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        pending: List[ColumnarBatch] = []
        pending_bytes = 0
        target = self.goal.bytes if isinstance(self.goal, TargetSize) else None
        for batch in self.child.execute_partition(p):
            self.metrics["numInputBatches"].add(1)
            b = batch.size_bytes()
            # RequireSingleBatch (target is None) never flushes mid-stream:
            # the whole partition concatenates into one output batch
            if target is not None and pending and (
                    pending_bytes + b > target
                    or sum(p.capacity for p in pending) + batch.capacity
                    > self.max_rows):
                yield self._flush(pending)
                pending, pending_bytes = [], 0
            pending.append(batch)
            pending_bytes += b
        if pending:
            yield self._flush(pending)


class CoalesceGoalError(RuntimeError):
    """A declared coalesce goal is not met by the converted plan."""


def verify_coalesce_goals(plan: Exec) -> None:
    """Planner-side verification (the 'verify' half of the contract): every
    child position whose parent declares RequireSingleBatch must be served
    by a single-batch producer (a RequireSingleBatch coalesce, or an exec
    that guarantees one batch per partition)."""
    for i, c in enumerate(plan.children):
        goal = plan.coalesce_goal_for_child(i)
        if isinstance(goal, RequireSingleBatch) and \
                not c.produces_single_batch:
            raise CoalesceGoalError(
                f"{plan.name} child {i} declares RequireSingleBatch but "
                f"{c.name} may emit multiple batches")
        verify_coalesce_goals(c)
    for extra in getattr(plan, "child_execs", []):
        verify_coalesce_goals(extra)
