"""Hash aggregate — sort-based segmented reduction.

Reference: sql-plugin/.../aggregate.scala (GpuHashAggregateExec:1372,
GpuHashAggregateIterator:182): per-batch cudf groupBy, then iterative
concat+re-aggregate of partial results, with a sort-based fallback when
merged results exceed the batch target.

TPU-native re-design: cudf's hash groupby is replaced by ONE device sort by
the grouping keys followed by segment reductions with a static segment count
(the capacity bucket). Sorting is XLA's bread and butter; every aggregate in
the batch then runs as fused `segment_sum/min/max` over the same sorted
layout — a single compiled computation per capacity bucket, versus one JNI
kernel launch per aggregation in the reference.

Modes mirror Spark's: Partial (update → buffers), PartialMerge/Final (merge
buffers), Complete (update + evaluate). Layout convention between stages:
``[group keys..., buffer columns...]`` in declaration order.
"""

from __future__ import annotations

import enum
from typing import Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .. import types as T
from ..batch import ColumnarBatch, DeviceColumn, Field, Schema, bucket_capacity
from ..expressions.aggregates import AggregateFunction
from ..expressions.base import Alias, EvalContext, Expression
from .base import Exec, UnaryExec
from .basic import bind_all, output_name
from .common import adjacent_equal, adjacent_equal_ops, compaction_indices, \
    concat_batches, gather_column, sort_operands

# dtypes whose device payload is a flat 1-D array; such columns can ride a
# key sort as extra payload operands (docs/perf_r3.md: payload carry is
# ~free, versus 26–65 ms per post-hoc 4M-row gather)
_FLAT_KINDS = frozenset({
    T.TypeKind.INT8, T.TypeKind.INT16, T.TypeKind.INT32, T.TypeKind.INT64,
    T.TypeKind.FLOAT32, T.TypeKind.FLOAT64, T.TypeKind.BOOLEAN,
    T.TypeKind.DATE, T.TypeKind.TIMESTAMP,
})


def _is_flat(t: T.SqlType) -> bool:
    return t.kind in _FLAT_KINDS or (t.kind is T.TypeKind.DECIMAL
                                     and t.precision <= 18)


def _pad_column(c: DeviceColumn, cap: int) -> DeviceColumn:
    """Zero/False-pad a [L]-capacity column up to [cap] rows. Dictionary
    lanes are CARD-leading and ride along unpadded — every layout tier
    must produce the same pytree structure for the lax.cond dispatch."""
    pad = cap - c.capacity
    if pad == 0:
        return c

    def pz(a):
        if a is None:
            return None
        return jnp.pad(a, [(0, pad)] + [(0, 0)] * (a.ndim - 1))

    return DeviceColumn(pz(c.data), pz(c.validity), pz(c.lengths), c.dtype,
                        pz(c.data2), c.dict_data, c.dict_lengths)


class AggregateMode(enum.Enum):
    PARTIAL = "Partial"
    PARTIAL_MERGE = "PartialMerge"
    FINAL = "Final"
    COMPLETE = "Complete"


def _unalias(e: Expression) -> Tuple[AggregateFunction, str]:
    if isinstance(e, Alias):
        assert isinstance(e.child, AggregateFunction)
        return e.child, e.name
    assert isinstance(e, AggregateFunction), f"not an aggregate: {e!r}"
    return e, type(e).__name__.lower()


class HashAggregateExec(UnaryExec):
    def coalesce_goal_for_child(self, i):
        from .coalesce import TargetSize
        return TargetSize()

    def __init__(self, group_exprs: Sequence[Expression],
                 agg_exprs: Sequence[Expression], child: Exec,
                 mode: AggregateMode = AggregateMode.COMPLETE,
                 ctx: Optional[EvalContext] = None,
                 max_result_rows: int = 1 << 22,
                 small_groups_bucket: int = 1 << 12,
                 layout_tiers: Optional[Sequence[int]] = None):
        self.layout_tiers = layout_tiers
        super().__init__(child, ctx)
        self.mode = mode
        self.max_result_rows = max_result_rows
        named = [_unalias(e) for e in agg_exprs]
        self.agg_names = [n for _, n in named]

        child_schema = child.output_schema
        if mode in (AggregateMode.PARTIAL, AggregateMode.COMPLETE):
            self.group_exprs = bind_all(group_exprs, child_schema)
            self.aggs = [a.bind(child_schema) for a, _ in named]
            self.key_fields = [
                Field(output_name(e, i), e.dtype, e.nullable)
                for i, e in enumerate(self.group_exprs)]
        else:
            # Buffer-layout input: keys first, then buffers in order. The
            # agg functions must be BOUND against the pre-aggregation schema
            # (Spark's planner shares the bound AggregateExpressions between
            # the Partial and Final stages); if the caller passed unresolved
            # ones, recover the bound instances from the partial stage below.
            self.aggs = [a for a, _ in named]
            if any(not c.resolved for a in self.aggs for c in a.children):
                src: Optional[Exec] = child
                while src is not None and \
                        not isinstance(src, HashAggregateExec):
                    src = src.children[0] if len(src.children) == 1 else None
                if src is None:
                    raise ValueError(
                        "Final-mode aggregate functions must be bound (or "
                        "the child chain must contain the Partial stage)")
                self.aggs = list(src.aggs)
            # keys are positional in the buffer layout — reference them by
            # ordinal, never re-evaluate the original grouping expressions
            # (they may be computed, e.g. group_by(year(col("d"))))
            nk = len(group_exprs)
            from ..expressions.base import BoundReference
            self.group_exprs = [
                BoundReference(i, f.dtype, f.nullable, f.name)
                for i, f in enumerate(child_schema.fields[:nk])]
            self.key_fields = [Field(f.name, f.dtype, f.nullable)
                               for f in child_schema.fields[:nk]]

        # buffer fields (inter-stage schema)
        self.buffer_fields: List[Field] = []
        for (agg, name) in zip(self.aggs, self.agg_names):
            for j, (bt, bn) in enumerate(zip(agg.buffer_types(),
                                             agg.buffer_nullable())):
                self.buffer_fields.append(Field(f"{name}#{j}", bt, bn))

        if mode in (AggregateMode.PARTIAL, AggregateMode.PARTIAL_MERGE):
            self._schema = Schema(self.key_fields + self.buffer_fields)
        else:
            self._schema = Schema(self.key_fields + [
                Field(n, a.dtype, a.nullable)
                for a, n in zip(self.aggs, self.agg_names)])

        self.sort_sensitive = [
            a for a in self.aggs
            if getattr(a, "requires_sorted_input", False)]
        if len(self.sort_sensitive) > 1:
            raise ValueError(
                "one sort-sensitive aggregate (percentile) per exec; the "
                "planner must split multi-percentile projections")
        if self.sort_sensitive and mode is not AggregateMode.COMPLETE:
            raise ValueError(
                f"{type(self.sort_sensitive[0]).__name__} supports "
                f"COMPLETE mode only (not decomposable)")

        # ---- round-3 fast path eligibility (docs/perf_r3.md) ----------
        # values ride the key sort as payload; group-slot layout shrinks
        # to `small_groups_bucket` when the observed group count allows
        self.small_groups_bucket = small_groups_bucket
        self._upd_value_exprs: List[Expression] = []
        self._upd_per_agg: List[List[int]] = []
        index_of = {}
        for agg in self.aggs:
            idxs = []
            for c in agg.children:
                k = self._expr_key(c)
                if k not in index_of:
                    index_of[k] = len(self._upd_value_exprs)
                    self._upd_value_exprs.append(c)
                idxs.append(index_of[k])
            self._upd_per_agg.append(idxs)
        have_keys = len(self.group_exprs) > 0
        self._fast_update = (
            mode in (AggregateMode.PARTIAL, AggregateMode.COMPLETE)
            and have_keys and not self.sort_sensitive
            and all(_is_flat(c.dtype) for a in self.aggs for c in a.children)
            and all(_is_flat(bt) for a in self.aggs for bt in a.buffer_types()))
        self._fast_merge = (
            mode in (AggregateMode.PARTIAL_MERGE, AggregateMode.FINAL)
            and have_keys
            and all(_is_flat(f.dtype) for f in self.buffer_fields))

        self._update_jit = jax.jit(self._update_kernel)
        self._merge_jit = jax.jit(lambda b: self._merge_kernel(b, final=False))
        self._final_jit = jax.jit(lambda b: self._merge_kernel(b, final=True))
        self._eval_buffers_jit = jax.jit(self._eval_buffers_kernel)

    @staticmethod
    def _expr_key(e: Expression):
        """Identity for payload dedup: two aggregates over the same bound
        column share one carried payload lane."""
        from ..expressions.base import BoundReference
        if isinstance(e, BoundReference):
            return ("ref", e.ordinal)
        return id(e)

    @property
    def output_schema(self) -> Schema:
        return self._schema

    # ------------------------------------------------------------------
    # Shared segment machinery
    # ------------------------------------------------------------------

    def _segments(self, key_cols: List[DeviceColumn], live, cap: int,
                  value_cols: List[DeviceColumn] = ()):
        """Sort rows by key (+ optional value columns for sort-sensitive
        aggregates); return (perm, seg ids, new_group mask, count,
        sorted-live mask, live row count). ``live`` may exclude rows a
        fused upstream filter dropped — they sort last, exactly like
        padding rows, so no separate compaction pass is needed."""
        n_live = jnp.sum(live.astype(jnp.int32))
        if not key_cols and not value_cols:
            seg = jnp.where(live, 0, cap)
            new_group = jnp.arange(cap, dtype=jnp.int32) == 0
            return None, seg, new_group, jnp.asarray(1, jnp.int32), live, \
                n_live
        all_cols = list(key_cols) + list(value_cols)
        from .common import may_skip_null_lane
        nullable = [not may_skip_null_lane(e)
                    for e in self.group_exprs][:len(key_cols)] + \
            [True] * len(value_cols)
        if len(nullable) != len(all_cols):
            nullable = [True] * len(all_cols)
        ops = sort_operands(all_cols, [False] * len(all_cols),
                            [True] * len(all_cols), live, nullable)
        iota = jnp.arange(cap, dtype=jnp.int32)
        perm = jax.lax.sort(ops + [iota], num_keys=len(ops) + 1)[-1]
        sorted_keys = [gather_column(c, perm) for c in key_cols]
        sorted_live = jnp.arange(cap, dtype=jnp.int32) < n_live
        if key_cols:
            eq = adjacent_equal(sorted_keys)
        else:
            # value-only sort (global percentile): one segment
            eq = jnp.concatenate([jnp.zeros(1, bool),
                                  jnp.ones(cap - 1, bool)])
        new_group = sorted_live & ~eq
        group_id = jnp.cumsum(new_group.astype(jnp.int32)) - 1
        seg = jnp.where(sorted_live, group_id, cap)
        count = jnp.sum(new_group.astype(jnp.int32))
        return perm, seg, new_group, count, sorted_live, n_live

    def _segment_layout(self, seg, count, num_rows, cap: int):
        """(starts, ends) row-index bounds per group slot, feeding the
        aggregates' segmented-scan reductions (segment_bounds context in
        expressions/aggregates.py) AND first-key placement. One native
        int32 scatter (`segment_min` of iota) — the flag-sort alternative
        measured ~3x slower. Dead slots get ends < starts so their
        reductions resolve to the identity."""
        iota = jnp.arange(cap, dtype=jnp.int32)
        starts = jax.ops.segment_min(iota, seg, num_segments=cap,
                                     indices_are_sorted=True)
        nxt = jnp.concatenate([starts[1:], jnp.zeros(1, jnp.int32)])
        last = jnp.asarray(num_rows, jnp.int32) - 1
        ends = jnp.where(iota < count - 1, nxt - 1, last)
        starts = jnp.where(iota < count, starts, jnp.int32(1))
        ends = jnp.where(iota < count, ends, jnp.int32(0))
        return starts, ends

    def _group_first_keys(self, sorted_keys: List[DeviceColumn], perm,
                          count, cap: int) -> List[DeviceColumn]:
        """Place each segment's first-row key at its group slot — a gather
        through the slot order (segments ascend, so the g-th first-row IS
        group g's key; TPU scatters are ~40x slower than gathers)."""
        iota = jnp.arange(cap, dtype=jnp.int32)
        slot_live = iota < count
        # gather_column: dict-aware (codes gather, dictionary rides along)
        # and struct-recursive, with slot_live folded into validity
        return [gather_column(c, perm, slot_live) for c in sorted_keys]

    # ------------------------------------------------------------------
    # Round-3 fast kernel (docs/perf_r3.md): ONE key sort carrying every
    # aggregate input as payload; cumsum-diff reductions over the sorted
    # layout; dual small/large group-slot layout behind a lax.cond so the
    # common small-group-count case pays G-sized per-group gathers
    # instead of capacity-sized ones.
    # ------------------------------------------------------------------

    def _fast_group_kernel(self, batch: ColumnarBatch, mask,
                           merge: bool, final: bool) -> ColumnarBatch:
        cap = batch.capacity
        in_live = batch.row_mask()
        if mask is not None:
            in_live = in_live & mask
        nk = len(self.key_fields)
        if merge:
            key_cols = list(batch.columns[:nk])
            flat_vals = list(batch.columns[nk:])
            per_agg, off = [], 0
            for agg in self.aggs:
                nb = len(agg.buffer_types())
                per_agg.append(list(range(off, off + nb)))
                off += nb
            nullable = [f.nullable for f in self.key_fields]
            val_nullable = [f.nullable for f in self.buffer_fields]
        else:
            # raw_eval: dict-encoded string keys group on CODES — one u32
            # sort lane instead of max_len/8+1 word lanes, same order and
            # same group boundaries (sorted-dictionary invariant)
            from ..expressions.base import raw_eval
            key_cols = [raw_eval(e, batch, self.ctx)
                        for e in self.group_exprs]
            flat_vals = [e.eval(batch, self.ctx)
                         for e in self._upd_value_exprs]
            per_agg = self._upd_per_agg
            from .common import may_skip_null_lane
            nullable = [not may_skip_null_lane(e) for e in self.group_exprs]
            val_nullable = [e.nullable for e in self._upd_value_exprs]

        key_ops = sort_operands(key_cols, [False] * nk, [True] * nk,
                                in_live, nullable)
        nko = len(key_ops)
        iota = jnp.arange(cap, dtype=jnp.int32)
        # provably non-null columns skip their validity payload lane; their
        # sorted views share ONE validity object (sorted_live), which also
        # dedups the per-aggregate non-null-count lanes downstream
        payload: List[jax.Array] = [iota]
        for c, nl in zip(flat_vals, val_nullable):
            payload.append(c.data.astype(jnp.uint8)
                           if c.data.dtype == jnp.bool_ else c.data)
            if nl:
                payload.append(c.validity.astype(jnp.uint8))
        out = jax.lax.sort(key_ops + payload, num_keys=nko)
        sorted_key_ops, sperm = out[:nko], out[nko]
        n_live = jnp.sum(in_live.astype(jnp.int32))
        sorted_live = iota < n_live
        svals: List[DeviceColumn] = []
        j = nko + 1
        for c, nl in zip(flat_vals, val_nullable):
            data = out[j]
            j += 1
            if c.data.dtype == jnp.bool_:
                data = data.astype(jnp.bool_)
            if nl:
                validity = out[j].astype(jnp.bool_)
                j += 1
            else:
                validity = sorted_live
            svals.append(DeviceColumn(data, validity, None, c.dtype))
        eq = adjacent_equal_ops(sorted_key_ops[1:])  # skip the dead lane
        new_group = sorted_live & ~eq
        gid = jnp.cumsum(new_group.astype(jnp.int32)) - 1
        count = jnp.sum(new_group.astype(jnp.int32))

        from ..expressions.aggregates import (FastLanes, LaneResults,
                                              segment_bounds)

        # planning pass: batched aggregates register lanes on the builder;
        # the rest fall back to generic update/merge under segment_bounds
        lanes = FastLanes(sorted_live)
        plans = []
        for agg, idxs in zip(self.aggs, per_agg):
            views = [svals[i] for i in idxs]
            fin = (agg.fast_merge(views, sorted_live, lanes) if merge
                   else agg.fast_update(views, sorted_live, lanes))
            plans.append((agg, views, fin))
        # branch-independent segment ids for the suffix-scan ladders
        seg0 = jnp.where(sorted_live, gid, -1)

        def emit(L: int):
            slot = jnp.arange(L, dtype=jnp.int32)
            live_slot = slot < count
            pos = jnp.where(new_group & (gid < L), gid, L)
            starts = jnp.zeros(L + 1, jnp.int32).at[pos].set(
                iota, mode="drop")[:L]
            nxt = jnp.concatenate([starts[1:], jnp.zeros(1, jnp.int32)])
            ends = jnp.where(slot < count - 1, nxt - 1, n_live - 1)
            starts_m = jnp.where(live_slot, starts, 1)
            ends_m = jnp.where(live_slot, ends, 0)
            first_idx = jnp.take(sperm, jnp.where(live_slot, starts, 0))
            from .common import gather_columns
            out_cols = gather_columns(key_cols, first_idx, live_slot)
            res = LaneResults(lanes, seg0, starts_m, live_slot)
            seg = jnp.where(sorted_live & (gid < L), gid, L)
            with segment_bounds(starts_m, ends_m):
                for agg, views, fin in plans:
                    if fin is not None:
                        bufs = fin(res)
                    else:
                        bufs = (agg.merge(views, seg, sorted_live, L)
                                if merge
                                else agg.update(views, seg, sorted_live, L))
                    if merge and final:
                        out_cols.append(agg.evaluate(bufs, live_slot))
                    else:
                        out_cols.extend(bufs)
            return tuple(_pad_column(c, cap) for c in out_cols)

        # capacity-tiered layout: per-group gathers scale with the layout
        # size, so pick the smallest tier the observed group count fits
        # (nested lax.cond — only the selected tier executes). Tier count
        # is a compile-time/runtime trade: every tier re-traces the whole
        # reduction pipeline. Since the round-4 blocked scans shrank the
        # per-tier HLO, a THIRD mid tier (cap/4) is affordable and cuts the
        # group-starts row-gather 5x for mid-cardinality batches
        # (tools/profile_round4.py: (4M,6) f64 gather 180 ms at L=4M vs
        # 33 ms at L=1M; 1M-key hash_agg 568 ms -> 228 ms).
        G = min(self.small_groups_bucket, cap)
        default = (G, cap >> 2, cap) if cap >> 2 > G else (G, cap)
        tiers = sorted({t for t in (self.layout_tiers or default)
                        if 0 < t <= cap} | {cap})

        def select(ts):
            if len(ts) == 1:
                return emit(ts[0])
            return jax.lax.cond(count <= ts[0],
                                lambda: emit(ts[0]), lambda: select(ts[1:]))

        return ColumnarBatch(select(tiers), count)

    # ------------------------------------------------------------------
    # Kernels
    # ------------------------------------------------------------------

    def _update_kernel(self, batch: ColumnarBatch,
                       mask=None) -> ColumnarBatch:
        """input rows -> buffer-layout batch (Partial). ``mask`` fuses an
        upstream filter into the aggregation: masked rows become dead
        rows of the sort, skipping the separate compaction kernel
        (reference analogue: AST-fused filters)."""
        if self._fast_update:
            return self._fast_group_kernel(batch, mask, merge=False,
                                           final=False)
        cap = batch.capacity
        in_live = batch.row_mask()
        if mask is not None:
            in_live = in_live & mask
        from ..expressions.base import raw_eval
        key_cols = [raw_eval(e, batch, self.ctx)
                    for e in self.group_exprs]
        input_cols = [[c.eval(batch, self.ctx) for c in agg.children]
                      for agg in self.aggs]
        value_sort = []
        if self.sort_sensitive:
            si = self.aggs.index(self.sort_sensitive[0])
            value_sort = list(input_cols[si])
        perm, seg, new_group, count, live, n_live = self._segments(
            key_cols, in_live, cap, value_sort)
        if perm is not None:
            key_cols = [gather_column(c, perm) for c in key_cols]
            input_cols = [[gather_column(c, perm) for c in cols]
                          for cols in input_cols]
        from ..expressions.aggregates import segment_bounds
        starts, ends = self._segment_layout(seg, count, n_live, cap)
        out_cols = self._group_first_keys(key_cols, starts, count, cap)
        if perm is None:
            # unsorted (keyless) segments are not contiguous under a
            # fused mask — the scan path needs runs, use scatters
            for agg, cols in zip(self.aggs, input_cols):
                out_cols.extend(agg.update(cols, seg, live, cap))
        else:
            with segment_bounds(starts, ends):
                for agg, cols in zip(self.aggs, input_cols):
                    out_cols.extend(agg.update(cols, seg, live, cap))
        group_live = jnp.arange(cap, dtype=jnp.int32) < count
        out_cols = [c.replace(validity=c.validity & group_live)
                    if i < len(key_cols) else c
                    for i, c in enumerate(out_cols)]
        return ColumnarBatch(tuple(out_cols), count)

    def _merge_kernel(self, batch: ColumnarBatch, final: bool) -> ColumnarBatch:
        """buffer-layout rows -> merged buffer rows (or final results)."""
        if self._fast_merge:
            return self._fast_group_kernel(batch, None, merge=True,
                                           final=final)
        cap = batch.capacity
        nk = len(self.key_fields)
        key_cols = [batch.columns[i] for i in range(nk)]
        perm, seg, new_group, count, live, n_live = self._segments(
            key_cols, batch.row_mask(), cap)
        if perm is not None:
            cols = [gather_column(c, perm) for c in batch.columns]
        else:
            cols = list(batch.columns)
        from ..expressions.aggregates import segment_bounds
        starts, ends = self._segment_layout(seg, count, n_live, cap)
        out_cols = self._group_first_keys(cols[:nk], starts, count, cap)
        group_live = jnp.arange(cap, dtype=jnp.int32) < count
        off = nk
        with segment_bounds(starts, ends):
            for agg in self.aggs:
                nb = len(agg.buffer_types())
                bufs = cols[off:off + nb]
                merged = agg.merge(bufs, seg, live, cap)
                if final:
                    out_cols.append(agg.evaluate(merged, group_live))
                else:
                    out_cols.extend(merged)
                off += nb
        out_cols = [c.replace(validity=c.validity & group_live)
                    if i < nk else c for i, c in enumerate(out_cols)]
        return ColumnarBatch(tuple(out_cols), count)

    def _eval_buffers_kernel(self, batch: ColumnarBatch) -> ColumnarBatch:
        """buffer-layout rows -> final results WITHOUT a merge pass (the
        sort-sensitive COMPLETE path: groups are already unique)."""
        cap = batch.capacity
        nk = len(self.key_fields)
        group_live = batch.row_mask()
        out_cols = list(batch.columns[:nk])
        off = nk
        for agg in self.aggs:
            nb = len(agg.buffer_types())
            bufs = list(batch.columns[off:off + nb])
            out_cols.append(agg.evaluate(bufs, group_live))
            off += nb
        return ColumnarBatch(tuple(out_cols), batch.num_rows)

    # ------------------------------------------------------------------
    # Iterator (reference: GpuHashAggregateIterator.aggregateInputBatches +
    # tryMergeAggregatedBatches)
    # ------------------------------------------------------------------

    def do_execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        # accumulated partials ride the spill catalog (reference:
        # LazySpillableColumnarBatch deque in GpuHashAggregateIterator);
        # registrations and the merge passes run under the OOM retry loop
        # (no split: re-ordering partial merges would change float
        # accumulation order — spill-and-retry keeps results bit-for-bit)
        from ..memory import (SpillableBatch, device_budget,
                              register_with_retry)
        cat = device_budget()
        buf_schema = Schema(self.key_fields + self.buffer_fields)
        spillables: List[SpillableBatch] = []
        if self.sort_sensitive:
            # non-decomposable aggregates: ONE update over the whole
            # partition's rows, then evaluate (no merge step exists)
            raw = list(self.child.execute_partition(p))
            if not raw:
                if not self.key_fields and p == 0:
                    from ..batch import empty_batch
                    seed = empty_batch(Schema(self.key_fields
                                              + self.buffer_fields))
                    yield self._eval_buffers_jit(self._update_jit(
                        empty_batch(self.child.output_schema)))
                return
            if len(raw) == 1:
                whole = raw[0]
            else:
                whole = concat_batches(
                    raw, bucket_capacity(sum(b.capacity for b in raw)))
            yield self._eval_buffers_jit(self._update_jit(whole))
            return
        for batch in self.child.execute_partition(p):
            if self.mode in (AggregateMode.PARTIAL, AggregateMode.COMPLETE):
                part = self._update_jit(batch)
            else:
                part = batch
            # registered handles start unpinned (spillable)
            spillables.append((register_with_retry(part, buf_schema,
                                                   catalog=cat,
                                                   name=self.name),
                               int(part.capacity)))

        finalize = self.mode in (AggregateMode.FINAL, AggregateMode.COMPLETE)
        if not spillables:
            if not self.key_fields and p == 0:
                # global aggregate over empty input still yields one row
                from ..batch import empty_batch
                seed = empty_batch(Schema(self.key_fields + self.buffer_fields))
                out = self._final_jit(seed) if finalize else self._merge_jit(seed)
                yield out
            return

        try:
            yield from self._merge_and_emit(spillables, finalize, cat,
                                            buf_schema)
        finally:
            for sb, _ in spillables:
                sb.close()

    def _merge_and_emit(self, entries, finalize, cat, buf_schema):
        """Merge spilled partials WITHOUT ever acquiring more than
        ``max_result_rows`` of buffered rows at once (reference:
        tryMergeAggregatedBatches under targetMergeBatchSize,
        aggregate.scala:86-125). Two phases:

        1. windowed concat+merge passes — shrinks fast when keys repeat
           across batches;
        2. if a pass stops shrinking (high-cardinality keys), sort-based
           out-of-core fallback: key-sort all partials through the spilled
           chunked merge tree, then stream chunks in global key order,
           merging each and emitting every group except the boundary one
           (carried into the next chunk)."""
        from ..memory import register_with_retry, with_retry_no_split

        def _acquire_group(grp):
            """Pin a group of partials transactionally: a mid-loop OOM
            unpins what this attempt already pinned, so the retry loop
            re-runs against a clean (fully spillable) state."""
            got = []
            try:
                for sb, _ in grp:
                    got.append(sb.get())  # retry-ok: _acquire_group runs only inside final_merge/window_merge bodies under with_retry_no_split
            except BaseException:
                for j in range(len(got)):
                    grp[j][0].done_with()
                raise
            return got

        window = self.max_result_rows
        while True:
            total = sum(c for _, c in entries)
            if len(entries) == 1 or total <= window:
                def final_merge():
                    batches = _acquire_group(entries)
                    merged = batches[0] if len(batches) == 1 else \
                        concat_batches(batches, bucket_capacity(total))
                    for sb, _ in entries:
                        sb.done_with()
                    return merged
                merged = with_retry_no_split(final_merge, catalog=cat,
                                             name=self.name)
                yield self._final_jit(merged) if finalize \
                    else self._merge_jit(merged)
                return
            # one windowed pre-merge pass
            new_entries, shrunk = [], 0
            i = 0
            while i < len(entries):
                grp, cap_sum = [], 0
                while i < len(entries) and (
                        not grp or cap_sum + entries[i][1] <= window):
                    grp.append(entries[i])
                    cap_sum += entries[i][1]
                    i += 1
                if len(grp) == 1:
                    new_entries.append(grp[0])
                    continue

                def window_merge(grp=grp, cap_sum=cap_sum):
                    batches = _acquire_group(grp)
                    merged = self._merge_jit(
                        concat_batches(batches, bucket_capacity(cap_sum)))
                    n = int(merged.num_rows)
                    out_cap = bucket_capacity(max(n, 1))
                    if out_cap < merged.capacity:
                        merged = self._slice_compact(merged, out_cap)
                    for sb, _ in grp:
                        sb.done_with()
                    return merged

                merged = with_retry_no_split(window_merge, catalog=cat,
                                             name=self.name)
                for sb, _ in grp:
                    sb.close()
                nsb = register_with_retry(merged, buf_schema, catalog=cat,
                                          name=self.name)
                new_entries.append((nsb, int(merged.capacity)))
                shrunk += cap_sum - int(merged.capacity)
            # mutate the caller's list so the finally-close sees live handles
            entries[:] = new_entries
            if shrunk * 10 < total:
                # high-cardinality: merging barely shrinks → sort-based OOC
                yield from self._ooc_sorted_merge(entries, finalize, cat,
                                                  buf_schema)
                return

    def _slice_compact(self, batch: ColumnarBatch, cap: int) -> ColumnarBatch:
        from .common import slice_batch
        return jax.jit(slice_batch, static_argnums=3)(
            batch, jnp.int32(0), jnp.int32(cap), cap)

    def _ooc_sorted_merge(self, entries, finalize, cat, buf_schema):
        """Sort-based OOC aggregation: global key order via the spilled
        chunked merge tree, then bounded per-chunk merges. Only the boundary
        group can span chunks, so it is carried forward and every other
        group is emitted as soon as its chunk is merged."""
        from ..batch import MIN_CAPACITY
        from ..expressions.base import BoundReference
        from .common import slice_batch
        from .ooc_sort import OutOfCoreSorter
        from .sort import SortOrder

        orders = [SortOrder(BoundReference(i, f.dtype, f.nullable, f.name))
                  for i, f in enumerate(self.key_fields)]
        chunk_rows = max(min(self.max_result_rows // 4, 1 << 16),
                         MIN_CAPACITY)
        sorter = OutOfCoreSorter(orders, buf_schema, cat,
                                 chunk_rows=chunk_rows)
        slice_jit = jax.jit(slice_batch, static_argnums=3)

        def batches():
            from ..memory import acquire_with_retry
            for sb, _ in entries:
                b = acquire_with_retry(sb, name=self.name)
                sb.done_with()
                yield b

        carry: Optional[ColumnarBatch] = None
        for chunk in sorter.sort(batches()):
            if carry is not None:
                cap = bucket_capacity(carry.capacity + chunk.capacity)
                chunk = concat_batches([carry, chunk], cap)
            merged = self._merge_jit(chunk)
            n = int(merged.num_rows)
            if n == 0:
                carry = None
                continue
            if n == 1:
                carry = slice_jit(merged, jnp.int32(0), jnp.int32(1),
                                  MIN_CAPACITY)
                continue
            emit = slice_jit(merged, jnp.int32(0), jnp.int32(n - 1),
                             bucket_capacity(n - 1))
            carry = slice_jit(merged, jnp.int32(n - 1), jnp.int32(1),
                              MIN_CAPACITY)
            yield self._eval_buffers_jit(emit) if finalize else emit
        if carry is not None:
            yield self._eval_buffers_jit(carry) if finalize else carry
