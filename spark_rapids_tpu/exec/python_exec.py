"""Arrow-exchange Python UDF execs.

Reference: SURVEY.md §2.11 / §3.5 — GpuArrowEvalPythonExec.scala:241
(device → Arrow IPC → python worker → Arrow → device), GpuMapInBatchExec,
GpuAggregateInPandasExec, gated by PythonWorkerSemaphore.scala:41. Here the
engine IS Python, so the "worker" is an in-process callable behind the same
Arrow columnar boundary (to_arrow/from_arrow is the exact exchange the
reference does over a socket), and the worker semaphore bounds concurrent
evaluation the same way.

Two shapes, mirroring the reference's exec family:
- ArrowEvalPythonExec: per-batch scalar pandas UDF — f(pd.Series...) ->
  pd.Series appended as new columns.
- MapInBatchExec: f(pd.DataFrame) -> pd.DataFrame with an arbitrary output
  schema (mapInPandas).
"""

from __future__ import annotations

from typing import Callable, Iterator, List, Optional, Sequence, Tuple

import pyarrow as pa

from ..batch import ColumnarBatch, Field, Schema, from_arrow, to_arrow
from ..memory.semaphore import TpuSemaphore
from .base import Exec, UnaryExec

# reference: PythonWorkerSemaphore bounds concurrent GPU-using workers
_python_semaphore = TpuSemaphore(4)


# ---- forked-worker adapters (module-level: must pickle to the daemon;
# reference: python/rapids/worker.py applies the UDF inside the fork) ----

def _scalar_udf_on_table(table: pa.Table, fn, input_cols, out_names):
    pdf = table.to_pandas()
    args = [pdf[c] for c in input_cols]
    result = fn(*args)
    if not isinstance(result, (list, tuple)):
        result = [result]
    for name, series in zip(out_names, result):
        pdf[name] = series
    return pa.Table.from_pandas(pdf, preserve_index=False)


def _map_udf_on_table(table: pa.Table, fn):
    return pa.Table.from_pandas(fn(table.to_pandas()),
                                preserve_index=False)


class ArrowEvalPythonExec(UnaryExec):
    """Append columns computed by a scalar pandas UDF."""

    def __init__(self, fn: Callable, input_cols: Sequence[str],
                 output_fields: Sequence[Field], child: Exec,
                 use_daemon: bool = True):
        super().__init__(child)
        self.fn = fn
        self.input_cols = list(input_cols)
        self.output_fields = list(output_fields)
        self.use_daemon = use_daemon
        self._schema = Schema(list(child.output_schema.fields)
                              + self.output_fields)

    @property
    def output_schema(self) -> Schema:
        return self._schema

    def do_execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        child_schema = self.child.output_schema
        from ..python_worker import worker_apply
        out_names = [f.name for f in self.output_fields]
        for batch in self.child.execute_partition(p):
            with _python_semaphore.task():
                table = to_arrow(batch, child_schema)     # D2H + Arrow
                # forked worker when the UDF pickles (process isolation —
                # a crashing UDF fails the query, not the executor);
                # closures downgrade to in-process
                out = worker_apply(_scalar_udf_on_table, table,
                                   (self.fn, self.input_cols, out_names),
                                   use_daemon=self.use_daemon,
                                   pool_size=getattr(
                                       self, "pool_size", None))
                # cast to the declared output schema (pandas widens types)
                from .. import types as T
                target = pa.schema(
                    [pa.field(f.name, T.to_arrow(f.dtype), f.nullable)
                     for f in self._schema])
                out = out.select(self._schema.names).cast(target)
            nb, _ = from_arrow(out, schema=self._schema)   # H2D
            yield nb


class MapInBatchExec(UnaryExec):
    """mapInPandas: df-in, df-out with a new schema (reference:
    GpuMapInBatchExec)."""

    def __init__(self, fn: Callable, output_schema: Schema, child: Exec,
                 use_daemon: bool = True):
        super().__init__(child)
        self.fn = fn
        self.use_daemon = use_daemon
        self._schema = output_schema

    @property
    def output_schema(self) -> Schema:
        return self._schema

    def do_execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        child_schema = self.child.output_schema
        from .. import types as T
        target = pa.schema([pa.field(f.name, T.to_arrow(f.dtype), f.nullable)
                            for f in self._schema])
        from ..python_worker import worker_apply
        for batch in self.child.execute_partition(p):
            with _python_semaphore.task():
                table = to_arrow(batch, child_schema)
                out = worker_apply(_map_udf_on_table, table, (self.fn,),
                                   use_daemon=self.use_daemon,
                                   pool_size=getattr(
                                       self, "pool_size", None))
                out = out.select(self._schema.names).cast(target)
            if out.num_rows == 0:
                continue
            nb, _ = from_arrow(out, schema=self._schema)
            yield nb


def _to_pandas(batches, schema):
    import pandas as pd
    frames = [to_arrow(b, schema).to_pandas() for b in batches]
    if not frames:
        import pyarrow as _pa
        from .. import types as T
        empty = _pa.table({f.name: _pa.array([], T.to_arrow(f.dtype))
                           for f in schema})
        return empty.to_pandas()
    return pd.concat(frames, ignore_index=True) if len(frames) > 1 \
        else frames[0]


def _emit(pdf, schema: Schema) -> Iterator[ColumnarBatch]:
    from .. import types as T
    target = pa.schema([pa.field(f.name, T.to_arrow(f.dtype), f.nullable)
                        for f in schema])
    out = pa.Table.from_pandas(pdf, preserve_index=False)
    out = out.select(schema.names).cast(target)
    if out.num_rows == 0:
        return
    nb, _ = from_arrow(out, schema=schema)
    yield nb


class AggregateInPandasExec(UnaryExec):
    """groupBy().agg(pandas_udf): one output row per group (reference:
    GpuAggregateInPandasExec — there the cudf groupby feeds per-group
    Arrow batches to the worker; here pandas groupby plays cudf's role).
    The planner co-locates groups with a hash exchange first, exactly as
    it does for native aggregates."""

    def __init__(self, keys: Sequence[str], fn: Callable,
                 input_cols: Sequence[str],
                 output_fields: Sequence[Field], child: Exec):
        super().__init__(child)
        self.keys = list(keys)
        self.fn = fn
        self.input_cols = list(input_cols)
        self.output_fields = list(output_fields)
        key_fields = [child.output_schema.field(k) for k in self.keys]
        self._schema = Schema(key_fields + self.output_fields)

    @property
    def output_schema(self) -> Schema:
        return self._schema

    def do_execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        with _python_semaphore.task():
            pdf = _to_pandas(list(self.child.execute_partition(p)),
                             self.child.output_schema)
            rows = []
            if len(pdf):
                for key, grp in pdf.groupby(self.keys, dropna=False,
                                            sort=False):
                    if not isinstance(key, tuple):
                        key = (key,)
                    res = self.fn(*[grp[c] for c in self.input_cols])
                    if not isinstance(res, (list, tuple)):
                        res = [res]
                    rows.append(list(key) + list(res))
            import pandas as pd
            out = pd.DataFrame(rows, columns=self._schema.names)
        yield from _emit(out, self._schema)


class FlatMapGroupsInPandasExec(UnaryExec):
    """applyInPandas: f(group_df) -> df with an arbitrary schema
    (reference: GpuFlatMapGroupsInPandasExec)."""

    def __init__(self, keys: Sequence[str], fn: Callable,
                 output_schema: Schema, child: Exec):
        super().__init__(child)
        self.keys = list(keys)
        self.fn = fn
        self._schema = output_schema

    @property
    def output_schema(self) -> Schema:
        return self._schema

    def do_execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        import pandas as pd
        with _python_semaphore.task():
            pdf = _to_pandas(list(self.child.execute_partition(p)),
                             self.child.output_schema)
            outs = []
            if len(pdf):
                for _, grp in pdf.groupby(self.keys, dropna=False,
                                          sort=False):
                    outs.append(self.fn(grp.reset_index(drop=True)))
            out = pd.concat(outs, ignore_index=True) if outs else \
                pd.DataFrame(columns=self._schema.names)
        yield from _emit(out, self._schema)


class CoGroupInPandasExec(Exec):
    """cogroup().applyInPandas: f(left_group_df, right_group_df) -> df
    (reference: GpuFlatMapCoGroupsInPandasExec). Both sides must be
    co-partitioned on their keys (planner inserts the exchanges)."""

    def __init__(self, left_keys: Sequence[str],
                 right_keys: Sequence[str], fn: Callable,
                 output_schema: Schema, left: Exec, right: Exec):
        super().__init__((left, right))
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.fn = fn
        self._schema = output_schema

    @property
    def output_schema(self) -> Schema:
        return self._schema

    @property
    def num_partitions(self) -> int:
        return self.children[0].num_partitions

    @staticmethod
    def _norm_key(k) -> Tuple:
        """Group keys as dict keys: NaN objects are identity-hashed in
        CPython, so null keys normalize to None (Spark cogroups null keys
        as ONE group)."""
        if not isinstance(k, tuple):
            k = (k,)
        return tuple(None if (v is None or v != v) else v for v in k)

    def do_execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        import pandas as pd
        left, right = self.children
        if left.num_partitions != right.num_partitions:
            raise ValueError(
                f"cogroup sides must be co-partitioned: "
                f"{left.num_partitions} vs {right.num_partitions} "
                f"partitions (insert matching hash exchanges)")
        with _python_semaphore.task():
            lf = _to_pandas(list(left.execute_partition(p)),
                            left.output_schema)
            rf = _to_pandas(list(right.execute_partition(p)),
                            right.output_schema)
            lgroups = {self._norm_key(k): g
                       for k, g in lf.groupby(self.left_keys, dropna=False,
                                              sort=False)} if len(lf) else {}
            rgroups = {self._norm_key(k): g
                       for k, g in rf.groupby(self.right_keys,
                                              dropna=False, sort=False)} \
                if len(rf) else {}
            outs = []
            for key in list(lgroups) + [k for k in rgroups
                                        if k not in lgroups]:
                lg = lgroups.get(key)
                rg = rgroups.get(key)
                if lg is None:
                    lg = lf.iloc[0:0]
                if rg is None:
                    rg = rf.iloc[0:0]
                outs.append(self.fn(lg.reset_index(drop=True),
                                    rg.reset_index(drop=True)))
            out = pd.concat(outs, ignore_index=True) if outs else \
                pd.DataFrame(columns=self._schema.names)
        yield from _emit(out, self._schema)


class WindowInPandasExec(UnaryExec):
    """Window pandas UDF over whole partitions (reference:
    GpuWindowInPandasExec — unbounded-frame shape): f(series...) returns
    a same-length series per partition group; results append as columns
    in the original row order."""

    def __init__(self, keys: Sequence[str], fn: Callable,
                 input_cols: Sequence[str],
                 output_fields: Sequence[Field], child: Exec):
        super().__init__(child)
        self.keys = list(keys)
        self.fn = fn
        self.input_cols = list(input_cols)
        self.output_fields = list(output_fields)
        self._schema = Schema(list(child.output_schema.fields)
                              + self.output_fields)

    @property
    def output_schema(self) -> Schema:
        return self._schema

    def do_execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        import pandas as pd
        with _python_semaphore.task():
            pdf = _to_pandas(list(self.child.execute_partition(p)),
                             self.child.output_schema)
            for f in self.output_fields:
                pdf[f.name] = None
            if len(pdf):
                for _, grp in pdf.groupby(self.keys, dropna=False,
                                          sort=False):
                    res = self.fn(*[grp[c] for c in self.input_cols])
                    if not isinstance(res, (list, tuple)):
                        res = [res]
                    for f, series in zip(self.output_fields, res):
                        pdf.loc[grp.index, f.name] = \
                            series.values if hasattr(series, "values") \
                            else series
        yield from _emit(pdf, self._schema)
