"""Arrow-exchange Python UDF execs.

Reference: SURVEY.md §2.11 / §3.5 — GpuArrowEvalPythonExec.scala:241
(device → Arrow IPC → python worker → Arrow → device), GpuMapInBatchExec,
GpuAggregateInPandasExec, gated by PythonWorkerSemaphore.scala:41. Here the
engine IS Python, so the "worker" is an in-process callable behind the same
Arrow columnar boundary (to_arrow/from_arrow is the exact exchange the
reference does over a socket), and the worker semaphore bounds concurrent
evaluation the same way.

Two shapes, mirroring the reference's exec family:
- ArrowEvalPythonExec: per-batch scalar pandas UDF — f(pd.Series...) ->
  pd.Series appended as new columns.
- MapInBatchExec: f(pd.DataFrame) -> pd.DataFrame with an arbitrary output
  schema (mapInPandas).
"""

from __future__ import annotations

from typing import Callable, Iterator, List, Optional, Sequence

import pyarrow as pa

from ..batch import ColumnarBatch, Field, Schema, from_arrow, to_arrow
from ..memory.semaphore import TpuSemaphore
from .base import Exec, UnaryExec

# reference: PythonWorkerSemaphore bounds concurrent GPU-using workers
_python_semaphore = TpuSemaphore(4)


class ArrowEvalPythonExec(UnaryExec):
    """Append columns computed by a scalar pandas UDF."""

    def __init__(self, fn: Callable, input_cols: Sequence[str],
                 output_fields: Sequence[Field], child: Exec):
        super().__init__(child)
        self.fn = fn
        self.input_cols = list(input_cols)
        self.output_fields = list(output_fields)
        self._schema = Schema(list(child.output_schema.fields)
                              + self.output_fields)

    @property
    def output_schema(self) -> Schema:
        return self._schema

    def do_execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        child_schema = self.child.output_schema
        for batch in self.child.execute_partition(p):
            with _python_semaphore.task():
                table = to_arrow(batch, child_schema)     # D2H + Arrow
                pdf = table.to_pandas()
                args = [pdf[c] for c in self.input_cols]
                result = self.fn(*args)
                if not isinstance(result, (list, tuple)):
                    result = [result]
                for f, series in zip(self.output_fields, result):
                    pdf[f.name] = series
                out = pa.Table.from_pandas(pdf, preserve_index=False)
                # cast to the declared output schema (pandas widens types)
                from .. import types as T
                target = pa.schema(
                    [pa.field(f.name, T.to_arrow(f.dtype), f.nullable)
                     for f in self._schema])
                out = out.select(self._schema.names).cast(target)
            nb, _ = from_arrow(out, schema=self._schema)   # H2D
            yield nb


class MapInBatchExec(UnaryExec):
    """mapInPandas: df-in, df-out with a new schema (reference:
    GpuMapInBatchExec)."""

    def __init__(self, fn: Callable, output_schema: Schema, child: Exec):
        super().__init__(child)
        self.fn = fn
        self._schema = output_schema

    @property
    def output_schema(self) -> Schema:
        return self._schema

    def do_execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        child_schema = self.child.output_schema
        from .. import types as T
        target = pa.schema([pa.field(f.name, T.to_arrow(f.dtype), f.nullable)
                            for f in self._schema])
        for batch in self.child.execute_partition(p):
            with _python_semaphore.task():
                pdf = to_arrow(batch, child_schema).to_pandas()
                out_pdf = self.fn(pdf)
                out = pa.Table.from_pandas(out_pdf, preserve_index=False)
                out = out.select(self._schema.names).cast(target)
            if out.num_rows == 0:
                continue
            nb, _ = from_arrow(out, schema=self._schema)
            yield nb
