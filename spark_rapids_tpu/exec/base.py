"""Operator base classes and metrics.

Reference: sql-plugin/.../GpuExec.scala:211 (`GpuExec` trait) and its metric
machinery at GpuExec.scala:45-135 (ESSENTIAL/MODERATE/DEBUG GpuMetric levels).

Execution model: pull-based `Iterator[ColumnarBatch]` per partition, exactly
like the reference (SURVEY.md §3.3) — but where the reference dispatches one
JNI kernel per op per batch, here each operator's per-batch computation is a
traced jnp function, so chains of narrow operators (project→filter→project)
fuse into one XLA executable per capacity bucket.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import pyarrow as pa

from ..batch import ColumnarBatch, Schema, to_arrow
from ..expressions.base import EvalContext

ESSENTIAL, MODERATE, DEBUG = 0, 1, 2


@dataclass
class Metric:
    """Reference: GpuMetric over Spark SQLMetric (GpuExec.scala:45)."""

    name: str
    level: int = MODERATE
    value: int = 0
    _lazy: list = field(default_factory=list)

    def add(self, v) -> None:
        self.value += int(v)

    def add_lazy(self, device_scalar) -> None:
        """Accumulate a traced/device scalar WITHOUT forcing a sync; it is
        resolved when the metric is read (reference: GPU-side metric
        accumulation flushed at task end)."""
        self._lazy.append(device_scalar)

    def total(self) -> int:
        if self._lazy:
            self.value += sum(int(x) for x in self._lazy)
            self._lazy.clear()
        return self.value


class Exec:
    """A physical operator. Subclasses define `output_schema` and
    `do_execute() -> Iterator[ColumnarBatch]`."""

    def __init__(self, children: Sequence["Exec"] = (),
                 ctx: EvalContext = EvalContext()):
        self.children: Tuple[Exec, ...] = tuple(children)
        self.ctx = ctx
        self.metrics: Dict[str, Metric] = {
            "numOutputRows": Metric("numOutputRows", ESSENTIAL),
            "numOutputBatches": Metric("numOutputBatches", MODERATE),
            "opTime": Metric("opTime", MODERATE),
        }

    # ---- plan surface ----
    @property
    def output_schema(self) -> Schema:
        raise NotImplementedError(type(self).__name__)

    @property
    def num_partitions(self) -> int:
        """Spark RDD partition count. Narrow operators preserve their
        child's; exchanges define their own."""
        return self.children[0].num_partitions if self.children else 1

    def do_execute(self) -> Iterator[ColumnarBatch]:
        """All partitions chained (single-stream consumers / collect)."""
        for p in range(self.num_partitions):
            yield from self.do_execute_partition(p)

    def do_execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        """One partition's batches. Default: only valid single-partition."""
        if self.num_partitions != 1 or p != 0:
            raise NotImplementedError(
                f"{self.name} does not implement per-partition execution")
        yield from self.do_execute()

    def execute(self) -> Iterator[ColumnarBatch]:
        for p in range(self.num_partitions):
            yield from self.execute_partition(p)

    # ---- coalesce-goal contract (GpuCoalesceBatches.scala:156-228) ----
    def coalesce_goal_for_child(self, i: int):
        """The batch-size contract this operator declares for child ``i``:
        None (no requirement), TargetSize (feed me batches near the
        configured size) or RequireSingleBatch (I need the whole partition
        in one batch). The planner's transition pass inserts
        CoalesceBatchesExec to meet declared goals and verifies them."""
        return None

    @property
    def produces_single_batch(self) -> bool:
        """True when every partition of this exec yields at most ONE batch
        (satisfies RequireSingleBatch without a coalesce)."""
        return False

    def execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        """Iterate one partition, maintaining the op's metrics: batch and
        row counts plus opTime (ns spent INSIDE this operator's iterator,
        including its children — the reference's NS_TIMING convention).
        With query tracing active, the whole partition iteration is one
        operator span (same name as the metric prefix; the NS_TIMING
        caveat applies — children nest inside, and the span closes when
        the consumer exhausts or abandons the iterator)."""
        from .. import trace as qtrace
        from ..utils import tracing
        it = self.do_execute_partition(p)
        with qtrace.span(self.name, kind="operator", partition=p) as sp:
            rows = 0
            while True:
                t0 = time.perf_counter_ns()
                try:
                    # metric-linked profiler range: the slice name in
                    # xprof is the same exec name collect_metrics()
                    # reports (the reference wraps operators in NVTX
                    # ranges the same way)
                    with tracing.op_range(self.name):
                        batch = next(it)
                except StopIteration:
                    self.metrics["opTime"].add(time.perf_counter_ns() - t0)
                    if sp is not None:
                        sp.attrs["rows"] = rows
                    return
                self.metrics["opTime"].add(time.perf_counter_ns() - t0)
                self.metrics["numOutputBatches"].add(1)
                self.metrics["numOutputRows"].add_lazy(batch.num_rows)
                if sp is not None:
                    rows += int(batch.num_rows)
                yield batch

    def collect_metrics(self, max_level: int = DEBUG) -> Dict[str, int]:
        """Aggregate this subtree's metrics up to a level (the
        SQLMetrics→driver roll-up; level filter = metricsLevel conf)."""
        out: Dict[str, int] = {}

        def walk(e: "Exec"):
            for name, m in e.metrics.items():
                v = m.total()
                if m.level <= max_level and v:
                    out[f"{e.name}.{name}"] = \
                        out.get(f"{e.name}.{name}", 0) + v
            for c in e.children:
                walk(c)
        walk(self)
        return out

    def close(self) -> None:
        """Release catalog-registered resources after the query finishes
        (the reference's closeOnExcept/TaskCompletion hooks). Subclasses
        override do_close(); the tree walk happens here."""
        for c in self.children:
            c.close()
        self.do_close()

    def do_close(self) -> None:
        pass

    # ---- debugging / explain ----
    @property
    def name(self) -> str:
        return type(self).__name__

    def tree_string(self, indent: int = 0) -> str:
        s = "  " * indent + f"*{self.name} [{self.output_schema}]\n"
        for c in self.children:
            s += c.tree_string(indent + 1)
        return s

    def __repr__(self):
        return self.tree_string().rstrip()


class LeafExec(Exec):
    def __init__(self, ctx: EvalContext = EvalContext()):
        super().__init__((), ctx)


class UnaryExec(Exec):
    def __init__(self, child: Exec, ctx: Optional[EvalContext] = None):
        super().__init__((child,), ctx or child.ctx)

    @property
    def child(self) -> Exec:
        return self.children[0]


class BinaryExec(Exec):
    def __init__(self, left: Exec, right: Exec,
                 ctx: Optional[EvalContext] = None):
        super().__init__((left, right), ctx or left.ctx)

    @property
    def left(self) -> Exec:
        return self.children[0]

    @property
    def right(self) -> Exec:
        return self.children[1]


def iter_subplan_tables(plan: Exec):
    """The "subplan produced" side of the collect seam: run a plan and
    yield one host Arrow table per output batch, in partition order.
    Stage re-planning and subplan result sharing materialize interior
    boundaries through this, so a captured subtree output is exactly
    what assemble_result() would have consumed."""
    schema = plan.output_schema
    for b in plan.execute():
        yield to_arrow(b, schema)


def assemble_result(tables, schema) -> pa.Table:
    """The "query assembled" side of the collect seam: concatenate the
    per-batch tables (empty input keeps the declared schema)."""
    tables = list(tables)
    if not tables:
        from .. import types as T
        return pa.table({f.name: pa.array([], type=T.to_arrow(f.dtype))
                         for f in schema})
    return pa.concat_tables(tables)


def collect(plan: Exec) -> pa.Table:
    """Run a plan and pull the result to the host as one Arrow table — the
    test/collect boundary (reference: GpuColumnarToRowExec)."""
    return assemble_result(iter_subplan_tables(plan), plan.output_schema)
