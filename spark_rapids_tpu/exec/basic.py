"""Basic physical operators: scan-from-memory, project, filter, limit, union,
range, sample, expand.

Reference: sql-plugin/.../basicPhysicalOperators.scala (GpuProjectExec:147,
GpuFilterExec:423, GpuRangeExec:644, GpuSampleExec), limit.scala,
GpuExpandExec. The TPU-first difference: FilterExec compacts with a cumsum
scatter (no host sync, no dynamic shape) and a project→filter chain traces
into one XLA computation.
"""

from __future__ import annotations

import functools
from typing import Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from .. import types as T
from ..batch import (ColumnarBatch, DeviceColumn, Field, Schema,
                     bucket_capacity, from_arrow)
from ..expressions.base import Alias, EvalContext, Expression
from ..types import TypeKind
from .base import Exec, LeafExec, UnaryExec
from .common import compact, slice_batch


def output_name(e: Expression, i: int) -> str:
    if isinstance(e, Alias):
        return e.name
    name = getattr(e, "name", "")
    return name or f"col{i}"


def bind_all(exprs: Sequence[Expression], schema: Schema) -> List[Expression]:
    return [e.bind(schema) for e in exprs]


def schema_of(exprs: Sequence[Expression]) -> Schema:
    return Schema([Field(output_name(e, i), e.dtype, e.nullable)
                   for i, e in enumerate(exprs)])


class InMemoryScanExec(LeafExec):
    """Leaf feeding pre-loaded data; the H2D boundary for tests and caches
    (reference: GpuInMemoryTableScanExec)."""

    def __init__(self, data, schema: Optional[Schema] = None,
                 batch_rows: Optional[int] = None, num_slices: int = 1,
                 ctx: EvalContext = EvalContext(),
                 dict_conf: Optional[tuple] = None,
                 share: Optional[tuple] = None):
        super().__init__(ctx)
        self._num_slices = num_slices
        # (enabled, maxCardinality, maxCardinalityFraction) for the H2D
        # boundary; the planner threads the SESSION conf here so
        # dictEncoding.enabled=false is honored off the file-scan path
        # too. None = registry defaults (direct test construction).
        self._dict_conf = dict_conf
        # (ScanShareRegistry, key, digest, max_bytes) when cross-query
        # scan sharing is on (plan/sharing.py; the planner threads it) —
        # device batches are immutable, so concurrent queries over the
        # same table content ride one refcounted H2D upload. None = the
        # historical private-upload path, bit for bit.
        self._share = share
        self._share_entry = None
        if isinstance(data, pa.Table):
            self._tables = [data]
            self._batches = None
            if schema is None:
                from ..batch import schema_from_arrow
                schema = schema_from_arrow(data.schema)
        else:
            self._batches = list(data)
            self._tables = None
            assert schema is not None, "schema required for device batches"
        self._schema = schema
        self._batch_rows = batch_rows

    @property
    def output_schema(self) -> Schema:
        return self._schema

    @property
    def num_partitions(self) -> int:
        return self._num_slices

    def _upload_batches(self):
        from ..memory.retry import maybe_inject, with_retry_no_split

        def h2d(chunk):
            maybe_inject("scan.h2d")
            batch, _ = from_arrow(chunk, schema=self._schema,
                                  dict_conf=self._dict_conf)
            return batch

        for table in self._tables:
            n = table.num_rows
            step = self._batch_rows or max(n, 1)
            for off in range(0, max(n, 1), step):
                chunk = table.slice(off, step)
                # H2D under the retry loop. NO split here: batch count
                # feeds the partition round-robin below and the fusion
                # planner's exactly-one-batch contract (fuse.py) — a
                # split would reshuffle rows across partitions / drop
                # the second half of a fused input. File scans split at
                # their H2D instead (io/scan.py).
                yield with_retry_no_split(lambda c=chunk: h2d(c),
                                          name=self.name)
                if n == 0:
                    break

    def _all_batches(self):
        if self._batches is not None:
            yield from self._batches
            return
        if self._share is None:
            yield from self._upload_batches()
            return
        yield from self._shared_batches()

    def _shared_batches(self):
        """Acquire (or perform) the one refcounted upload for this table
        content; the pin is released in do_close()."""
        if self._share_entry is not None:
            return list(self._share_entry.batches)
        from ..plan import sharing
        registry, key, digest, max_bytes = self._share
        entry, uploader = registry.acquire(key, digest,
                                           max_bytes=max_bytes)
        if uploader:
            try:
                batches = list(self._upload_batches())
            except BaseException:
                registry.abort(entry)   # a parked acquirer retries
                raise
            nbytes = sum(t.nbytes for t in self._tables)
            registry.publish(entry, batches, nbytes)
            sharing.metrics().note("scan_share_uploads")
        else:
            sharing.metrics().note("scan_share_hits")
        self._share_entry = entry
        return list(entry.batches)

    def do_close(self) -> None:
        entry = self._share_entry
        if entry is not None:
            self._share_entry = None
            self._share[0].release(entry)

    def do_execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        for i, b in enumerate(self._all_batches()):
            if i % self._num_slices == p:
                yield b


class ProjectExec(UnaryExec):
    """Reference: GpuProjectExec (basicPhysicalOperators.scala:147)."""

    def __init__(self, exprs: Sequence[Expression], child: Exec,
                 ctx: Optional[EvalContext] = None):
        super().__init__(child, ctx)
        self.exprs = bind_all(exprs, child.output_schema)
        self._schema = schema_of(self.exprs)

        def kernel(batch: ColumnarBatch, bseed):
            # errors dict is always live: ANSI rows report conditionally,
            # CAPACITY_* budget overflows report unconditionally. bseed is
            # a traced per-(partition, batch) scalar for stateless PRNG
            # expressions (Rand) — traced, so no per-batch retraces.
            ctx = EvalContext(self.ctx.ansi, {}, batch_seed=bseed)
            # raw_eval: a bare column reference passes the stored column
            # through VERBATIM — dictionary-encoded strings keep their
            # encoding across identity projections (select/reorder), the
            # common case; computed expressions decode at the choke point
            from ..expressions.base import raw_eval
            cols = tuple(raw_eval(e, batch, ctx) for e in self.exprs)
            return ColumnarBatch(cols, batch.num_rows), _sum_errors(ctx)

        self._kernel = jax.jit(kernel)

    @property
    def output_schema(self) -> Schema:
        return self._schema

    def do_execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        for i, batch in enumerate(self.child.execute_partition(p)):
            # deterministic on re-execution: derived from position, not a
            # global counter
            out, errs = self._kernel(batch,
                                     jnp.uint32((p << 16) ^ (i & 0xFFFF)))
            _raise_ansi(errs)
            yield out


class ArithmeticException(ArithmeticError):
    """ANSI-mode evaluation error (Spark's ArithmeticException parity)."""


def _sum_errors(ctx) -> dict:
    return {k: sum(v) for k, v in ctx.errors.items()}


def _raise_ansi(errs: dict) -> None:
    from ..batch import CapacityError
    for kind, count in errs.items():
        if int(count) > 0:
            if kind.startswith("CAPACITY"):
                raise CapacityError(
                    f"[{kind}] {int(count)} row(s) exceeded a fixed device "
                    f"budget; raise the budget or fall back to CPU")
            raise ArithmeticException(
                f"[{kind}] {int(count)} row(s) failed (ANSI mode)")


class FilterExec(UnaryExec):
    """Reference: GpuFilterExec (basicPhysicalOperators.scala:423).

    Null condition values drop the row (Spark semantics). Compaction is a
    cumsum scatter on device — no host round trip.
    """

    def __init__(self, condition: Expression, child: Exec,
                 ctx: Optional[EvalContext] = None):
        super().__init__(child, ctx)
        self.condition = condition.bind(child.output_schema)
        if self.condition.dtype.kind is not TypeKind.BOOLEAN:
            raise TypeError(f"filter condition must be boolean, got "
                            f"{self.condition.dtype}")

        def kernel(batch: ColumnarBatch):
            ctx = EvalContext(self.ctx.ansi, {})
            c = self.condition.eval(batch, ctx)
            keep = c.data & c.validity
            return compact(batch, keep), _sum_errors(ctx)

        self._kernel = jax.jit(kernel)

    @property
    def output_schema(self) -> Schema:
        return self.child.output_schema

    def do_execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        for batch in self.child.execute_partition(p):
            out, errs = self._kernel(batch)
            _raise_ansi(errs)
            yield out


class LocalLimitExec(UnaryExec):
    """Reference: limit.scala GpuLocalLimitExec — cap rows per partition."""

    def __init__(self, limit: int, child: Exec):
        super().__init__(child)
        self.limit = limit
        self._kernel = jax.jit(
            lambda b, remaining: slice_batch(b, jnp.int32(0), remaining))

    @property
    def output_schema(self) -> Schema:
        return self.child.output_schema

    def do_execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        remaining = self.limit
        for batch in self.child.execute_partition(p):
            if remaining <= 0:
                break
            out = self._kernel(batch, jnp.int32(remaining))
            remaining -= int(out.num_rows)  # host sync: limits are control flow
            yield out


class GlobalLimitExec(LocalLimitExec):
    """Reference: GpuGlobalLimitExec — drains all upstream partitions into
    one (the planner places it after a single-partition exchange)."""

    @property
    def num_partitions(self) -> int:
        return 1

    def do_execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        remaining = self.limit
        for cp in range(self.child.num_partitions):
            for batch in self.child.execute_partition(cp):
                if remaining <= 0:
                    return
                out = self._kernel(batch, jnp.int32(remaining))
                remaining -= int(out.num_rows)
                yield out


class UnionExec(Exec):
    """Reference: GpuUnionExec — concatenation of children's partitions."""

    def __init__(self, children: Sequence[Exec]):
        super().__init__(children)

    @property
    def output_schema(self) -> Schema:
        return self.children[0].output_schema

    @property
    def num_partitions(self) -> int:
        return sum(c.num_partitions for c in self.children)

    def do_execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        for c in self.children:
            if p < c.num_partitions:
                yield from c.execute_partition(p)
                return
            p -= c.num_partitions
        raise IndexError(p)


class RangeExec(LeafExec):
    """Reference: GpuRangeExec (basicPhysicalOperators.scala:644)."""

    def __init__(self, start: int, end: int, step: int = 1,
                 batch_rows: int = 1 << 20, name: str = "id"):
        super().__init__()
        if step == 0:
            raise ValueError("step must not be 0")
        self.start, self.end, self.step = start, end, step
        self.batch_rows = batch_rows
        self._schema = Schema([Field(name, T.INT64, nullable=False)])

    @property
    def output_schema(self) -> Schema:
        return self._schema

    def do_execute(self) -> Iterator[ColumnarBatch]:
        total = max(0, -(-(self.end - self.start) // self.step))
        emitted = 0
        while emitted < total or (total == 0 and emitted == 0):
            n = min(self.batch_rows, total - emitted)
            cap = bucket_capacity(max(n, 1))
            base = self.start + emitted * self.step
            data = (jnp.arange(cap, dtype=jnp.int64) * self.step + base)
            live = jnp.arange(cap, dtype=jnp.int32) < n
            col = DeviceColumn(jnp.where(live, data, 0), live, None, T.INT64)
            yield ColumnarBatch((col,), jnp.asarray(n, jnp.int32))
            emitted += n
            if total == 0:
                break


class SampleExec(UnaryExec):
    """Bernoulli row sample (reference: GpuSampleExec, GpuPoissonSampler)."""

    def __init__(self, fraction: float, seed: int, child: Exec):
        super().__init__(child)
        self.fraction, self.seed = fraction, seed

        def kernel(batch: ColumnarBatch, key) -> ColumnarBatch:
            u = jax.random.uniform(key, (batch.capacity,))
            return compact(batch, u < self.fraction)

        self._kernel = jax.jit(kernel)

    @property
    def output_schema(self) -> Schema:
        return self.child.output_schema

    def do_execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        root = jax.random.fold_in(jax.random.PRNGKey(self.seed), p)
        for i, batch in enumerate(self.child.execute_partition(p)):
            yield self._kernel(batch, jax.random.fold_in(root, i))


class ExpandExec(UnaryExec):
    """Reference: GpuExpandExec — one output batch per projection per input
    batch (rollup/cube/grouping sets)."""

    def __init__(self, projections: Sequence[Sequence[Expression]],
                 child: Exec, ctx: Optional[EvalContext] = None):
        super().__init__(child, ctx)
        self.projections = [bind_all(p, child.output_schema)
                            for p in projections]
        self._schema = schema_of(self.projections[0])
        # nullability is the union across projections
        fields = []
        for i, f in enumerate(self._schema):
            nullable = any(p[i].nullable for p in self.projections)
            fields.append(Field(f.name, f.dtype, nullable))
        self._schema = Schema(fields)

        def kernel(batch: ColumnarBatch, pi: int) -> ColumnarBatch:
            cols = tuple(e.eval(batch, self.ctx) for e in self.projections[pi])
            return ColumnarBatch(cols, batch.num_rows)

        self._kernel = jax.jit(kernel, static_argnums=1)

    @property
    def output_schema(self) -> Schema:
        return self._schema

    def do_execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        for batch in self.child.execute_partition(p):
            for pi in range(len(self.projections)):
                yield self._kernel(batch, pi)
