"""Window exec.

Reference: sql-plugin/.../GpuWindowExec.scala:1876 (batched partitioned
windows; running-window :1534; cached double-pass :1846). See
expressions/window.py for the lowering strategy: one sort, then segmented
scans — every window expression in the projection shares the same sorted
layout and fuses into a single XLA computation per batch.

Output = child columns + one column per window expression, in the child's
original row order (results are scattered back through the sort
permutation), matching Spark's WindowExec contract.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .. import types as T
from ..batch import ColumnarBatch, DeviceColumn, Field, Schema, bucket_capacity
from ..expressions.base import Alias, EvalContext, Expression
from ..expressions.window import (LagLead, NTile, Rank, RowNumber,
                                  WindowAgg, WindowExpression, WindowFrame,
                                  segmented_scan)
from ..types import TypeKind
from .base import Exec, UnaryExec
from .common import adjacent_equal, concat_batches, gather_column, \
    sort_operands


def _unalias(e: Expression) -> Tuple[WindowExpression, str]:
    if isinstance(e, Alias):
        return e.child, e.name
    return e, "window"


class WindowExec(UnaryExec):
    """All window expressions must share one WindowSpec (the planner splits
    multi-spec projections into a chain of WindowExecs, like the reference's
    GpuWindowExec partitioning of window ops)."""

    def coalesce_goal_for_child(self, i):
        from .coalesce import TargetSize
        return TargetSize()

    def __init__(self, window_exprs: Sequence[Expression], child: Exec,
                 ctx: Optional[EvalContext] = None):
        super().__init__(child, ctx)
        named = [_unalias(e) for e in window_exprs]
        self.exprs = [w.bind(child.output_schema) for w, _ in named]
        self.names = [n for _, n in named]
        # Expression __eq__ builds comparison trees, so compare specs by repr
        spec_keys = {(repr(w.spec.partition_keys), repr(w.spec.orders))
                     for w in self.exprs}
        if len(spec_keys) > 1:
            raise ValueError("one WindowExec handles one partition/order "
                             "spec; chain execs for multiple")
        self.spec = self.exprs[0].spec
        # fail fast on frames the device kernel cannot express — the planner
        # tags these for CPU fallback before ever constructing this exec;
        # without this guard a bounded RANGE frame would silently get ROWS
        # semantics from the shift-fold path
        from ..expressions.window import NthValue as _NV, WindowAgg as _WA, \
            unsupported_frame_reason
        for w in self.exprs:
            if isinstance(w.function, (_WA, _NV)):
                reason = unsupported_frame_reason(w.spec.frame, w.spec)
                if reason:
                    raise NotImplementedError(reason)
        fields = list(child.output_schema.fields)
        for w, n in zip(self.exprs, self.names):
            fields.append(Field(n, w.dtype, w.nullable))
        self._schema = Schema(fields)
        self._kernel = jax.jit(self._window_kernel)

    @property
    def output_schema(self) -> Schema:
        return self._schema

    # ------------------------------------------------------------------

    def _window_kernel(self, batch: ColumnarBatch) -> ColumnarBatch:
        cap = batch.capacity
        spec = self.spec
        live = batch.row_mask()
        pkeys = [e.eval(batch, self.ctx) for e in spec.partition_keys]
        okeys = [o.child.eval(batch, self.ctx) for o in spec.orders]

        ops = sort_operands(
            list(pkeys) + list(okeys),
            [False] * len(pkeys) + [o.descending for o in spec.orders],
            [True] * len(pkeys) + [o.effective_nulls_first
                                   for o in spec.orders], live)
        iota = jnp.arange(cap, dtype=jnp.int32)
        perm = jax.lax.sort(ops + [iota], num_keys=len(ops) + 1)[-1]

        s_pkeys = [gather_column(c, perm) for c in pkeys]
        s_okeys = [gather_column(c, perm) for c in okeys]
        sorted_live = iota < batch.num_rows
        # trace-scoped context for value-bounded RANGE ranking (the merge
        # rank re-evaluates the order column; XLA CSEs the duplicate)
        self._range_batch = batch
        self._range_perm = perm

        if s_pkeys:
            same_part = adjacent_equal(s_pkeys)
        else:
            same_part = jnp.concatenate(
                [jnp.zeros(1, bool), jnp.ones(cap - 1, bool)])
        head = sorted_live & ~same_part          # first row of each partition
        tail = sorted_live & jnp.concatenate(
            [~same_part[1:] | ~sorted_live[1:], jnp.ones(1, bool)])

        # peer groups (ties on order keys) for RANGE frames / rank
        if s_okeys:
            same_peer = same_part & adjacent_equal(s_okeys)
        else:
            same_peer = same_part
        peer_head = sorted_live & ~same_peer

        out_cols = []
        for w in self.exprs:
            col = self._eval_window(w, batch, perm, head, tail, peer_head,
                                    sorted_live, cap)
            # scatter back to original row order
            inv = jnp.zeros(cap, jnp.int32).at[perm].set(iota)
            out_cols.append(gather_column(col, inv, batch.row_mask()))
        return ColumnarBatch(batch.columns + tuple(out_cols), batch.num_rows)

    # ------------------------------------------------------------------

    def _eval_window(self, w: WindowExpression, batch, perm, head, tail,
                     peer_head, live, cap: int) -> DeviceColumn:
        fn = w.function
        iota = jnp.arange(cap, dtype=jnp.int32)
        seg_start = segmented_scan(
            jnp.where(head, iota, 0), head, jnp.maximum)
        pos = iota - seg_start                      # 0-based row in partition

        if isinstance(fn, RowNumber):
            return DeviceColumn((pos + 1).astype(jnp.int32), live, None,
                                T.INT32)
        if isinstance(fn, Rank):
            peer_start = segmented_scan(
                jnp.where(peer_head, iota, 0), head, jnp.maximum)
            if fn.dense:
                v = segmented_scan(peer_head.astype(jnp.int32), head,
                                   jnp.add)
            else:
                v = peer_start - seg_start + 1
            return DeviceColumn(v.astype(jnp.int32), live, None, T.INT32)
        from ..expressions.window import CumeDist, NthValue, PercentRank
        if isinstance(fn, PercentRank):
            peer_start = segmented_scan(
                jnp.where(peer_head, iota, 0), head, jnp.maximum)
            rank = peer_start - seg_start + 1
            seg_len = self._seg_len(head, tail, iota, cap)
            v = jnp.where(seg_len > 1,
                          (rank - 1).astype(jnp.float64) /
                          jnp.maximum(seg_len - 1, 1).astype(jnp.float64),
                          0.0)
            return DeviceColumn(v, live, None, T.FLOAT64)
        if isinstance(fn, CumeDist):
            peer_tail = jnp.concatenate(
                [peer_head[1:], jnp.ones(1, bool)]) | tail
            pe = segmented_scan(jnp.where(peer_tail, iota, cap),
                                peer_tail, jnp.minimum, reverse=True)
            seg_len = self._seg_len(head, tail, iota, cap)
            v = (pe - seg_start + 1).astype(jnp.float64) / \
                jnp.maximum(seg_len, 1).astype(jnp.float64)
            return DeviceColumn(v, live, None, T.FLOAT64)
        if isinstance(fn, NthValue):
            src = fn.child.eval(batch, self.ctx)
            s = gather_column(src, perm)
            lo, hi = self._frame_bounds(w.spec.frame, head, tail,
                                        peer_head, live, iota, cap)
            idx = lo + fn.n - 1
            ok = (idx <= hi) & (idx >= lo) & live
            v = gather_column(s, jnp.clip(idx, 0, cap - 1))
            return v.replace(validity=v.validity & ok)
        if isinstance(fn, NTile):
            seg_len = self._seg_len(head, tail, iota, cap)
            b = jnp.int32(fn.buckets)
            base, rem = seg_len // b, seg_len % b
            cut = rem * (base + 1)
            v = jnp.where(pos < cut, pos // jnp.maximum(base + 1, 1),
                          rem + (pos - cut) // jnp.maximum(base, 1)) + 1
            return DeviceColumn(v.astype(jnp.int32), live, None, T.INT32)
        if isinstance(fn, LagLead):
            src = fn.child.eval(batch, self.ctx)
            s = gather_column(src, perm)
            off = fn.offset if fn.is_lag else -fn.offset
            shifted_ix = jnp.clip(iota - off, 0, cap - 1)
            ok = (iota - off >= 0) & (iota - off < cap)
            sv = gather_column(s, shifted_ix)
            # same partition check: partition id = cumsum(head)
            pid = jnp.cumsum(head.astype(jnp.int32))
            same = ok & (jnp.take(pid, shifted_ix) == pid) & live
            data = sv.data
            validity = sv.validity & same
            if fn.default is not None:
                dcol = gather_column(
                    fn.default.eval(batch, self.ctx), perm)
                use_d = ~same & live
                if s.lengths is not None:
                    data = jnp.where(use_d[:, None], dcol.data, data)
                    lengths = jnp.where(use_d, dcol.lengths, sv.lengths)
                    validity = jnp.where(use_d, dcol.validity, validity)
                    return DeviceColumn(data, validity & live, lengths,
                                        fn.dtype)
                data = jnp.where(use_d, dcol.data, data)
                validity = jnp.where(use_d, dcol.validity, validity)
            return DeviceColumn(data, validity & live, sv.lengths, fn.dtype)
        if isinstance(fn, WindowAgg):
            return self._eval_window_agg(fn, w.spec.frame, batch, perm,
                                         head, tail, peer_head, live, cap)
        raise NotImplementedError(type(fn).__name__)

    def _seg_len(self, head, tail, iota, cap):
        seg_start = segmented_scan(jnp.where(head, iota, 0), head,
                                   jnp.maximum)
        seg_end = segmented_scan(jnp.where(tail, iota, cap), tail,
                                 jnp.minimum, reverse=True)
        return seg_end - seg_start + 1

    def _eval_window_agg(self, fn: WindowAgg, frame: WindowFrame, batch,
                         perm, head, tail, peer_head, live, cap: int
                         ) -> DeviceColumn:
        from ..expressions.aggregates import (Average, Count, Max, Min, Sum)
        agg = fn.agg
        child_cols = [gather_column(c.eval(batch, self.ctx), perm)
                      for c in agg.children]
        col = child_cols[0] if child_cols else None
        iota = jnp.arange(cap, dtype=jnp.int32)

        if isinstance(agg, Count):
            x = ((col.validity & live) if col is not None else live
                 ).astype(jnp.int64)
            out_t = T.INT64
            v, valid = self._frame_reduce(x, jnp.add, jnp.int64(0), frame,
                                          head, tail, peer_head, live, iota,
                                          cap)
            return DeviceColumn(v, live, None, out_t)
        if isinstance(agg, (Sum, Average)):
            acc_t = jnp.float64 if isinstance(agg, Average) or \
                agg.dtype.kind in (TypeKind.FLOAT32, TypeKind.FLOAT64) \
                else jnp.int64
            ok = col.validity & live
            x = jnp.where(ok, col.data, 0).astype(acc_t)
            s, _ = self._frame_reduce(x, jnp.add, acc_t(0), frame, head,
                                      tail, peer_head, live, iota, cap)
            n, _ = self._frame_reduce(ok.astype(jnp.int64), jnp.add,
                                      jnp.int64(0), frame, head, tail,
                                      peer_head, live, iota, cap)
            if isinstance(agg, Average):
                v = s / jnp.maximum(n, 1).astype(jnp.float64)
                return DeviceColumn(jnp.where(n > 0, v, 0.0),
                                    (n > 0) & live, None, T.FLOAT64)
            return DeviceColumn(s.astype(agg.dtype.storage_dtype),
                                (n > 0) & live, None, agg.dtype)
        if isinstance(agg, (Min, Max)):
            is_min = isinstance(agg, Min)
            ok = col.validity & live
            if agg.dtype.kind is TypeKind.BOOLEAN:
                fill = jnp.asarray(is_min, bool)
                op = jnp.logical_and if is_min else jnp.logical_or
                x = jnp.where(ok, col.data, fill)
            elif agg.dtype.kind in (TypeKind.FLOAT32, TypeKind.FLOAT64):
                fill = jnp.asarray(jnp.inf if is_min else -jnp.inf,
                                   col.data.dtype)
                op = jnp.minimum if is_min else jnp.maximum
                x = jnp.where(ok, col.data, fill)
            else:
                info = jnp.iinfo(col.data.dtype)
                fill = jnp.asarray(info.max if is_min else info.min,
                                   col.data.dtype)
                op = jnp.minimum if is_min else jnp.maximum
                x = jnp.where(ok, col.data, fill)
            v, _ = self._frame_reduce(x, op, fill, frame, head, tail,
                                      peer_head, live, iota, cap)
            n, _ = self._frame_reduce(ok.astype(jnp.int64), jnp.add,
                                      jnp.int64(0), frame, head, tail,
                                      peer_head, live, iota, cap)
            valid = (n > 0) & live
            return DeviceColumn(jnp.where(valid, v, jnp.zeros_like(v)),
                                valid, None, agg.dtype)
        raise NotImplementedError(type(agg).__name__)

    def _frame_reduce(self, x, op, identity, frame: WindowFrame, head, tail,
                      peer_head, live, iota, cap):
        """Reduce x over each row's frame; returns (values, None)."""
        if frame.is_full_partition:
            # segment total broadcast back: forward running to tail, gather
            run = segmented_scan(x, head, op)
            seg_end = segmented_scan(jnp.where(tail, iota, cap), tail,
                                     jnp.minimum, reverse=True)
            return jnp.take(run, jnp.clip(seg_end, 0, cap - 1)), None
        if frame.is_running:
            run = segmented_scan(x, head, op)
            if frame.is_rows:
                return run, None
            # RANGE running: value at each row = running at its peer END
            peer_tail = jnp.concatenate(
                [peer_head[1:], jnp.ones(1, bool)]) | tail
            pe = segmented_scan(jnp.where(peer_tail, iota, cap), peer_tail,
                                jnp.minimum, reverse=True)
            return jnp.take(run, jnp.clip(pe, 0, cap - 1)), None
        if frame.is_rows and frame.start is not None and \
                frame.end is not None and frame.end - frame.start < 64:
            # small literal ROWS windows: static shift fold beats the
            # scan/gather machinery (exact for every op, incl. floats)
            p, f = -frame.start, frame.end
            pid = jnp.cumsum(head.astype(jnp.int32))
            acc = jnp.full(x.shape, identity, x.dtype)
            for o in range(-p, f + 1):
                ix = jnp.clip(iota + o, 0, cap - 1)
                ok = (iota + o >= 0) & (iota + o < cap)
                same = ok & (jnp.take(pid, ix) == pid)
                contrib = jnp.where(same, jnp.take(x, ix), identity)
                acc = op(acc, contrib)
            return acc, None
        # general path: per-row [lo, hi] absolute bounds, then a
        # prefix-difference (sums) or sparse-table (min/max) reduction
        # (reference: GpuWindowExec.scala:1846 double-pass machinery)
        lo, hi = self._frame_bounds(frame, head, tail, peer_head, live,
                                    iota, cap)
        return self._reduce_between(x, op, identity, lo, hi, head, cap), None

    # ------------------------------------------------------------------
    # General frames (round 4 — VERDICT r3 Next #3)
    # ------------------------------------------------------------------

    def _partition_bounds(self, head, tail, iota, cap):
        seg_start = segmented_scan(jnp.where(head, iota, 0), head,
                                   jnp.maximum)
        seg_end = segmented_scan(jnp.where(tail, iota, cap), tail,
                                 jnp.minimum, reverse=True)
        return seg_start, seg_end

    def _frame_bounds(self, frame: WindowFrame, head, tail, peer_head,
                      live, iota, cap):
        """Absolute sorted-layout [lo, hi] index bounds of each row's
        frame (hi < lo = empty). ROWS bounds are positional; RANGE bounds
        with nonzero offsets rank shifted ORDER VALUES into the sorted
        layout via one merge-sort per bounded side."""
        seg_start, seg_end = self._partition_bounds(head, tail, iota, cap)
        if frame.is_rows:
            lo = seg_start if frame.start is None \
                else jnp.maximum(iota + frame.start, seg_start)
            hi = seg_end if frame.end is None \
                else jnp.minimum(iota + frame.end, seg_end)
            return lo, jnp.maximum(hi, lo - 1)
        # RANGE: peer-group bounds for CURRENT ROW ends; merge-rank for
        # value offsets
        peer_tail = jnp.concatenate(
            [peer_head[1:], jnp.ones(1, bool)]) | tail
        peer_start = segmented_scan(jnp.where(peer_head, iota, 0), head,
                                    jnp.maximum)
        peer_end = segmented_scan(jnp.where(peer_tail, iota, cap),
                                  peer_tail, jnp.minimum, reverse=True)
        if frame.start is None:
            lo = seg_start
        elif frame.start == 0:
            lo = peer_start
        else:
            lo = self._range_rank(frame.start, True, head, peer_start,
                                  peer_end, live, iota, cap)
        if frame.end is None:
            hi = seg_end
        elif frame.end == 0:
            hi = peer_end
        else:
            hi = self._range_rank(frame.end, False, head, peer_start,
                                  peer_end, live, iota, cap)
        return lo, hi

    def _range_rank(self, delta: int, is_lo: bool, head, peer_start,
                    peer_end, live, iota, cap):
        """Rank each row's shifted order value among the partition's rows:
        lo = first index with value >= v+delta, hi = last index with
        value <= v+delta. One (pid, null-rank, word, tag, iota) merge sort
        of 2n lanes; bound rows' sorted relative order equals their
        original order (values ascend within partitions), so
        count-of-data-before = merged position - own index. NULL order
        rows take their peer group (the SQL standard's all-nulls frame)."""
        from .common import orderable_words
        spec = self.spec
        o = spec.orders[0]
        # evaluated + sorted order column (CSE'd with the kernel's own
        # sort by XLA — identical subgraphs)
        batch = self._range_batch
        col = o.child.eval(batch, self.ctx)
        col = gather_column(col, self._range_perm)
        data = col.data
        if o.descending:
            # descending layouts sort by FLIPPED orderable words (~w,
            # bijective — value negation would merge INT_MIN with
            # INT_MIN+1); Spark's desc range frame covers values
            # [v-end, v-start], so the bound value is v - delta and only
            # the word domain flips
            shifted = self._sat_add(data, -delta)
            word = ~orderable_words(
                col.replace(data=shifted, validity=col.validity))[0]
            data_word = ~orderable_words(
                col.replace(data=data, validity=col.validity))[0]
        else:
            shifted = self._sat_add(data, delta)
            word = orderable_words(
                col.replace(data=shifted, validity=col.validity))[0]
            data_word = orderable_words(
                col.replace(data=data, validity=col.validity))[0]
        nulls_first = o.effective_nulls_first
        n_rank = jnp.where(col.validity,
                           jnp.uint8(1),
                           jnp.uint8(0 if nulls_first else 2))
        pid_raw = jnp.cumsum(head.astype(jnp.int32))
        pid = jnp.where(live, pid_raw, jnp.int32(2147483647))
        # tag: lo-side bounds sort BEFORE equal data (rank = count of
        # data strictly below); hi-side bounds sort AFTER equal data
        tag_data = jnp.full(cap, 1 if is_lo else 0, jnp.uint8)
        tag_bound = jnp.full(cap, 0 if is_lo else 1, jnp.uint8)
        # bounds carry their row's OWN null rank: null-row bounds stay
        # confined to the null region (their words are garbage; the rank
        # lane keeps them from interleaving among real-valued entries,
        # which preserves the bounds-sort-in-original-order identity the
        # count arithmetic relies on)
        lanes = [
            jnp.concatenate([pid, pid]),
            jnp.concatenate([n_rank, n_rank]),
            jnp.concatenate([data_word, word]),
            jnp.concatenate([tag_data, tag_bound]),
            jnp.arange(2 * cap, dtype=jnp.int32),
        ]
        perm2 = jax.lax.sort(lanes, num_keys=4)[-1]
        inv = jnp.zeros(2 * cap, jnp.int32).at[perm2].set(
            jnp.arange(2 * cap, dtype=jnp.int32))
        count_before = inv[cap:] - iota          # data rows sorting before
        if is_lo:
            pos = count_before                   # first idx with w >= bound
        else:
            pos = count_before - 1               # last idx with w <= bound
        # null order rows: frame = their (all-null) peer group
        pos = jnp.where(col.validity, pos,
                        peer_start if is_lo else peer_end)
        return pos

    @staticmethod
    def _sat_add(x, d: int):
        if jnp.issubdtype(x.dtype, jnp.floating):
            return x + d
        info = jnp.iinfo(x.dtype)
        if d >= 0:
            return jnp.where(x > info.max - d, info.max, x + d)
        return jnp.where(x < info.min - d, info.min, x + d)

    def _reduce_between(self, x, op, identity, lo, hi, head, cap):
        """Per-row reduce of x over [lo, hi] (identity when hi < lo).
        Sums ride a segmented prefix difference (rounding stays partition-
        local); arbitrary ops (min/max/and/or — idempotent) use a doubling
        sparse table: result = op(T_j[lo], T_j[hi-2^j+1]) with
        j = floor(log2(len)), overlap harmless for idempotent ops."""
        iota = jnp.arange(cap, dtype=jnp.int32)
        ident = jnp.asarray(identity, x.dtype)
        empty = hi < lo
        lo_c = jnp.clip(lo, 0, cap - 1)
        hi_c = jnp.clip(hi, 0, cap - 1)
        if op is jnp.add:
            run = segmented_scan(x, head, jnp.add)
            seg_start = segmented_scan(jnp.where(head, iota, 0), head,
                                       jnp.maximum)
            upper = jnp.take(run, hi_c)
            lower = jnp.where(lo > seg_start,
                              jnp.take(run, jnp.clip(lo - 1, 0, cap - 1)),
                              jnp.zeros_like(ident))
            return jnp.where(empty, ident, upper - lower)
        levels = [x]
        d = 1
        while d < cap:
            top = levels[-1]
            shifted = jnp.concatenate(
                [top[d:], jnp.full((d,), ident, top.dtype)])
            levels.append(op(top, shifted))
            d <<= 1
        stacked = jnp.stack(levels)              # (J, cap)
        L = jnp.maximum(hi - lo + 1, 1)
        j = jnp.floor(jnp.log2(L.astype(jnp.float64))).astype(jnp.int32)
        flat = stacked.reshape(-1)
        a = jnp.take(flat, j * cap + lo_c)
        b_pos = jnp.clip(hi - jnp.left_shift(jnp.int32(1), j) + 1,
                         0, cap - 1)
        b = jnp.take(flat, j * cap + b_pos)
        return jnp.where(empty, ident, op(a, b))

    # ------------------------------------------------------------------

    def do_execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        # windows need WHOLE window-partitions per batch. A key-batching
        # child guarantees that with bounded batch sizes (reference:
        # GpuKeyBatchingIterator) — process batch-at-a-time; otherwise
        # concat the stream partition into one batch.
        guarantee = getattr(self.child, "key_complete_for", None)
        if guarantee is not None and \
                guarantee == repr(list(self.spec.partition_keys)):
            for batch in self.child.execute_partition(p):
                yield self._kernel(batch)
            return
        # accumulated input batches ride the spill catalog across the
        # retry boundary (SpillableColumnarBatch discipline); the concat +
        # window kernel re-runs after an OOM with pins released and the
        # store spilled (no split: a window partition must stay whole)
        from ..memory import admit_all, device_budget, with_retry_no_split
        cat = device_budget()
        in_schema = self.child.output_schema
        inputs = admit_all(self.child.execute_partition(p), in_schema, cat,
                           name=f"{self.name}.admit")
        if not inputs:
            return

        def assemble_and_run():
            got = []
            try:
                for item in inputs:
                    got.append(item.acquire())
                if len(got) == 1:
                    return self._kernel(got[0])
                cap = bucket_capacity(sum(b.capacity for b in got))
                return self._kernel(concat_batches(got, cap))
            finally:
                for j in range(len(got)):
                    inputs[j].release()

        try:
            yield with_retry_no_split(assemble_and_run, catalog=cat,
                                      name=self.name)
        finally:
            for item in inputs:
                item.close()
