"""Generate (explode / posexplode) — lateral view over array columns.

Reference: sql-plugin/.../rapids/GpuGenerateExec.scala (explode,
posexplode, outer variants; lazy-array optimization). The cudf design
gathers via an offsets column; the TPU layout is already rectangular
(``data[cap, max_elems]`` + ``lengths``), so explode is a *reshape*:

1. broadcast every required child column across the element axis
   → ``[cap, me]`` and flatten to ``[cap*me]``,
2. build the element keep-mask (slot < length; for OUTER, slot 0 of an
   empty/null array also survives, with a null element),
3. stable-compact — the same cumsum-scatter primitive filters use.

The whole thing is one fused XLA program per batch; no per-row host work.
Output capacity is the static bound ``cap * me`` (the planner gates
oversized budgets via TypeSig, like the reference's batch-size splitting
in GpuGenerateExec.scala's fixUpBatches).
"""

from __future__ import annotations

from typing import Iterator, Optional

import jax
import jax.numpy as jnp

from .. import types as T
from ..batch import ColumnarBatch, DeviceColumn, Field, Schema
from ..expressions.base import EvalContext, Expression
from ..types import TypeKind
from .base import UnaryExec
from .common import compact


class GenerateExec(UnaryExec):
    """explode/posexplode over one array-typed generator expression.

    ``outer=True`` keeps rows whose array is null/empty, emitting one row
    with a null element (Spark's EXPLODE_OUTER / LATERAL VIEW OUTER).
    ``pos=True`` prepends the element position column (posexplode).
    """

    def __init__(self, generator: Expression, child: "Exec",
                 outer: bool = False, pos: bool = False,
                 elem_name: str = "col", pos_name: str = "pos",
                 value_name: str = "value",
                 ctx: Optional[EvalContext] = None):
        super().__init__(child, ctx)
        self.generator = generator.bind(child.output_schema)
        self.outer = outer
        self.pos = pos
        gt = self.generator.dtype
        if gt.kind not in (TypeKind.ARRAY, TypeKind.MAP):
            raise TypeError(f"explode expects an array or map, got {gt}")
        self.is_map = gt.kind is TypeKind.MAP
        fields = list(child.output_schema.fields)
        if pos:
            fields.append(Field(pos_name, T.INT32, outer))
        if self.is_map:
            fields.append(Field(elem_name, gt.children[0], outer))
            fields.append(Field(value_name, gt.children[1], outer))
        else:
            fields.append(Field(elem_name, gt.children[0], outer))
        self._schema = Schema(fields)

        def kernel(batch):
            from .basic import _sum_errors
            kctx = EvalContext(self.ctx.ansi, {})
            return self._explode_kernel(batch, kctx), _sum_errors(kctx)

        self._kernel = jax.jit(kernel)

    @property
    def output_schema(self) -> Schema:
        return self._schema

    def _explode_kernel(self, batch: ColumnarBatch,
                        ctx: EvalContext) -> ColumnarBatch:
        # flatten_repeat rebuilds carried columns lane by lane and has no
        # dictionary slot — decode dict strings first (repeat-then-decode
        # and decode-then-repeat commute)
        from ..dictenc import decode_batch
        batch = decode_batch(batch)
        arr = self.generator.eval(batch, ctx)
        cap, me = arr.data.shape[:2]     # array<string> data is 3D
        out_cap = cap * me
        slot = jnp.arange(me, dtype=jnp.int32)[None, :]        # [1, me]
        row_live = batch.row_mask()
        has_elem = arr.validity & (arr.lengths > 0)
        keep = (slot < arr.lengths[:, None]) & arr.validity[:, None]
        elem_valid = keep
        if self.outer:
            pad_row = (slot == 0) & (~has_elem)[:, None]
            keep = keep | pad_row
        keep = keep & row_live[:, None]

        def flatten_repeat(col: DeviceColumn) -> DeviceColumn:
            data = jnp.broadcast_to(col.data[:, None], (cap, me) +
                                    col.data.shape[1:]).reshape(
                (out_cap,) + col.data.shape[1:])
            validity = jnp.broadcast_to(col.validity[:, None],
                                        (cap, me)).reshape(out_cap)
            lengths = None
            if col.lengths is not None:
                lengths = jnp.broadcast_to(col.lengths[:, None],
                                           (cap, me)).reshape(out_cap)
            data2 = None
            if col.data2 is not None:
                data2 = jnp.broadcast_to(col.data2[:, None], (cap, me) +
                                         col.data2.shape[1:]).reshape(
                    (out_cap,) + col.data2.shape[1:])
            return DeviceColumn(data, validity, lengths, col.dtype, data2)

        cols = [flatten_repeat(c) for c in batch.columns]
        if self.pos:
            # Spark posexplode_outer: pad rows carry NULL pos
            pos_data = jnp.broadcast_to(slot, (cap, me)).reshape(out_cap)
            cols.append(DeviceColumn(pos_data, elem_valid.reshape(out_cap),
                                     None, T.INT32))
        gt = self.generator.dtype
        if not self.is_map and arr.data.ndim == 3:
            # array<string>: elements flatten to a [cap*me, max_len] byte
            # matrix with per-element lengths from data2
            el = jnp.where(elem_valid.reshape(out_cap)[:, None],
                           arr.data.reshape(out_cap, arr.data.shape[2]), 0)
            el_lens = jnp.where(elem_valid.reshape(out_cap),
                                arr.data2.reshape(out_cap), 0)
            cols.append(DeviceColumn(el, elem_valid.reshape(out_cap),
                                     el_lens, gt.children[0]))
        else:
            cols.append(DeviceColumn(arr.data.reshape(out_cap),
                                     elem_valid.reshape(out_cap), None,
                                     gt.children[0]))
        if self.is_map:
            cols.append(DeviceColumn(arr.data2.reshape(out_cap),
                                     elem_valid.reshape(out_cap), None,
                                     gt.children[1]))
        # every flat slot is "live" (compact ANDs with row_mask; the real
        # row selection is the keep mask)
        flat = ColumnarBatch(tuple(cols), jnp.asarray(out_cap, jnp.int32))
        return compact(flat, keep.reshape(out_cap))

    def do_execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        from .basic import _raise_ansi
        for batch in self.child.execute_partition(p):
            out, errs = self._kernel(batch)
            _raise_ansi(errs)
            yield out
