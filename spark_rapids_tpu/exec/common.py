"""Shared device kernels used by every operator.

These are the TPU-native replacements for libcudf's table primitives
(reference contract in SURVEY.md §2.9: gather 13 call sites, filter 77,
concatenate 11, orderBy 4, partition 5). Everything here is shape-static and
jit-traceable: row counts are traced scalars, capacities are static ints, so
operator pipelines fuse into single XLA computations.

Key primitives:
- ``compact``     — stable scatter-compaction of kept rows (cudf filter).
- ``gather``      — row gather with out-of-bounds-as-null (cudf gather map).
- ``concat``      — batch concatenation at a given capacity (cudf concatenate).
- ``sort_keys``   — rank-preserving normalization of any SQL column into
                    uint-comparable operands for ``lax.sort`` (cudf orderBy).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .. import types as T
from ..batch import ColumnarBatch, DeviceColumn, Schema
from ..types import SqlType, TypeKind


# ---------------------------------------------------------------------------
# Gather / compact / concat
# ---------------------------------------------------------------------------

def _and_validity_deep(col: DeviceColumn, mask: jax.Array) -> DeviceColumn:
    """AND ``mask`` into a column's validity (struct children included, so
    padded/OOB rows read as null at every nesting level)."""
    if col.is_struct:
        kids = tuple(_and_validity_deep(c, mask) for c in col.data)
        return col.replace(data=kids, validity=col.validity & mask)
    return col.replace(validity=col.validity & mask)


def gather_column(col: DeviceColumn, indices: jax.Array,
                  row_valid: Optional[jax.Array] = None) -> DeviceColumn:
    """Gather rows of ``col`` at ``indices`` (int32[out_cap]).

    ``row_valid`` marks which output slots hold a real gathered row; slots
    outside it become null (the cudf gather-map convention where an OOB index
    yields null — used by outer joins).
    """
    if col.is_struct:
        return gather_columns([col], indices, row_valid)[0]
    idx = jnp.clip(indices, 0, col.capacity - 1)
    data = jnp.take(col.data, idx, axis=0)
    validity = jnp.take(col.validity, idx, axis=0)
    lengths = jnp.take(col.lengths, idx, axis=0) if col.lengths is not None else None
    data2 = jnp.take(col.data2, idx, axis=0) if col.data2 is not None else None
    if row_valid is not None:
        validity = validity & row_valid
    # dict strings: the CODES are the row lane; the dictionary rides along
    # untouched (its leading dim is card, not cap)
    return DeviceColumn(data, validity, lengths, col.dtype, data2,
                        col.dict_data, col.dict_lengths)


def _batched_takes(arrays: Sequence[jax.Array], idx: jax.Array
                   ) -> List[jax.Array]:
    """Gather many same-length arrays at ONE index set with as few device
    gathers as possible: same-dtype 1-D arrays stack into a [n, m] matrix
    for a single row-gather (docs/perf_r3.md: a 4M-row gather costs
    ~55-65 ms regardless of row width, and sibling gathers do NOT fuse)."""
    from collections import defaultdict
    byd = defaultdict(list)
    for i, a in enumerate(arrays):
        byd[(a.dtype, a.ndim)].append(i)
    out: List[Optional[jax.Array]] = [None] * len(arrays)
    for (dt, nd), idxs in byd.items():
        if nd != 1 or len(idxs) == 1:
            for i in idxs:
                out[i] = jnp.take(arrays[i], idx, axis=0)
        else:
            m = jnp.stack([arrays[i] for i in idxs], axis=1)
            g = jnp.take(m, idx, axis=0)
            for j, i in enumerate(idxs):
                out[i] = g[:, j]
    return out


def gather_columns(cols: Sequence[DeviceColumn], indices: jax.Array,
                   row_valid: Optional[jax.Array] = None
                   ) -> List[DeviceColumn]:
    """Gather MANY columns at one index set, batching the underlying takes
    (data lanes by dtype, all validity lanes together, lengths with other
    int32 lanes)."""
    if not cols:
        return []
    cap = cols[0].capacity
    idx = jnp.clip(indices, 0, cap - 1)
    # dictionaries are NOT row lanes — strip them before the flatten so
    # they are never row-gathered, reattach after (codes gather like any
    # int32 lane)
    dicts = [(c.dict_data, c.dict_lengths)
             if not c.is_struct and c.dict_data is not None else None
             for c in cols]
    stripped = [c.replace(dict_data=None, dict_lengths=None)
                if d is not None else c for c, d in zip(cols, dicts)]
    # every array lane (incl. struct leaf lanes — DeviceColumn is a
    # pytree and struct children are pytree nodes) flattens into one
    # batched-take set; unflatten restores the column structure
    leaves, treedef = jax.tree_util.tree_flatten(list(stripped))
    taken = _batched_takes(leaves, idx)
    out = list(jax.tree_util.tree_unflatten(treedef, taken))
    for i, d in enumerate(dicts):
        if d is not None:
            out[i] = out[i].replace(dict_data=d[0], dict_lengths=d[1])
    if row_valid is not None:
        out = [_and_validity_deep(c, row_valid) for c in out]
    return list(out)


def gather(batch: ColumnarBatch, indices: jax.Array, num_rows: jax.Array,
           row_valid: Optional[jax.Array] = None) -> ColumnarBatch:
    cols = tuple(gather_columns(batch.columns, indices, row_valid))
    return ColumnarBatch(cols, jnp.asarray(num_rows, jnp.int32))


def compaction_indices(keep: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Map a keep-mask to (gather_indices, kept_count).

    Stable: kept rows retain relative order. Implemented as a two-operand
    key sort (drop flag, row index) — TPU scatters run ~40x slower than
    sorts+gathers (~240ms vs ~6ms per 4M rows on v5e), so the sort
    formulation beats the classic cumsum-scatter here.
    """
    cap = keep.shape[0]
    src = jnp.arange(cap, dtype=jnp.int32)
    _, indices = jax.lax.sort([(~keep).astype(jnp.uint8), src], num_keys=2)
    return indices, jnp.sum(keep.astype(jnp.int32))


def compact(batch: ColumnarBatch, keep: jax.Array) -> ColumnarBatch:
    """Remove rows where ``keep`` is False (cudf ``Table.filter``)."""
    keep = keep & batch.row_mask()
    indices, count = compaction_indices(keep)
    live = jnp.arange(batch.capacity, dtype=jnp.int32) < count
    return gather(batch, indices, count, live)


def concat_columns(cols: Sequence[DeviceColumn], counts: Sequence[jax.Array],
                   capacity: int) -> DeviceColumn:
    """Concatenate columns into one of ``capacity`` rows.

    Rows of piece i land at offset sum(counts[:i]); done with one scatter per
    piece. Counts are traced, so offsets are traced too.
    """
    first = cols[0]
    if any(not c.is_struct and c.dict_data is not None for c in cols):
        shared = (not first.is_struct and first.dict_data is not None
                  and all(c.dict_data is first.dict_data for c in cols))
        if shared:
            # all pieces share ONE dictionary object (sliced from one
            # batch, or pre-unified by dictenc.unify_dict_batches): the
            # codes concatenate like a plain int32 lane
            plain = [c.replace(dict_data=None, dict_lengths=None)
                     for c in cols]
            out = concat_columns(plain, counts, capacity)
            return out.replace(dict_data=first.dict_data,
                               dict_lengths=first.dict_lengths)
        # distinct per-piece dictionaries under tracing: decode (one
        # gather each) and concatenate the padded form — callers that can
        # run eagerly unify first and keep the encoding
        from ..dictenc import decode_column
        cols = [decode_column(c) if not c.is_struct else c for c in cols]
        first = cols[0]
    if first.is_struct:
        kids = tuple(
            concat_columns([c.data[j] for c in cols], counts, capacity)
            for j in range(len(first.data)))
        validity = jnp.zeros(capacity, bool)
        offset = jnp.asarray(0, jnp.int32)
        for col, n in zip(cols, counts):
            src = jnp.arange(col.capacity, dtype=jnp.int32)
            dest = jnp.where(src < n, src + offset, capacity)
            validity = validity.at[dest].set(col.validity, mode="drop")
            offset = offset + jnp.asarray(n, jnp.int32)
        return DeviceColumn(kids, validity, None, first.dtype)
    is_var = first.lengths is not None     # strings / arrays / maps
    if first.data.ndim > 1:
        data = jnp.zeros((capacity,) + first.data.shape[1:],
                         first.data.dtype)
    else:
        data = jnp.zeros(capacity, first.data.dtype)
    lengths = jnp.zeros(capacity, jnp.int32) if is_var else None
    data2 = None
    if first.data2 is not None:
        data2 = jnp.zeros((capacity,) + first.data2.shape[1:],
                          first.data2.dtype)
    validity = jnp.zeros(capacity, bool)
    offset = jnp.asarray(0, jnp.int32)
    for col, n in zip(cols, counts):
        cap_i = col.capacity
        src = jnp.arange(cap_i, dtype=jnp.int32)
        live = src < n
        dest = jnp.where(live, src + offset, capacity)
        data = data.at[dest].set(col.data, mode="drop")
        validity = validity.at[dest].set(col.validity, mode="drop")
        if is_var:
            lengths = lengths.at[dest].set(col.lengths, mode="drop")
        if data2 is not None:
            data2 = data2.at[dest].set(col.data2, mode="drop")
        offset = offset + jnp.asarray(n, jnp.int32)
    return DeviceColumn(data, validity, lengths, first.dtype, data2)


def concat_batches(batches: Sequence[ColumnarBatch], capacity: int) -> ColumnarBatch:
    """cudf ``Table.concatenate`` — the coalesce kernel."""
    counts = [b.num_rows for b in batches]
    ncols = batches[0].num_columns
    cols = tuple(
        concat_columns([b.columns[i] for b in batches], counts, capacity)
        for i in range(ncols))
    total = sum(jnp.asarray(c, jnp.int32) for c in counts)
    return ColumnarBatch(cols, jnp.asarray(total, jnp.int32))


def slice_batch(batch: ColumnarBatch, start: jax.Array, count: jax.Array,
                capacity: Optional[int] = None) -> ColumnarBatch:
    """Rows [start, start+count) as a new batch (cudf Table slice)."""
    cap = capacity or batch.capacity
    idx = jnp.arange(cap, dtype=jnp.int32) + jnp.asarray(start, jnp.int32)
    n = jnp.minimum(jnp.asarray(count, jnp.int32),
                    jnp.maximum(batch.num_rows - start, 0))
    live = jnp.arange(cap, dtype=jnp.int32) < n
    return gather(batch, idx, n, live)


# ---------------------------------------------------------------------------
# Sort-key normalization (cudf orderBy contract)
# ---------------------------------------------------------------------------

def _float_orderable(x: jax.Array, bits) -> jax.Array:
    """IEEE754 total order as unsigned ints; NaN sorts greatest (Spark)."""
    u = x.view(bits.dtype)
    sign = bits.dtype.type(1) << (bits.dtype.itemsize * 8 - 1)
    flipped = jnp.where(u & sign != 0, ~u, u | sign)
    nan = jnp.isnan(x)
    return jnp.where(nan, ~bits.dtype.type(0), flipped)


def orderable_words(col: DeviceColumn) -> List[jax.Array]:
    """Normalize a column into unsigned arrays whose lexicographic order is
    the column's SQL ascending order. Strings produce several word operands."""
    d = col.dtype
    k = d.kind
    if k is TypeKind.STRUCT:
        raise TypeError("struct sort/partition keys have no device order "
                        "(planner tags them for CPU fallback)")
    if k is TypeKind.STRING and col.dict_data is not None:
        # dict-encoded strings: the dictionary is sorted by (bytes, length)
        # — dictenc.py invariant 2 — so the CODE is a complete orderable
        # word. One u32 lane through the sort instead of max_len/8 + 1.
        # Only valid within one column (codes from different dictionaries
        # are not comparable; cross-batch sites unify or decode first).
        return [col.data.astype(jnp.uint32)]
    if k is TypeKind.STRING:
        # big-endian packed padded bytes: byte-wise lexicographic == uint64
        # word-wise lexicographic; zero padding sorts shorter strings first,
        # matching UTF-8 byte order because 0x00 is below any content byte.
        cap, ml = col.data.shape
        words = []
        for w in range(0, ml, 8):
            chunk = col.data[:, w:w + 8]
            if chunk.shape[1] < 8:
                chunk = jnp.pad(chunk, ((0, 0), (0, 8 - chunk.shape[1])))
            word = jnp.zeros(cap, jnp.uint64)
            for b in range(8):
                word = (word << jnp.uint64(8)) | chunk[:, b].astype(jnp.uint64)
            words.append(word)
        # length tiebreak: strings may legally CONTAIN 0x00 bytes, which the
        # zero padding would otherwise make indistinguishable from absent
        # bytes ("a" vs "a\x00"); byte-wise order puts the shorter first
        words.append(col.lengths.astype(jnp.uint64))
        return words
    data = col.data
    if k is TypeKind.DECIMAL and d.precision > 18:
        from ..expressions.decimal128 import orderable_words128
        return orderable_words128(data)
    if k is TypeKind.BOOLEAN:
        return [data.astype(jnp.uint8)]
    if k in (TypeKind.FLOAT32,):
        return [_float_orderable(data, jnp.zeros((), jnp.uint32))]
    if k in (TypeKind.FLOAT64,):
        # NO f64→u64 bitcast: TPU emulates f64 (f32 pairs) and XLA's x64
        # rewriter cannot lower 64-bit bitcast_convert. Sort on a native
        # float operand instead, with a leading nan-flag word so NaN ranks
        # greatest (Spark total order). sort_operands negates float words
        # for descending order (bitwise NOT is uint-only).
        nan = jnp.isnan(data)
        return [nan.astype(jnp.uint8),
                jnp.where(nan, jnp.zeros((), data.dtype), data)]
    # integral / date / timestamp / decimal: flip the sign bit
    u = data.astype({1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32,
                     8: jnp.uint64}[data.dtype.itemsize])
    sign = u.dtype.type(1) << (u.dtype.itemsize * 8 - 1)
    return [u ^ sign]


def may_skip_null_lane(expr) -> bool:
    """True when a sort key expression PROVABLY never yields null rows, so
    its null-rank operand can be dropped. Only a direct reference to a
    schema-non-nullable column qualifies: computed expressions may
    produce runtime nulls (divide-by-zero, failed casts) whatever their
    static flag claims — those ops also override .nullable to True, but
    the restriction here is the defense in depth."""
    from ..expressions.base import BoundReference
    return isinstance(expr, BoundReference) and not expr.nullable


def sort_operands(cols: Sequence[DeviceColumn], descending: Sequence[bool],
                  nulls_first: Sequence[bool], live: jax.Array,
                  nullable: Optional[Sequence[bool]] = None
                  ) -> List[jax.Array]:
    """Build the lax.sort key operands for a multi-column sort.

    Dead rows (beyond num_rows) always sort last regardless of direction.
    ``nullable[i]=False`` (a schema-level guarantee) drops that column's
    null-rank operand — one fewer u8 lane through the whole sort.
    """
    ops: List[jax.Array] = [(~live).astype(jnp.uint8)]  # live rows first
    if nullable is None:
        nullable = [True] * len(cols)
    for col, desc, nf, nl in zip(cols, descending, nulls_first, nullable):
        if nl:
            null_rank = jnp.where(col.validity, jnp.uint8(1),
                                  jnp.uint8(0) if nf else jnp.uint8(2))
            ops.append(jnp.where(live, null_rank, jnp.uint8(3)))
        for w in orderable_words(col):
            if nl:
                # zero the word lanes of null rows: the rank lane already
                # dominates the ORDER; equal words make null==null rows
                # adjacent-EQUAL too, which the aggregate's word-level
                # group-boundary detection relies on
                w = jnp.where(col.validity, w, jnp.zeros((), w.dtype))
            if not desc:
                ops.append(w)
            elif jnp.issubdtype(w.dtype, jnp.floating):
                ops.append(-w)      # float words flip by negation
            else:
                ops.append(~w)
    return ops


def adjacent_equal_ops(ops: Sequence[jax.Array]) -> jax.Array:
    """eq[i] = position i matches position i-1 on EVERY operand; eq[0]=False.

    Word-level group-boundary detection over the SORTED key operands of
    ``sort_operands`` (null word lanes are zeroed there, so null==null holds
    without consulting validity). Avoids gathering the original key columns
    just to compare them.
    """
    cap = ops[0].shape[0]
    eq = jnp.ones(cap - 1, bool)
    for w in ops:
        eq = eq & (w[1:] == w[:-1])
    return jnp.concatenate([jnp.zeros(1, bool), eq])


def sort_permutation(batch: ColumnarBatch, key_cols: Sequence[DeviceColumn],
                     descending: Sequence[bool], nulls_first: Sequence[bool]
                     ) -> jax.Array:
    """Stable permutation ordering the batch by the given keys."""
    cap = batch.capacity
    live = batch.row_mask()
    ops = sort_operands(key_cols, descending, nulls_first, live)
    iota = jnp.arange(cap, dtype=jnp.int32)
    out = jax.lax.sort(ops + [iota], num_keys=len(ops) + 1)  # iota key => stable
    return out[-1]


# ---------------------------------------------------------------------------
# Group-key equality over sorted rows (aggregate/window boundary detection)
# ---------------------------------------------------------------------------

def adjacent_equal(cols: Sequence[DeviceColumn]) -> jax.Array:
    """eq[i] = row i has the same key (incl. null==null) as row i-1; eq[0]=False.

    Call on ALREADY SORTED/GATHERED key columns.
    """
    cap = cols[0].capacity
    eq = jnp.ones(cap, bool)
    for c in cols:
        if c.lengths is not None:
            same = jnp.all(c.data[1:] == c.data[:-1], axis=1) & \
                (c.lengths[1:] == c.lengths[:-1])
        elif c.data.ndim > 1:   # decimal128 limb matrices
            same = jnp.all(c.data[1:] == c.data[:-1], axis=1)
        else:
            same = c.data[1:] == c.data[:-1]
        vsame = c.validity[1:] == c.validity[:-1]
        # null==null counts equal; value comparison only if both valid
        pair = vsame & (same | ~c.validity[1:])
        eq = eq & jnp.concatenate([jnp.zeros(1, bool), pair])
    return eq
