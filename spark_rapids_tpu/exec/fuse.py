"""Whole-stage fusion: one XLA program for a linear device-resident subplan.

The XLA twin of Spark's whole-stage codegen, and the single-chip sibling of
``parallel/lowering.try_lower_to_mesh``. The reference pipelines operators as
JVM iterators over per-op JNI kernel launches (SURVEY.md §3.3); here a whole
scan→filter→join→aggregate/sort stage traces into ONE jitted program, so a
stage execution is ONE dispatch with NO host round trips (each costs a
~0.7 s tunnel RTT in this environment — docs/perf_r3.md).

Two-phase join output sizing (the reference sizes gather maps with a device
count read back by the host — GpuHashJoin.scala:811 JoinGatherer sizing)
becomes OPTIMISTIC static sizing: the fused program sizes the join output at
the stream-side capacity bucket times a planner hint, and emits an overflow
FLAG alongside the result instead of forcing a mid-stage sync. The runner
validates flags at its single materialization point and re-executes with a
larger bucket when the guess lost (rare: FK joins produce at most one match
per probe row). ANSI/capacity error counters ride the same flag vector.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..batch import ColumnarBatch, Schema, bucket_capacity
from ..expressions.base import EvalContext
from .base import Exec, LeafExec
from .basic import (FilterExec, InMemoryScanExec, LocalLimitExec,
                    ProjectExec, _raise_ansi)
from .common import compact, slice_batch
from .join import HashJoinExec, JoinType
from .sort import SortExec, TakeOrderedAndProjectExec, sort_batch

_FUSABLE_JOIN_TYPES = (JoinType.INNER, JoinType.LEFT_OUTER,
                       JoinType.LEFT_SEMI, JoinType.LEFT_ANTI,
                       JoinType.EXISTENCE)


class FusionUnsupported(Exception):
    pass


class _Planner:
    """Static walk: validate every node is fusable and collect the leaf
    scans (each must supply exactly ONE device-resident batch)."""

    def __init__(self):
        self.scans: List[InMemoryScanExec] = []

    def walk(self, node: Exec) -> None:
        if isinstance(node, InMemoryScanExec):
            batches = list(node._all_batches())
            if len(batches) != 1:
                raise FusionUnsupported("scan must yield exactly one batch")
            self.scans.append(node)
            return
        if isinstance(node, (ProjectExec, FilterExec, SortExec,
                             TakeOrderedAndProjectExec, LocalLimitExec)):
            self.walk(node.children[0])
            return
        if isinstance(node, HashJoinExec):
            if node.join_type not in _FUSABLE_JOIN_TYPES:
                raise FusionUnsupported(
                    f"join type {node.join_type} needs cross-batch state")
            self.walk(node.left)
            self.walk(node.right)
            return
        from .aggregate import AggregateMode, HashAggregateExec
        if isinstance(node, HashAggregateExec):
            if node.mode not in (AggregateMode.COMPLETE,
                                 AggregateMode.PARTIAL):
                raise FusionUnsupported("merge-mode agg joins batches")
            if node.sort_sensitive:
                raise FusionUnsupported("sort-sensitive aggregate")
            self.walk(node.children[0])
            return
        raise FusionUnsupported(f"{type(node).__name__} not fusable")


class FusedStage:
    """A compiled whole-stage program plus its staged inputs."""

    def __init__(self, plan: Exec, expand_factor: int = 1):
        self.plan = plan
        self.expand_factor = expand_factor
        planner = _Planner()
        planner.walk(plan)
        self.scans = planner.scans
        self.inputs = [next(iter(s._all_batches())) for s in self.scans]
        self._program = jax.jit(self._trace)

    # -- trace ---------------------------------------------------------

    def _trace(self, *batches: ColumnarBatch):
        by_scan: Dict[int, ColumnarBatch] = {
            id(s): b for s, b in zip(self.scans, batches)}
        # separate channels: ANSI/capacity error counters (raise as such)
        # vs join-bucket overflow (drives the exact-size retrace)
        self._err_kinds: List[str] = []
        self._err_vals: List[jax.Array] = []
        self._join_over: List[jax.Array] = []
        self._join_needs: List[jax.Array] = []
        out = self._emit(self.plan, by_scan, self._join_over)
        errs = (jnp.stack(self._err_vals) if self._err_vals
                else jnp.zeros(1, jnp.int64))
        over = (jnp.stack(self._join_over) if self._join_over
                else jnp.zeros(1, jnp.int64))
        needs = (jnp.stack(self._join_needs) if self._join_needs
                 else jnp.zeros(1, jnp.int64))
        return out, errs, over, needs

    def _emit(self, node: Exec, by_scan, flags) -> ColumnarBatch:
        if isinstance(node, InMemoryScanExec):
            return by_scan[id(node)]

        if isinstance(node, ProjectExec):
            b = self._emit(node.children[0], by_scan, flags)
            ctx = EvalContext(node.ctx.ansi, {})
            # raw_eval: identity projections keep dictionary-encoded
            # strings encoded through the fused stage (same contract as
            # the standalone ProjectExec kernel in basic.py)
            from ..expressions.base import raw_eval
            cols = tuple(raw_eval(e, b, ctx) for e in node.exprs)
            self._err_flags(ctx, flags)
            return ColumnarBatch(cols, b.num_rows)

        if isinstance(node, FilterExec):
            b = self._emit(node.children[0], by_scan, flags)
            ctx = EvalContext(node.ctx.ansi, {})
            c = node.condition.eval(b, ctx)
            self._err_flags(ctx, flags)
            return compact(b, c.data & c.validity)

        if isinstance(node, HashJoinExec):
            stream = self._emit(node.left, by_scan, flags)
            build = self._emit(node.right, by_scan, flags)
            return self._emit_join(node, stream, build, flags)

        if isinstance(node, SortExec):
            b = self._emit(node.children[0], by_scan, flags)
            return sort_batch(b, node.orders, node.ctx)

        if isinstance(node, TakeOrderedAndProjectExec):
            b = self._emit(node.children[0], by_scan, flags)
            s = sort_batch(b, node.orders, node.ctx)
            n = jnp.minimum(s.num_rows, jnp.int32(node.limit))
            cut = bucket_capacity(min(node.limit, b.capacity))
            out = slice_batch(s, jnp.int32(0), n, cut)
            if node.project:
                cols = tuple(e.eval(out, node.ctx) for e in node.project)
                out = ColumnarBatch(cols, out.num_rows)
            return out

        if isinstance(node, LocalLimitExec):
            b = self._emit(node.children[0], by_scan, flags)
            return slice_batch(b, jnp.int32(0), jnp.int32(node.limit))

        from .aggregate import AggregateMode, HashAggregateExec
        if isinstance(node, HashAggregateExec):
            b = self._emit(node.children[0], by_scan, flags)
            part = node._update_kernel(b)
            if node.mode is AggregateMode.COMPLETE:
                return node._merge_kernel(part, final=True)
            return part

        raise AssertionError(f"unplanned node {type(node).__name__}")

    def _emit_join(self, node: HashJoinExec, stream: ColumnarBatch,
                   build: ColumnarBatch, flags) -> ColumnarBatch:
        sorted_h, sbuild, _ = node._build_kernel(build)
        lo, counts, offsets, total = node._count_kernel(stream, sorted_h)
        out_cap = bucket_capacity(stream.capacity * self.expand_factor)
        matched = jnp.zeros(sbuild.capacity, bool)
        semi = node.join_type in (JoinType.LEFT_SEMI, JoinType.LEFT_ANTI,
                                  JoinType.EXISTENCE)
        # overflow: candidates that would not fit the optimistic bucket.
        # The needed/available ratio drives the single exact-size retrace.
        flags.append((total > out_cap).astype(jnp.int64))
        self._join_needs.append(
            ((total + out_cap - 1) // out_cap).astype(jnp.int64))
        if semi:
            return node._semi_kernel(stream, sbuild,
                                     (lo, counts, offsets), matched, out_cap)
        out, _ = node._expand_kernel(stream, sbuild,
                                     (lo, counts, offsets), matched, out_cap)
        return out

    def _err_flags(self, ctx: EvalContext, flags) -> None:
        for kind, v in ctx.errors.items():
            self._err_kinds.append(kind)
            self._err_vals.append(sum(v).astype(jnp.int64))

    # -- execution -----------------------------------------------------

    def prepare(self) -> Tuple[object, List[ColumnarBatch]]:
        """(jitted program, staged inputs) — for steady-state benching and
        callers that manage their own flag validation."""
        return self._program, self.inputs

    def run(self, max_retries: int = 3) -> ColumnarBatch:
        """Execute; validate flags at the single materialization sync.
        On join-bucket overflow the program's own needed/available ratios
        size ONE exact retrace (plus headroom for the pathological case
        where a bigger bucket uncovers more candidates downstream)."""
        stage = self
        for _ in range(max_retries):
            out, errs, over, needs = stage._program(*stage.inputs)
            ev = [int(x) for x in errs]
            if stage._err_kinds and any(ev):
                _raise_ansi(dict(zip(stage._err_kinds, ev)))
            if int(jnp.max(over)) == 0:
                return out
            grow = int(jnp.max(needs))
            factor = max(stage.expand_factor * max(grow, 2),
                         stage.expand_factor * 2)
            stage = FusedStage(self.plan, factor)
        raise RuntimeError("fused stage overflowed after retries; "
                           "join output exceeds retry buckets")


def try_fuse(plan: Exec, expand_factor: int = 1) -> Optional[FusedStage]:
    """Compile ``plan`` into one XLA program, or None if any node needs
    cross-batch state / host control flow."""
    try:
        return FusedStage(plan, expand_factor)
    except FusionUnsupported:
        return None


class FusedStageExec(LeafExec):
    """Planner wrapper: the fused program as a one-partition exec, so the
    session's collect path runs whole-stage programs transparently
    (Session.prepare wires this in under sql.fusion.enabled)."""

    def __init__(self, stage: FusedStage):
        super().__init__()
        self.stage = stage

    @property
    def name(self) -> str:
        return "FusedStageExec"

    @property
    def output_schema(self) -> Schema:
        return self.stage.plan.output_schema

    @property
    def num_partitions(self) -> int:
        return 1

    def do_execute_partition(self, p: int):
        yield self.stage.run()


def try_fuse_exec(plan: Exec) -> Optional[FusedStageExec]:
    stage = try_fuse(plan)
    return FusedStageExec(stage) if stage is not None else None
