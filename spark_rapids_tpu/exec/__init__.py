"""Physical operator layer — the analogue of the reference's Gpu*Exec nodes
(reference: sql-plugin/.../GpuExec.scala:211 base trait and the operator files
in SURVEY.md §2.5). Operators are pull-based iterators of ColumnarBatch; each
per-batch kernel is a jitted XLA computation compiled once per capacity bucket.
"""

from .base import Exec, LeafExec, UnaryExec, BinaryExec, Metric, collect
from .basic import (ProjectExec, FilterExec, RangeExec, UnionExec,
                    LocalLimitExec, GlobalLimitExec, SampleExec,
                    InMemoryScanExec, ExpandExec)
from .aggregate import HashAggregateExec, AggregateMode
from .sort import SortExec, SortOrder, TakeOrderedAndProjectExec
from .join import (HashJoinExec, BroadcastNestedLoopJoinExec, JoinType)
from .coalesce import CoalesceBatchesExec, TargetSize, RequireSingleBatch
from .generate import GenerateExec
from .key_batching import KeyBatchingExec

__all__ = [n for n in dir() if not n.startswith("_")]
