"""Sort and TopN.

Reference: sql-plugin/.../GpuSortExec.scala:83 (in-core), :246 (out-of-core
merge of spilled runs), SortUtils.scala GpuSorter; limit.scala
GpuTakeOrderedAndProjectExec.

TPU-native design: every sort key is normalized into rank-preserving unsigned
words (exec/common.sort_operands) and ONE multi-operand `lax.sort` orders any
schema — ints, floats (NaN greatest, Spark order), decimals, strings — in a
single fused XLA op, instead of cudf's orderBy dispatch. Global sort = local
sort per batch + device merge of runs (concat + one more sort; an N-way
priority-queue merge like the reference's OOC iterator arrives with spill).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator, List, Optional, Sequence

import jax
import jax.numpy as jnp

from ..batch import ColumnarBatch, Schema, bucket_capacity
from ..expressions.base import EvalContext, Expression
from .base import Exec, UnaryExec
from .basic import bind_all
from .common import concat_batches, gather, gather_column, slice_batch, \
    sort_operands


@dataclass(frozen=True)
class SortOrder:
    """A sort key: expression + direction + null ordering (Spark SortOrder).

    Spark defaults: ascending nulls first, descending nulls last.
    """

    child: Expression
    descending: bool = False
    nulls_first: Optional[bool] = None

    @property
    def effective_nulls_first(self) -> bool:
        if self.nulls_first is None:
            return not self.descending
        return self.nulls_first

    def bind(self, schema: Schema) -> "SortOrder":
        return SortOrder(self.child.bind(schema), self.descending,
                         self.nulls_first)


def asc(e: Expression) -> SortOrder:
    return SortOrder(e, False)


def desc(e: Expression) -> SortOrder:
    return SortOrder(e, True)


def sort_batch(batch: ColumnarBatch, orders: Sequence[SortOrder],
               ctx: EvalContext = EvalContext()) -> ColumnarBatch:
    """Stable in-core sort of one batch (jit-traceable)."""
    cap = batch.capacity
    live = batch.row_mask()
    key_cols = [o.child.eval(batch, ctx) for o in orders]
    ops = sort_operands(key_cols, [o.descending for o in orders],
                        [o.effective_nulls_first for o in orders], live)
    iota = jnp.arange(cap, dtype=jnp.int32)
    perm = jax.lax.sort(ops + [iota], num_keys=len(ops) + 1)[-1]
    return gather(batch, perm, batch.num_rows, live)


class SortExec(UnaryExec):
    def coalesce_goal_for_child(self, i):
        from .coalesce import TargetSize
        return TargetSize()

    @property
    def produces_single_batch(self):
        return self.global_sort

    def __init__(self, orders: Sequence[SortOrder], child: Exec,
                 global_sort: bool = True, ctx: Optional[EvalContext] = None,
                 max_rows: int = 1 << 22):
        super().__init__(child, ctx)
        self.orders = [o.bind(child.output_schema) for o in orders]
        self.global_sort = global_sort
        self.max_rows = max_rows
        self._sort_jit = jax.jit(lambda b: sort_batch(b, self.orders, self.ctx))

    @property
    def output_schema(self) -> Schema:
        return self.child.output_schema

    @property
    def num_partitions(self) -> int:
        return 1 if self.global_sort else self.child.num_partitions

    def do_execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        if not self.global_sort:
            for b in self.child.execute_partition(p):
                yield self._sort_jit(b)
            return
        # Global sort: accumulate input batches through the spill catalog so
        # the accumulation phase cannot blow the device budget (reference:
        # GpuOutOfCoreSortIterator spills pending batches; the final merge
        # still materializes the full result — OOC chunked merge is the
        # planned refinement). Registration AND the acquire-all merge run
        # under the OOM retry loop: a failed attempt unpins, spills, and
        # re-runs (the merge itself cannot split — the OOC path is the
        # bounded-memory fallback for oversized inputs).
        from ..memory import (acquire_with_retry, device_budget,
                              register_with_retry, with_retry_no_split)
        cat = device_budget()
        spillables = []
        schema = self.output_schema
        for cp in range(self.child.num_partitions):
            for b in self.child.execute_partition(cp):
                # registered handles start unpinned (spillable)
                spillables.append(register_with_retry(
                    b, schema, catalog=cat, name=self.name))
        if not spillables:
            return
        try:
            if len(spillables) == 1:
                yield self._sort_jit(acquire_with_retry(
                    spillables[0], name=self.name))
                spillables[0].done_with()
                return

            def acquire_all():
                got = []
                try:
                    for sb in spillables:
                        got.append(sb.get())
                except BaseException:
                    for i in range(len(got)):
                        spillables[i].done_with()
                    raise
                for sb in spillables:
                    sb.done_with()
                return got

            caps = with_retry_no_split(acquire_all, catalog=cat,
                                       name=self.name)
            total_cap = sum(b.capacity for b in caps)
            if total_cap > self.max_rows:
                # out-of-core chunked merge (reference: GpuOutOfCoreSort)
                from .ooc_sort import OutOfCoreSorter
                sorter = OutOfCoreSorter(self.orders, schema,
                                         device_budget())
                yield from sorter.sort(iter(caps))
                return
            merged = concat_batches(caps, bucket_capacity(total_cap))
            yield self._sort_jit(merged)
        finally:
            for sb in spillables:
                sb.close()


class TakeOrderedAndProjectExec(UnaryExec):
    """TopN: per-batch sort+limit, tournament across batches, final project
    (reference: GpuTakeOrderedAndProjectExec, GpuOverrides.scala:3735)."""

    def coalesce_goal_for_child(self, i):
        from .coalesce import TargetSize
        return TargetSize()

    @property
    def produces_single_batch(self):
        return True

    def __init__(self, limit: int, orders: Sequence[SortOrder],
                 project: Optional[Sequence[Expression]], child: Exec,
                 ctx: Optional[EvalContext] = None):
        super().__init__(child, ctx)
        self.limit = limit
        self.orders = [o.bind(child.output_schema) for o in orders]
        self.project = bind_all(project, child.output_schema) if project else None
        from .basic import schema_of
        self._schema = schema_of(self.project) if self.project \
            else child.output_schema

        def topn(b: ColumnarBatch) -> ColumnarBatch:
            s = sort_batch(b, self.orders, self.ctx)
            n = jnp.minimum(s.num_rows, jnp.int32(self.limit))
            cut = bucket_capacity(min(self.limit, b.capacity))
            return slice_batch(s, jnp.int32(0), n, cut)

        self._topn_jit = jax.jit(topn)

        def proj(b: ColumnarBatch) -> ColumnarBatch:
            cols = tuple(e.eval(b, self.ctx) for e in self.project)
            return ColumnarBatch(cols, b.num_rows)

        self._proj_jit = jax.jit(proj) if self.project else None

    @property
    def output_schema(self) -> Schema:
        return self._schema

    @property
    def num_partitions(self) -> int:
        return 1

    def do_execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        best: Optional[ColumnarBatch] = None
        for batch in self.child.execute():
            cand = self._topn_jit(batch)
            if best is None:
                best = cand
            else:
                cap = bucket_capacity(best.capacity + cand.capacity)
                best = self._topn_jit(concat_batches([best, cand], cap))
        if best is None:
            return
        yield self._proj_jit(best) if self._proj_jit else best
