"""Joins.

Reference: sql-plugin/.../execution/GpuHashJoin.scala:811 (gather-map hash
join core with BaseHashJoinIterator batched output sizing),
GpuShuffledHashJoinExec.scala:85, GpuBroadcastNestedLoopJoinExec.

TPU-native re-design (no cudf hash table, no dynamic shapes):
1. BUILD: hash the build keys to 64 bits and sort them — a sorted hash
   column IS the hash table (binary search replaces probing; sort and
   searchsorted are native XLA ops that tile well on TPU).
2. COUNT: probe rows binary-search the sorted hashes; candidate counts come
   from lower/upper bounds. One scalar (total candidates) syncs to the host
   to pick the output capacity bucket — the same two-phase sizing cudf's
   join gather-maps do (reference: join output sizing in JoinGatherer).
3. EXPAND: each output slot finds its (probe row, candidate ordinal) via
   searchsorted over the cumulative counts, gathers both sides, then
   VERIFIES real key equality (hash collisions are rejected here, so join
   results are exact, not probabilistic). Outer/semi/anti variants derive
   from verified per-row match counts — all in the same fused computation.

Null semantics: SQL equi-join keys never match NULL; null-keyed rows surface
only through outer sides. The optional non-equi ``condition`` is evaluated on
the candidate pair batch (the reference compiles an AST for this; here it is
just another traced expression).
"""

from __future__ import annotations

import enum
from typing import Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .. import types as T
from ..batch import ColumnarBatch, DeviceColumn, Field, Schema, bucket_capacity
from ..expressions.base import EvalContext, Expression
from ..expressions.hashing import murmur3_batch
from ..types import TypeKind
from .base import BinaryExec, Exec
from .basic import bind_all
from .common import compact, concat_batches, gather, gather_column


class JoinType(enum.Enum):
    INNER = "Inner"
    LEFT_OUTER = "LeftOuter"
    RIGHT_OUTER = "RightOuter"
    FULL_OUTER = "FullOuter"
    LEFT_SEMI = "LeftSemi"
    LEFT_ANTI = "LeftAnti"
    EXISTENCE = "Existence"   # left cols + exists flag (IN-subquery rewrite)
    CROSS = "Cross"


_PAIR_TYPES = (JoinType.INNER, JoinType.LEFT_OUTER, JoinType.RIGHT_OUTER,
               JoinType.FULL_OUTER)


def _hash64(cols: Sequence[DeviceColumn], valid: jnp.ndarray) -> jnp.ndarray:
    """32-bit row hash in a uint32 lane. 64-bit integers are EMULATED on
    TPU, which made the searchsorted probes ~3x slower; 32-bit collisions
    only create extra CANDIDATE pairs, and every candidate is verified by
    exact key comparison (_keys_equal), so a narrower hash trades a few
    false candidates for native-width searches. Invalid rows get the max
    value so they sort last and never collide with probe hashes that are
    themselves forced to a DIFFERENT sentinel (top bit cleared for real
    rows)."""
    h = murmur3_batch(cols, 42).view(jnp.uint32)
    h = h >> jnp.uint32(1)
    return jnp.where(valid, h, ~jnp.uint32(0))


def _keys_equal(a: List[DeviceColumn], b: List[DeviceColumn]) -> jnp.ndarray:
    eq = None
    for x, y in zip(a, b):
        if x.dict_data is not None or y.dict_data is not None:
            # the two sides carry DIFFERENT dictionaries (codes are not
            # comparable across columns) — verify on decoded bytes; the
            # decode gathers fuse into this kernel
            from ..dictenc import decode_column
            x, y = decode_column(x), decode_column(y)
        if x.lengths is not None:
            e = jnp.all(x.data == y.data, axis=1) & (x.lengths == y.lengths)
        elif x.data.ndim > 1:      # decimal128 limb matrices
            e = jnp.all(x.data == y.data, axis=1)
        else:
            e = x.data == y.data
        e = e & x.validity & y.validity
        eq = e if eq is None else eq & e
    return eq


from functools import partial  # noqa: E402


@partial(jax.jit, static_argnums=3)
def _slice_tile(build, off, count, cap):
    from .common import slice_batch
    return slice_batch(build, off, count, cap)


def _null_gather(batch: ColumnarBatch, out_cap: int) -> List[DeviceColumn]:
    """All-null columns shaped like ``batch`` at out_cap (outer padding)."""
    zero_idx = jnp.zeros(out_cap, jnp.int32)
    none = jnp.zeros(out_cap, bool)
    return [gather_column(c, zero_idx, none) for c in batch.columns]


class HashJoinExec(BinaryExec):
    """Equi-join; left child streams, right child builds (the planner swaps
    children to put the smaller side on the right, like the reference's
    build-side selection in GpuShuffledHashJoinExec)."""

    def coalesce_goal_for_child(self, i):
        # stream side wants sized batches; the build side is concatenated
        # whole (RequireSingleBatch — reference: GpuShuffledHashJoinExec
        # build-side single-batch contract)
        from .coalesce import RequireSingleBatch, TargetSize
        return TargetSize() if i == 0 else RequireSingleBatch()

    def __init__(self, left_keys: Sequence[Expression],
                 right_keys: Sequence[Expression], join_type: JoinType,
                 left: Exec, right: Exec,
                 condition: Optional[Expression] = None,
                 broadcast_build: bool = True,
                 ctx: Optional[EvalContext] = None,
                 max_build_rows: int = 1 << 22,
                 skew_split_rows: Optional[int] = None,
                 broadcast_switch_rows: Optional[int] = None):
        super().__init__(left, right, ctx)
        # AQE runtime broadcast switch: in the co-partitioned mode, a
        # build side that MEASURES at or under this many rows after its
        # shuffle materializes is replicated to every stream partition
        # instead of co-partition-probed (the planner's byte estimate
        # said shuffle; the measured rows say broadcast). None = off.
        # do_close restores the planned mode so a re-execute re-decides
        # from fresh statistics.
        self.broadcast_switch_rows = broadcast_switch_rows
        self._planned_broadcast = broadcast_build
        # AQE skew-join: in the co-partitioned mode, a stream-side reader
        # partition larger than this is split, replicating the matching
        # build partition (reference: OptimizeSkewedJoin /
        # GpuCustomShuffleReaderExec PartialReducerPartitionSpec). None =
        # off. Coordination also keeps adaptive partition-coalescing
        # CONSISTENT across the two exchanges — see _maybe_coordinate.
        self.skew_split_rows = skew_split_rows
        self._coordinated = False
        # Build relation materialized ONCE for a runtime broadcast
        # switch: the switched-to build side is a ShuffleExchangeExec
        # whose spillable pieces are freed after their single
        # refcounted read, so re-reading it per stream partition would
        # hit closed pieces. A PLANNED broadcast reads a
        # BroadcastExchangeExec, which is multi-read safe, and keeps
        # its per-read spill discipline (no caching there).
        self._switch_build_cache: Optional[List[ColumnarBatch]] = None
        # broadcast_build: build side replicated (broadcast hash join).
        # False = co-partitioned inputs (shuffled hash join); requires both
        # children hash-partitioned on the join keys by an exchange.
        self.broadcast_build = broadcast_build
        # Oversized-build sub-partitioning (reference: GpuHashJoin.scala:811
        # build-side sub-partitioning in GpuShuffledHashJoinExec): when the
        # build side exceeds this row budget, grace-hash split BOTH sides
        # into murmur3(key) % S buckets and join bucket-by-bucket — every
        # join type stays correct because equal keys land in the same
        # bucket and each build/stream row lands in exactly one.
        self.max_build_rows = max_build_rows
        if join_type is JoinType.CROSS:
            raise ValueError("use BroadcastNestedLoopJoinExec for cross joins")
        self.join_type = join_type
        self.left_keys = bind_all(left_keys, left.output_schema)
        self.right_keys = bind_all(right_keys, right.output_schema)
        for lk, rk in zip(self.left_keys, self.right_keys):
            if lk.dtype != rk.dtype:
                raise TypeError(f"join key type mismatch {lk.dtype} vs "
                                f"{rk.dtype}; planner must insert casts")

        lf, rf = left.output_schema.fields, right.output_schema.fields
        l_nullable = join_type in (JoinType.RIGHT_OUTER, JoinType.FULL_OUTER)
        r_nullable = join_type in (JoinType.LEFT_OUTER, JoinType.FULL_OUTER)
        if join_type in (JoinType.LEFT_SEMI, JoinType.LEFT_ANTI):
            self._schema = left.output_schema
        elif join_type is JoinType.EXISTENCE:
            self._schema = Schema(list(lf) + [Field("exists", T.BOOLEAN,
                                                    False)])
        else:
            self._schema = Schema(
                [Field(f.name, f.dtype, f.nullable or l_nullable) for f in lf]
                + [Field(f.name, f.dtype, f.nullable or r_nullable) for f in rf])
        self.condition = condition.bind(self._pair_schema()) if condition else None

        # single fixed-width key: probe the key's orderable word EXACTLY
        # (sorted keys ARE the hash table; zero false candidates, so the
        # optimistic fused-output bucket never overflows on FK joins).
        # Multi-key / float / string keys keep the 32-bit hash probe with
        # equality verification.
        _EXACT_KINDS = (TypeKind.INT8, TypeKind.INT16, TypeKind.INT32,
                        TypeKind.INT64, TypeKind.DATE, TypeKind.TIMESTAMP,
                        TypeKind.BOOLEAN)
        self._exact_probe = (
            len(self.right_keys) == 1
            and self.right_keys[0].dtype.kind in _EXACT_KINDS)

        self._build_jit = jax.jit(self._build_kernel)
        self._count_jit = jax.jit(self._count_kernel)
        self._expand_jit = jax.jit(self._expand_kernel, static_argnums=(4,))
        self._semi_jit = jax.jit(self._semi_kernel, static_argnums=(4,))

    def _probe_words(self, keys, valid, build_side: bool) -> jnp.ndarray:
        """The sorted/probed search key: exact orderable word (single
        fixed-width key) or verified 32-bit hash."""
        if self._exact_probe:
            from .common import orderable_words
            w = orderable_words(keys[0])[0]
            if build_side:
                # dead/invalid build rows take the MAX word so they sort
                # last; the validity tie-break in _build_kernel puts real
                # max-key rows BEFORE them, and _count_kernel clamps
                # search bounds by the live count — so padding can never
                # inflate candidate counts (a padded dim batch otherwise
                # makes every key-0 probe match the whole dead tail)
                return jnp.where(valid, w, ~jnp.zeros((), w.dtype))
            return w
        h = _hash64(keys, valid)
        if not build_side:
            return jnp.where(valid, h, ~jnp.uint32(0) - 1)
        return h

    def _pair_schema(self) -> Schema:
        return Schema(list(self.left.output_schema.fields)
                      + list(self.right.output_schema.fields))

    @property
    def output_schema(self) -> Schema:
        return self._schema

    # ------------------------------------------------------------------

    def _build_kernel(self, build: ColumnarBatch):
        """Sort the build side by probe word and MATERIALIZE it in that
        order. Expansion then gathers build columns directly at sorted
        positions — no perm indirection per probe batch (a 1M-row index
        gather costs ~7 ms on this chip; the build-side gather here is
        paid once and amortizes over every probe batch)."""
        keys = [e.eval(build, self.ctx) for e in self.right_keys]
        live = build.row_mask()
        valid = live
        for k in keys:
            valid = valid & k.validity
        h = self._probe_words(keys, valid, build_side=True)
        iota = jnp.arange(build.capacity, dtype=jnp.int32)
        # three-way rank tie-break: valid-keyed rows sort by word first,
        # then LIVE null-keyed rows (outer tails still need them), then
        # dead padding — so live rows stay a prefix in sorted order
        rank = jnp.where(valid, 0, jnp.where(live, 1, 2)).astype(jnp.uint8)
        sorted_h, _, perm = jax.lax.sort([h, rank, iota], num_keys=2)
        n_valid = jnp.sum(valid.astype(jnp.int32)).astype(jnp.int32)
        from .common import gather_columns
        sorted_live = iota < build.num_rows
        sorted_cols = gather_columns(list(build.columns), perm, sorted_live)
        sorted_build = ColumnarBatch(tuple(sorted_cols), build.num_rows)
        # per-position run length of the word STARTING at that position:
        # candidate count for a probe that lands on a run start. Replaces
        # the probe-side side="right" searchsorted (a second concat-sort
        # of the whole stream, ~40 ms per 4M probes) with build-side work
        # that amortizes over every probe batch.
        prev_ne = jnp.concatenate(
            [jnp.ones(1, bool), sorted_h[1:] != sorted_h[:-1]])
        gid = jnp.cumsum(prev_ne.astype(jnp.int32)) - 1
        run_start = jax.ops.segment_min(
            iota, gid, num_segments=build.capacity, indices_are_sorted=True)
        nxt = jnp.concatenate(
            [run_start[1:], jnp.full(1, 0, jnp.int32)])
        n_runs = gid[-1] + 1
        run_len_g = jnp.where(
            jnp.arange(build.capacity, dtype=jnp.int32) < n_runs - 1,
            nxt - run_start, build.capacity - run_start)
        runlen = jnp.take(run_len_g, gid).astype(jnp.int32)
        # clamp runs that spill into the dead tail ([n_valid, cap))
        runlen = jnp.minimum(runlen, jnp.maximum(n_valid - iota, 0))
        # dense-unique detection (exact-probe only): dimension PKs are
        # typically a contiguous range, making the probe a DIRECT index —
        # no searchsorted at all (reference: cudf builds a hash table; a
        # contiguous sorted build IS a perfect hash). Uniqueness is part
        # of the predicate: span == n-1 alone holds for {0,2,2}, where a
        # direct landing would hit mid-run and miss candidates.
        last = jnp.take(sorted_h, jnp.maximum(n_valid - 1, 0))
        first = sorted_h[0]
        valid_runs = jnp.take(gid, jnp.maximum(n_valid - 1, 0)) + 1
        dense = (n_valid > 0) & (valid_runs == n_valid) & \
            ((last - first) == (n_valid - 1).astype(sorted_h.dtype))
        return (sorted_h, n_valid, runlen, first, dense), sorted_build, valid

    def _count_kernel(self, stream: ColumnarBatch, sorted_h):
        keys = [e.eval(stream, self.ctx) for e in self.left_keys]
        live = stream.row_mask()
        valid = live
        for k in keys:
            valid = valid & k.validity
        # hash path: probe sentinel 0xFFFFFFFE ≠ build null sentinel
        # 0xFFFFFFFF, both outside the >>1 hash range, so null/dead
        # probes find nothing. Exact path: no sentinel — counts are only
        # taken where `valid` (below), and a wrong-landing probe fails the
        # word-equality check.
        h = self._probe_words(keys, valid, build_side=False)
        sorted_words, n_valid, runlen, first, dense = sorted_h

        def dense_path():
            # unique contiguous build (a dimension PK): position is
            # (key - first) and presence is a RANGE test — the whole probe
            # is elementwise, zero gathers, zero searches
            off = h - first
            in_r = (h >= first) & (off < n_valid.astype(h.dtype))
            lo = jnp.where(in_r, off, 0).astype(jnp.int32)
            counts = jnp.where(valid & in_r, 1, 0).astype(jnp.int32)
            return lo, counts

        def general_path():
            # method="sort": one concat-sort instead of a serialized
            # binary search (log-n dependent gather rounds) — measured
            # 5.2x faster at 4M probes on v5e. The old side="right"
            # second search is a build-side run-length gather now.
            lo = jnp.minimum(
                jnp.searchsorted(sorted_words, h, side="left",
                                 method="sort").astype(jnp.int32),
                n_valid)
            word_at = jnp.take(sorted_words,
                               jnp.clip(lo, 0, runlen.shape[0] - 1))
            hit = (word_at == h) & (lo < n_valid)
            counts = jnp.where(valid & hit,
                               jnp.take(runlen, lo), 0).astype(jnp.int32)
            return lo, counts
        lo, counts = jax.lax.cond(dense, dense_path, general_path) \
            if self._exact_probe else general_path()
        offsets = jnp.cumsum(counts)
        # int32 offsets keep the searches native-width; the 64-bit total
        # lets the host detect candidate counts that would wrap them
        total64 = jnp.sum(counts.astype(jnp.int64))
        return lo, counts, offsets, total64

    def _side_gather(self, batch, keys, idx, ok, need_keys: bool,
                     subst=None):
        """ONE batched gather per side (docs/perf_r3.md — sibling gathers
        don't fuse; stacked row-gathers are width-flat). Key columns that
        are plain references reuse the already-gathered output column
        instead of adding a duplicate gather lane; on the exact-probe path
        keys aren't gathered at all (word equality IS key equality).
        ``subst`` maps an output ordinal to a pre-known column (the build
        key equals the probe key on exact matches — no gather needed)."""
        from ..expressions.base import BoundReference
        from .common import gather_columns
        subst = subst or {}
        cols = list(batch.columns)
        gathered_idx = [i for i in range(len(cols)) if i not in subst]
        extra, key_src = [], []
        if need_keys:
            for e in keys:
                if isinstance(e, BoundReference) and e.ordinal not in subst:
                    key_src.append(("col", e.ordinal))
                else:
                    key_src.append(("extra", len(extra)))
                    extra.append(e.eval(batch, self.ctx))
        g = gather_columns([cols[i] for i in gathered_idx] + extra, idx, ok)
        out_cols: List[Optional[DeviceColumn]] = [None] * len(cols)
        for j, i in enumerate(gathered_idx):
            out_cols[i] = g[j]
        for i, c in subst.items():
            out_cols[i] = c
        key_cols = [out_cols[i] if kind == "col"
                    else g[len(gathered_idx) + i]
                    for (kind, i) in key_src]
        return out_cols, key_cols

    def _exact_subst(self, key_col_at_pairs, pair_ok):
        """Exact-probe: the build key column's output values equal the
        probe key values on every surviving slot, so substitute instead of
        gathering (kills the build side's whole i32 gather group for a
        typical star-schema dim). Returns {build ordinal: column} or {}."""
        from ..expressions.base import BoundReference
        rk = self.right_keys[0] if self._exact_probe else None
        if not isinstance(rk, BoundReference) or key_col_at_pairs is None:
            return {}
        return {rk.ordinal: key_col_at_pairs.replace(
            validity=key_col_at_pairs.validity & pair_ok)}

    def _gather_pairs(self, stream, build, lo, counts, offsets, out_cap):
        """Candidate pair gather + key verification (+ condition).
        ``build`` is the build-kernel's SORTED build batch, so candidate
        positions index it directly (no perm indirection)."""
        j = jnp.arange(out_cap, dtype=jnp.int32)
        total = offsets[-1]
        probe_row = jnp.searchsorted(offsets, j, side="right",
                                     method="sort").astype(jnp.int32)
        probe_row = jnp.clip(probe_row, 0, stream.capacity - 1)
        start = jnp.take(offsets, probe_row) - jnp.take(counts, probe_row)
        ordinal = j - start
        build_row = jnp.take(lo, probe_row) + ordinal
        build_row = jnp.clip(build_row, 0, build.capacity - 1).astype(jnp.int32)
        in_range = j < total

        # exact-probe candidates already matched on the full key word, so
        # no key re-gather or equality verification is needed; the hash
        # path gathers keys and rejects collisions here
        need_keys = not self._exact_probe
        s_cols, s_keys = self._side_gather(stream, self.left_keys,
                                           probe_row, in_range, need_keys)
        from ..expressions.base import BoundReference
        lk = self.left_keys[0]
        key_at_pairs = s_cols[lk.ordinal] \
            if self._exact_probe and isinstance(lk, BoundReference) else None
        b_cols, b_keys = self._side_gather(
            build, self.right_keys, build_row, in_range, need_keys,
            self._exact_subst(key_at_pairs, in_range))
        pair_ok = in_range if self._exact_probe \
            else in_range & _keys_equal(s_keys, b_keys)
        if self.condition is not None:
            pair_batch = ColumnarBatch(tuple(s_cols + b_cols), total)
            c = self.condition.eval(pair_batch, self.ctx)
            pair_ok = pair_ok & c.data & c.validity
        return s_cols, b_cols, pair_ok, probe_row, build_row

    def _expand_kernel(self, stream, build, lo_counts, matched_build_in,
                       out_cap: int):
        lo, counts, offsets = lo_counts
        # FK fast path (the overwhelmingly common star-schema shape):
        # when every probe has AT MOST ONE candidate, the expansion is a
        # 1:1 row mapping — no cumulative-offset search, no out_cap-wide
        # pair gathers, no pair compaction. Selected per batch by
        # lax.cond; both branches produce the same [out_cap] layout.
        if self.condition is None and \
                self.join_type in (JoinType.INNER, JoinType.LEFT_OUTER) \
                and out_cap >= stream.capacity:
            unique = jnp.max(counts) <= 1
            return jax.lax.cond(
                unique,
                lambda: self._expand_unique(stream, build, lo,
                                            counts, matched_build_in,
                                            out_cap),
                lambda: self._expand_general(stream, build, lo,
                                             counts, offsets,
                                             matched_build_in, out_cap))
        return self._expand_general(stream, build, lo, counts,
                                    offsets, matched_build_in, out_cap)

    def _unique_probe_cols(self, stream, build, lo, counts):
        """Shared <=1-match-per-probe verification: gather build columns
        1:1 at stream layout and compute the verified pair mask (exact
        path: word equality IS key equality + key substitution; hash
        path: gather keys and reject collisions)."""
        matched = counts > 0
        build_row = jnp.clip(lo, 0, build.capacity - 1)
        if self._exact_probe:
            pair_ok = matched & stream.row_mask()
            from ..expressions.base import BoundReference
            key_col = self.left_keys[0].eval(stream, self.ctx) \
                if isinstance(self.right_keys[0], BoundReference) else None
            b_cols, _ = self._side_gather(
                build, self.right_keys, build_row, matched, False,
                self._exact_subst(key_col, pair_ok))
        else:
            b_cols, b_keys = self._side_gather(build, self.right_keys,
                                               build_row, matched, True)
            s_keys = [e.eval(stream, self.ctx) for e in self.left_keys]
            pair_ok = matched & stream.row_mask() & \
                _keys_equal(s_keys, b_keys)
        return b_cols, pair_ok

    def _expand_unique(self, stream, build, lo, counts,
                       matched_build_in, out_cap: int):
        """<=1 match per probe: direct row mapping at stream capacity."""
        b_cols, pair_ok = self._unique_probe_cols(stream, build, lo, counts)
        # only RIGHT/FULL outer consume build-match state, and this path
        # serves INNER/LEFT only — skip the scatter
        matched_build = matched_build_in
        if self.join_type is JoinType.LEFT_OUTER:
            # every stream row survives; unmatched rows take null builds.
            # Pad to the general path's post-concat capacity so lax.cond
            # sees identical output types.
            b_cols = [c.replace(validity=c.validity & pair_ok)
                      for c in b_cols]
            out = ColumnarBatch(stream.columns + tuple(b_cols),
                                stream.num_rows)
            target = bucket_capacity(out_cap + stream.capacity)
        else:
            out = compact(ColumnarBatch(stream.columns + tuple(b_cols),
                                        stream.num_rows), pair_ok)
            target = out_cap
        return self._pad_batch(out, target), matched_build

    @staticmethod
    def _pad_batch(batch: ColumnarBatch, cap: int) -> ColumnarBatch:
        if batch.capacity == cap:
            return batch
        from .aggregate import _pad_column
        return ColumnarBatch(
            tuple(_pad_column(c, cap) for c in batch.columns),
            batch.num_rows)

    def _expand_general(self, stream, build, lo,
                        counts, offsets, matched_build_in, out_cap: int):
        s_cols, b_cols, pair_ok, probe_row, build_row = self._gather_pairs(
            stream, build, lo, counts, offsets, out_cap)

        # compact verified pairs to the front
        pairs = compact(ColumnarBatch(tuple(s_cols + b_cols),
                                      jnp.asarray(out_cap, jnp.int32)),
                        pair_ok)

        # per-stream-row verified match count (probe_row ascending)
        seg = jnp.where(pair_ok, probe_row, stream.capacity)
        stream_matches = jax.ops.segment_sum(
            pair_ok.astype(jnp.int32), seg, num_segments=stream.capacity + 1,
            indices_are_sorted=True)[: stream.capacity]
        if self.join_type in (JoinType.RIGHT_OUTER, JoinType.FULL_OUTER):
            matched_build = matched_build_in.at[
                jnp.where(pair_ok, build_row, build.capacity)].set(
                True, mode="drop")
        else:
            matched_build = matched_build_in

        if self.join_type in (JoinType.LEFT_OUTER, JoinType.FULL_OUTER):
            unmatched = stream.row_mask() & (stream_matches == 0)
            u_cols = list(stream.columns) + _null_gather(build, stream.capacity)
            u_batch = compact(ColumnarBatch(
                tuple(u_cols), stream.num_rows), unmatched)
            out = concat_batches([pairs, u_batch],
                                 bucket_capacity(out_cap + stream.capacity))
        else:
            out = pairs
        return out, matched_build

    def _expand_masked(self, stream, build, lo, counts, offsets,
                       out_cap: int):
        """INNER-join expansion WITHOUT the compaction pass: the pair
        batch at out_cap slots (num_rows == capacity) plus a live-pair
        mask, for consumers that tolerate interleaved dead rows — a
        downstream aggregation key-sorts anyway, so fused join→agg skips
        an entire compact (cumsum + scatter + per-column gathers).
        Reference analogue: AST-fused filter feeding cudf groupby."""
        assert self.join_type is JoinType.INNER

        def unique_fn():
            b_cols, pair_ok = self._unique_probe_cols(stream, build, lo,
                                                      counts)
            if self.condition is not None:
                pb = ColumnarBatch(stream.columns + tuple(b_cols),
                                   stream.num_rows)
                c = self.condition.eval(pb, self.ctx)
                pair_ok = pair_ok & c.data & c.validity
            out = self._pad_batch(
                ColumnarBatch(stream.columns + tuple(b_cols),
                              jnp.asarray(stream.capacity, jnp.int32)),
                out_cap)
            mask = jnp.pad(pair_ok, (0, out_cap - stream.capacity))
            return out, mask

        def general_fn():
            s_cols, b_cols, pair_ok, _, _ = self._gather_pairs(
                stream, build, lo, counts, offsets, out_cap)
            return ColumnarBatch(tuple(s_cols + b_cols),
                                 jnp.asarray(out_cap, jnp.int32)), pair_ok

        if out_cap >= stream.capacity:
            unique = jnp.max(counts) <= 1
            return jax.lax.cond(unique, unique_fn, general_fn)
        return general_fn()

    def _semi_kernel(self, stream, build, lo_counts, matched_build_in,
                     out_cap: int):
        lo, counts, offsets = lo_counts
        if self._exact_probe and self.condition is None:
            # candidate counts ARE verified match counts on the exact
            # path: no pair expansion at all
            stream_matches = counts
        else:
            _, _, pair_ok, probe_row, _ = self._gather_pairs(
                stream, build, lo, counts, offsets, out_cap)
            seg = jnp.where(pair_ok, probe_row, stream.capacity)
            stream_matches = jax.ops.segment_sum(
                pair_ok.astype(jnp.int32), seg,
                num_segments=stream.capacity + 1,
                indices_are_sorted=True)[: stream.capacity]
        if self.join_type is JoinType.LEFT_SEMI:
            keep = stream_matches > 0
        elif self.join_type is JoinType.LEFT_ANTI:
            keep = stream.row_mask() & (stream_matches == 0)
        else:   # EXISTENCE: no filtering, append the flag column
            exists = DeviceColumn((stream_matches > 0), stream.row_mask(),
                                  None, T.BOOLEAN)
            return ColumnarBatch(stream.columns + (exists,),
                                 stream.num_rows)
        return compact(stream, keep)

    def left_child_placeholder(self) -> ColumnarBatch:
        # a zero-row batch shaped like the left child, for null padding
        from ..batch import empty_batch
        return empty_batch(self.left.output_schema, 1)

    # ------------------------------------------------------------------

    def _maybe_coordinate(self) -> None:
        """Co-partitioned mode over two shuffle exchanges: plan BOTH
        reader layouts jointly (coalesce on combined stats + skew split).
        Without this, each adaptive exchange would coalesce by its own row
        counts and reader partition p would hold different keys on the two
        sides."""
        if self.broadcast_build or self._coordinated:
            return
        self._coordinated = True
        from ..shuffle.exchange import (ShuffleExchangeExec,
                                        coordinate_join_reads)
        l, r = self.left, self.right
        if not (isinstance(l, ShuffleExchangeExec) and
                isinstance(r, ShuffleExchangeExec)):
            return
        if self._maybe_broadcast_switch(r):
            return
        if not (l.adaptive or r.adaptive or self.skew_split_rows):
            return
        split = self.skew_split_rows
        if self.join_type in (JoinType.RIGHT_OUTER, JoinType.FULL_OUTER):
            # per-partition build tails stay correct only while each build
            # row is probed in exactly one reader partition
            split = None
        coordinate_join_reads(l, r, l.target_rows, split)

    def _maybe_broadcast_switch(self, build_ex) -> bool:
        """Runtime shuffled->broadcast switch: the build exchange has
        materialized (or is about to — reading its row counts forces
        it), so compare MEASURED build rows against the conf'd ceiling
        and replicate a small build instead of co-partition-probing it.
        Restricted to join types without build-side null tails
        (RIGHT/FULL outer fold to one partition under broadcast and are
        not worth re-planning into that shape at runtime). Bit-for-bit:
        a replicated build probes the same pairs per stream partition
        as the co-partitioned layout probes across partitions."""
        if self.broadcast_switch_rows is None or \
                self.join_type in (JoinType.RIGHT_OUTER,
                                   JoinType.FULL_OUTER):
            return False
        build_rows = sum(build_ex.partition_row_counts())
        if build_rows > self.broadcast_switch_rows:
            return False
        from ..plan.adaptive import record_decision
        record_decision(
            "broadcastSwitch",
            f"shuffled {self.join_type.name} join: build side measured "
            f"{build_rows} rows <= maxBuildRows="
            f"{self.broadcast_switch_rows} -> replicating build "
            f"(runtime broadcast)")
        self.broadcast_build = True
        return True

    def do_close(self) -> None:
        # the exchanges drop their materialization + reader specs on
        # close; a re-execute must re-coordinate or the two sides would
        # fall back to inconsistent solo layouts — and a runtime
        # broadcast switch must re-decide from fresh statistics
        self._coordinated = False
        self.broadcast_build = self._planned_broadcast
        self._switch_build_cache = None

    @property
    def num_partitions(self) -> int:
        self._maybe_coordinate()
        # With a replicated build side, RIGHT/FULL outer needs GLOBAL
        # matched-build state: a per-partition tail would both duplicate
        # unmatched build rows (once per stream partition) and null-pad
        # build rows matched in a different partition. Fold every stream
        # partition into one so the tail is emitted exactly once. The
        # co-partitioned (shuffled) path keeps per-partition tails — each
        # build row lives in exactly one partition there.
        if (self.broadcast_build and
                self.join_type in (JoinType.RIGHT_OUTER, JoinType.FULL_OUTER)):
            return 1
        return self.left.num_partitions

    def do_execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        self._maybe_coordinate()
        if self.broadcast_build and not self._planned_broadcast:
            # Runtime switch: the build side is still a shuffle
            # exchange — read the whole relation exactly once (its
            # pieces close after their refcounted read) and reuse it
            # across stream partitions. Bounded: the switch only fires
            # at <= broadcastJoin.maxBuildRows measured rows. Partition
            # execution is sequential, so no synchronization needed.
            if self._switch_build_cache is None:
                self._switch_build_cache = [
                    b for cp in range(self.right.num_partitions)
                    for b in self.right.execute_partition(cp)]
            build_batches = self._switch_build_cache
        elif self.broadcast_build:
            build_batches = [b for cp in range(self.right.num_partitions)
                             for b in self.right.execute_partition(cp)]
        else:
            build_batches = list(self.right.execute_partition(p))
        if self.num_partitions == 1 and self.left.num_partitions > 1:
            stream_parts: Sequence[int] = range(self.left.num_partitions)
        else:
            stream_parts = (p,)
        stream_iter = (b for sp in stream_parts
                       for b in self.left.execute_partition(sp))

        build_rows = sum(int(b.num_rows) for b in build_batches)
        if build_rows > self.max_build_rows:
            yield from self._grace_join(build_batches, stream_iter)
        else:
            yield from self._probe(build_batches, stream_iter)

    def _probe(self, build_batches: List[ColumnarBatch],
               stream_iter: Iterator[ColumnarBatch]
               ) -> Iterator[ColumnarBatch]:
        """Core probe loop against ONE in-memory build table.

        Retry discipline: the build side is admitted to the spill catalog
        (SpillableColumnarBatch shape — held across the retry boundary as
        handles, not raw device arrays) and the concat+build runs under
        with_retry_no_split; each probe batch runs under with_retry with
        halving — a half-stream probes to the same pairs in the same
        stream-row order, so concatenated outputs are bit-for-bit."""
        from ..batch import empty_batch
        from ..memory import (SpillableInput, admit_all, device_budget,
                              split_input_halves, with_retry,
                              with_retry_no_split)
        cat = device_budget()
        build_schema = self.right.output_schema
        build_inputs = admit_all(build_batches, build_schema, cat,
                                 name=f"{self.name}.build")

        def build_body():
            got: List[ColumnarBatch] = []
            try:
                for binp in build_inputs:
                    got.append(binp.acquire())
                if not got:
                    build = empty_batch(build_schema)
                elif len(got) == 1:
                    build = got[0]
                else:
                    cap = bucket_capacity(sum(b.capacity for b in got))
                    build = concat_batches(got, cap)
                return self._build_jit(build)
            finally:
                for j in range(len(got)):
                    build_inputs[j].release()

        try:
            sorted_h, sbuild, _ = with_retry_no_split(
                build_body, catalog=cat, name=f"{self.name}.build")
        finally:
            for binp in build_inputs:
                binp.close()
        matched_build = jnp.zeros(sbuild.capacity, bool)

        semi = self.join_type in (JoinType.LEFT_SEMI, JoinType.LEFT_ANTI,
                                  JoinType.EXISTENCE)
        stream_schema = self.left.output_schema

        def probe_body(item: SpillableInput):
            b = item.acquire()
            try:
                lo, counts, offsets, total = self._count_jit(b, sorted_h)
                total_i = int(total)
                if total_i > (1 << 31) - 1:
                    raise RuntimeError(
                        f"join candidate explosion: {total_i} pairs in "
                        f"one probe batch exceeds the int32 offset range; "
                        f"reduce the batch size or pre-aggregate the "
                        f"build side")
                out_cap = bucket_capacity(max(total_i, 1))
                if semi:
                    return self._semi_jit(b, sbuild, (lo, counts, offsets),
                                          matched_build, out_cap), None
                return self._expand_jit(b, sbuild, (lo, counts, offsets),
                                        matched_build, out_cap)
            finally:
                item.release()

        for stream in stream_iter:
            inp = SpillableInput.admit(stream, stream_schema, cat,
                                       name=self.name)
            # adaptive skew seam: a stream batch the shuffle statistics
            # already measured over the skew row target pre-splits
            # through the same split-and-retry machinery instead of
            # OOMing its way down to size
            for out, mb in with_retry(inp, probe_body,
                                      split=split_input_halves,
                                      catalog=cat, name=self.name,
                                      presplit_rows=self.skew_split_rows):
                if mb is not None:
                    matched_build = mb
                yield out

        if self.join_type in (JoinType.RIGHT_OUTER, JoinType.FULL_OUTER):
            # matched state lives in SORTED build space; the tail reads
            # the sorted build batch (row order is not part of the
            # contract)
            unmatched = sbuild.row_mask() & ~matched_build
            null_left = _null_gather(self.left_child_placeholder(),
                                     sbuild.capacity)
            tail = ColumnarBatch(tuple(null_left) + sbuild.columns,
                                 sbuild.num_rows)
            yield compact(tail, unmatched)

    # ------------------------------------------------------------------
    # Grace-hash sub-partitioning (reference: GpuHashJoin.scala:811 /
    # GpuShuffledHashJoinExec oversized-build handling)
    # ------------------------------------------------------------------

    def _bucket_pids(self, batch: ColumnarBatch, keys, n_buckets: int):
        cols = [e.eval(batch, self.ctx) for e in keys]
        h = murmur3_batch(cols, 77)   # independent of the join's _hash64
        m = h % jnp.int32(n_buckets)
        return jnp.where(m < 0, m + n_buckets, m).astype(jnp.int32)

    def _grace_join(self, build_batches: List[ColumnarBatch],
                    stream_iter: Iterator[ColumnarBatch]
                    ) -> Iterator[ColumnarBatch]:
        """Split BOTH sides into murmur3(key) % S buckets, join each bucket
        pair independently with the normal probe loop. Stream buckets wait
        in the spill catalog, so peak device residency stays one bucket's
        build + one stream batch regardless of input size."""
        from ..memory import (SpillableBatch, acquire_with_retry,
                              device_budget, register_with_retry)
        cat = device_budget()
        build_rows = sum(int(b.num_rows) for b in build_batches)
        n_buckets = -(-build_rows // self.max_build_rows)

        split_build = jax.jit(
            lambda b, s: compact(
                b, self._bucket_pids(b, self.right_keys, n_buckets) == s),
            static_argnums=1)
        split_stream = jax.jit(
            lambda b, s: compact(
                b, self._bucket_pids(b, self.left_keys, n_buckets) == s),
            static_argnums=1)

        sub_builds: List[List[ColumnarBatch]] = [[] for _ in range(n_buckets)]
        for b in build_batches:
            for s in range(n_buckets):
                piece = split_build(b, s)
                if int(piece.num_rows) > 0:
                    sub_builds[s].append(piece)

        sub_stream: List[List[SpillableBatch]] = \
            [[] for _ in range(n_buckets)]
        stream_schema = self.left.output_schema
        for batch in stream_iter:
            for s in range(n_buckets):
                piece = split_stream(batch, s)
                if int(piece.num_rows) > 0:
                    sub_stream[s].append(register_with_retry(
                        piece, stream_schema, catalog=cat,
                        name=f"{self.name}.grace"))

        for s in range(n_buckets):
            def pieces(bucket=s):
                for sp in sub_stream[bucket]:
                    out = acquire_with_retry(sp, name=f"{self.name}.grace")
                    sp.done_with()
                    yield out
            try:
                yield from self._probe(sub_builds[s], pieces())
            finally:
                for sp in sub_stream[s]:
                    sp.close()


class BroadcastNestedLoopJoinExec(BinaryExec):
    """Cross / conditional nested-loop join (reference:
    GpuBroadcastNestedLoopJoinExec). Tiles the build side so each expansion
    stays inside a bounded capacity."""

    def coalesce_goal_for_child(self, i):
        # stream side wants sized batches; the build side is concatenated
        # whole (RequireSingleBatch — reference: GpuShuffledHashJoinExec
        # build-side single-batch contract)
        from .coalesce import RequireSingleBatch, TargetSize
        return TargetSize() if i == 0 else RequireSingleBatch()

    def __init__(self, join_type: JoinType, left: Exec, right: Exec,
                 condition: Optional[Expression] = None,
                 ctx: Optional[EvalContext] = None,
                 max_tile_rows: int = 1 << 20):
        super().__init__(left, right, ctx)
        self.join_type = join_type
        self.max_tile_rows = max_tile_rows
        lf, rf = left.output_schema.fields, right.output_schema.fields
        pair_schema = Schema(list(lf) + list(rf))
        l_nullable = join_type in (JoinType.RIGHT_OUTER, JoinType.FULL_OUTER)
        r_nullable = join_type in (JoinType.LEFT_OUTER, JoinType.FULL_OUTER)
        if join_type in (JoinType.LEFT_SEMI, JoinType.LEFT_ANTI):
            self._schema = left.output_schema
        elif join_type is JoinType.EXISTENCE:
            self._schema = Schema(list(lf) + [Field("exists", T.BOOLEAN,
                                                    False)])
        else:
            self._schema = Schema(
                [Field(f.name, f.dtype, f.nullable or l_nullable)
                 for f in lf] +
                [Field(f.name, f.dtype, f.nullable or r_nullable)
                 for f in rf])
        # the condition sees the (left, right) PAIR row, whatever the
        # join type projects out (reference: AST closures in
        # GpuBroadcastNestedLoopJoinExec conditional variants)
        self.condition = condition.bind(pair_schema) if condition else None
        self._cross_jit = jax.jit(self._cross_kernel)
        self._count_jit = jax.jit(self._count_kernel)

    @property
    def output_schema(self) -> Schema:
        return self._schema

    def _keep_mask(self, stream: ColumnarBatch, build: ColumnarBatch):
        s_cap, b_cap = stream.capacity, build.capacity
        out_cap = s_cap * b_cap
        j = jnp.arange(out_cap, dtype=jnp.int32)
        si, bi = j // b_cap, j % b_cap
        live = (si < stream.num_rows) & (bi < build.num_rows)
        s_cols = [gather_column(c, si, live) for c in stream.columns]
        b_cols = [gather_column(c, bi, live) for c in build.columns]
        out = ColumnarBatch(tuple(s_cols + b_cols),
                            jnp.asarray(out_cap, jnp.int32))
        keep = live
        if self.condition is not None:
            c = self.condition.eval(out, self.ctx)
            keep = keep & c.data & c.validity
        return out, keep, si, bi

    def _matches(self, keep, si, bi, s_cap: int, b_cap: int):
        # NOT indices_are_sorted: masking drops condition-failing slots to
        # the sentinel segment BETWEEN ascending si values, so the ids are
        # no longer monotone and the sorted-scatter lowering would be
        # unsound
        seg_s = jnp.where(keep, si, s_cap)
        s_m = jax.ops.segment_sum(keep.astype(jnp.int32), seg_s,
                                  num_segments=s_cap + 1)[:s_cap]
        seg_b = jnp.where(keep, bi, b_cap)
        b_m = jax.ops.segment_sum(keep.astype(jnp.int32), seg_b,
                                  num_segments=b_cap + 1)[:b_cap]
        return s_m, b_m

    def _cross_kernel(self, stream: ColumnarBatch, build: ColumnarBatch):
        out, keep, si, bi = self._keep_mask(stream, build)
        if self.join_type in (JoinType.INNER, JoinType.CROSS):
            # no tails -> no match bookkeeping; keep the kernel lean
            return compact(out, keep), None, None
        s_m, b_m = self._matches(keep, si, bi, stream.capacity,
                                 build.capacity)
        # live slots are interleaved (row-major tiles), so always compact
        return compact(out, keep), s_m, b_m

    def _count_kernel(self, stream: ColumnarBatch, build: ColumnarBatch):
        _, keep, si, bi = self._keep_mask(stream, build)
        return self._matches(keep, si, bi, stream.capacity, build.capacity)

    @property
    def num_partitions(self) -> int:
        # RIGHT/FULL outer emit the unmatched-build tail exactly once, so
        # every stream partition folds into one (broadcast build — same
        # policy as HashJoinExec)
        if self.join_type in (JoinType.RIGHT_OUTER, JoinType.FULL_OUTER):
            return 1
        return self.left.num_partitions

    def _build_tiles(self, build: ColumnarBatch, stream_cap: int):
        """(offset, piece) tiles of the build side bounded so one
        expansion stays under max_tile_rows output slots."""
        if stream_cap * build.capacity <= self.max_tile_rows:
            yield 0, build
            return
        tile = max(self.max_tile_rows // stream_cap, 1)
        tile_cap = bucket_capacity(tile)
        n_build = int(build.num_rows)
        for off in range(0, max(n_build, 1), tile_cap):
            yield off, _slice_tile(build, jnp.int32(off),
                                   jnp.int32(tile_cap), tile_cap)

    def do_execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        build_batches = [b for cp in range(self.right.num_partitions)
                         for b in self.right.execute_partition(cp)]
        if not build_batches:
            from ..batch import empty_batch
            build = empty_batch(self.right.output_schema)
        elif len(build_batches) == 1:
            build = build_batches[0]
        elif self.join_type in (JoinType.INNER, JoinType.CROSS):
            # no cross-batch match bookkeeping: stream build batches one
            # at a time instead of materializing a padded concat (these
            # types never fold stream partitions, so read just p)
            for stream in self.left.execute_partition(p):
                for b in build_batches:
                    for _, piece in self._build_tiles(b, stream.capacity):
                        pairs, _, _ = self._cross_jit(stream, piece)
                        yield pairs
            return
        else:
            build = concat_batches(
                build_batches,
                bucket_capacity(sum(b.capacity for b in build_batches)))

        if self.num_partitions == 1 and self.left.num_partitions > 1:
            stream_parts: Sequence[int] = range(self.left.num_partitions)
        else:
            stream_parts = (p,)
        pair_out = self.join_type in (JoinType.INNER, JoinType.CROSS,
                                      JoinType.LEFT_OUTER,
                                      JoinType.RIGHT_OUTER,
                                      JoinType.FULL_OUTER)
        matched_build = jnp.zeros(build.capacity, jnp.int32)
        for sp in stream_parts:
            for stream in self.left.execute_partition(sp):
                s_matched = jnp.zeros(stream.capacity, jnp.int32)
                for off, piece in self._build_tiles(build,
                                                    stream.capacity):
                    if pair_out:
                        pairs, s_m, b_m = self._cross_jit(stream, piece)
                        yield pairs
                    else:
                        s_m, b_m = self._count_jit(stream, piece)
                    if s_m is not None:
                        s_matched = s_matched + s_m
                        matched_build = matched_build.at[
                            off:off + piece.capacity].add(
                            b_m[:min(piece.capacity,
                                     build.capacity - off)])
                yield from self._emit_stream_tail(stream, s_matched)

        if self.join_type in (JoinType.RIGHT_OUTER, JoinType.FULL_OUTER):
            unmatched = build.row_mask() & (matched_build == 0)
            null_left = _null_gather(
                self._empty_like(self.left.output_schema), build.capacity)
            tail = ColumnarBatch(tuple(null_left) + build.columns,
                                 build.num_rows)
            yield compact(tail, unmatched)

    @staticmethod
    def _empty_like(schema: Schema) -> ColumnarBatch:
        from ..batch import empty_batch
        return empty_batch(schema, 1)

    def _emit_stream_tail(self, stream: ColumnarBatch,
                          s_matched) -> Iterator[ColumnarBatch]:
        jt = self.join_type
        if jt in (JoinType.LEFT_OUTER, JoinType.FULL_OUTER):
            unmatched = stream.row_mask() & (s_matched == 0)
            null_right = _null_gather(
                self._empty_like(self.right.output_schema),
                stream.capacity)
            tail = ColumnarBatch(stream.columns + tuple(null_right),
                                 stream.num_rows)
            yield compact(tail, unmatched)
        elif jt is JoinType.LEFT_SEMI:
            yield compact(stream, s_matched > 0)
        elif jt is JoinType.LEFT_ANTI:
            yield compact(stream, stream.row_mask() & (s_matched == 0))
        elif jt is JoinType.EXISTENCE:
            exists = DeviceColumn((s_matched > 0), stream.row_mask(),
                                  None, T.BOOLEAN)
            yield ColumnarBatch(stream.columns + (exists,),
                                stream.num_rows)
