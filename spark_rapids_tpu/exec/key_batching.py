"""Key-batching: split partitions into bounded, key-complete batches.

Reference: GpuKeyBatchingIterator.scala (236 LoC) — the reference splits a
stream of batches on group-key boundaries so per-key operators (windows)
never see a key straddling two batches and never hold an unbounded batch.

TPU-first shape: instead of the reference's iterator that carries leftover
rows between cudf batches, the whole stream partition is sorted by the
keys ONCE (one lax.sort — windows need that sort anyway) and the group
boundary positions come back to the host, which picks cut points on whole
groups closest to the row target. Each emitted batch is a static-shape
slice, so downstream kernels compile once per bucket size.

What this bounds: the DOWNSTREAM operator's per-batch working set (window
scans allocate several columns per expression over the batch). The
batching sort itself still materializes the partition once — same peak as
the previous concat-whole-partition behavior, not worse; a spill-aware
chunked pre-sort (through OutOfCoreSorter) is the refinement if window
inputs ever exceed HBM on their own.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..batch import ColumnarBatch, Schema, bucket_capacity
from ..expressions.base import EvalContext, Expression
from .base import UnaryExec
from .basic import bind_all
from .common import (adjacent_equal, concat_batches, gather_column,
                     slice_batch, sort_operands)


class KeyBatchingExec(UnaryExec):
    """Re-chunk each input partition into batches that hold WHOLE key
    groups and approach ``target_rows``. Downstream execs can detect the
    guarantee through ``key_complete_for`` and process batch-at-a-time
    instead of concatenating the partition."""

    def __init__(self, keys: Sequence[Expression], child,
                 target_rows: int = 1 << 20,
                 ctx: Optional[EvalContext] = None):
        super().__init__(child, ctx)
        self.keys = bind_all(keys, child.output_schema)
        self.target_rows = target_rows

        def prep(batch: ColumnarBatch):
            key_cols = [e.eval(batch, self.ctx) for e in self.keys]
            live = batch.row_mask()
            k = len(key_cols)
            from .common import may_skip_null_lane
            nullable = [not may_skip_null_lane(e) for e in self.keys]
            ops = sort_operands(key_cols, [False] * k, [True] * k, live,
                                nullable)
            iota = jnp.arange(batch.capacity, dtype=jnp.int32)
            perm = jax.lax.sort(ops + [iota], num_keys=len(ops) + 1)[-1]
            cols = tuple(gather_column(c, perm) for c in batch.columns)
            skeys = [gather_column(c, perm) for c in key_cols]
            sorted_live = jnp.arange(batch.capacity) < batch.num_rows
            new_group = sorted_live & ~adjacent_equal(skeys)
            return ColumnarBatch(cols, batch.num_rows), new_group

        self._prep_jit = jax.jit(prep)
        self._slice_jit = jax.jit(
            lambda b, start, count, cap: slice_batch(b, start, count, cap),
            static_argnums=3)

    @property
    def output_schema(self) -> Schema:
        return self.child.output_schema

    @property
    def key_complete_for(self) -> str:
        """Identity of the guarantee: every emitted batch contains whole
        groups of these (bound) keys."""
        return repr(list(self.keys))

    def do_execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        batches = list(self.child.execute_partition(p))
        if not batches:
            return
        total = sum(int(b.num_rows) for b in batches)
        if total == 0:
            return
        if len(batches) == 1:
            merged = batches[0]
        else:
            cap = bucket_capacity(sum(b.capacity for b in batches))
            merged = concat_batches(batches, cap)
        srt, new_group = self._prep_jit(merged)
        if total <= self.target_rows:
            yield srt
            return
        # group start positions -> host; cut on whole groups at the LAST
        # start that keeps the batch <= target_rows (a batch exceeds the
        # target only when one single group does — the same bound
        # GpuKeyBatchingIterator guarantees)
        starts = np.flatnonzero(np.asarray(new_group))
        n = int(srt.num_rows)
        cuts: List[int] = [0]
        prev = 0
        for s in list(starts[1:]) + [n]:
            if s - cuts[-1] > self.target_rows and prev > cuts[-1]:
                cuts.append(int(prev))
            prev = int(s)
        if cuts[-1] != n:
            cuts.append(n)
        for lo, hi in zip(cuts, cuts[1:]):
            if hi > lo:
                yield self._slice_jit(srt, lo, hi - lo,
                                      bucket_capacity(hi - lo))
