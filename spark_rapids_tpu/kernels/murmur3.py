"""Pallas murmur3 row-hash kernel.

Spark Murmur3_x86_32 over an int32 column with per-row seeds (the fold-left
chain hash of expressions/hashing.py): one VMEM-resident fused kernel —
load tile, run the whole mix/fmix chain in registers, store tile. Tiled
(8, 128) per the 32-bit tiling constraint; callers pad row counts to the
1024-row tile (capacity buckets already are powers of two >= 128).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

_TILE_ROWS = 8 * 128


def _u32(x):
    return x.astype(jnp.uint32)


def _kernel(data_ref, valid_ref, seed_ref, out_ref):
    k = data_ref[:].astype(jnp.int32).view(jnp.uint32)
    seed = seed_ref[:].view(jnp.uint32)
    c1 = jnp.uint32(0xCC9E2D51)
    c2 = jnp.uint32(0x1B873593)
    k1 = k * c1
    k1 = (k1 << 15) | (k1 >> 17)
    k1 = k1 * c2
    h1 = seed ^ k1
    h1 = (h1 << 13) | (h1 >> 19)
    h1 = h1 * jnp.uint32(5) + jnp.uint32(0xE6546B64)
    h1 = h1 ^ jnp.uint32(4)
    h1 = h1 ^ (h1 >> 16)
    h1 = h1 * jnp.uint32(0x85EBCA6B)
    h1 = h1 ^ (h1 >> 13)
    h1 = h1 * jnp.uint32(0xC2B2AE35)
    h1 = h1 ^ (h1 >> 16)
    valid = valid_ref[:]
    out_ref[:] = jnp.where(valid, h1, seed).view(jnp.int32)


@functools.partial(jax.jit, static_argnames=("interpret",))
def pallas_murmur3_int32(data: jax.Array, validity: jax.Array,
                         seeds: jax.Array, interpret: bool = False
                         ) -> jax.Array:
    """hashInt per row: data int32[n], validity bool[n], seeds int32[n]
    (the running fold-left hash) -> int32[n]. n must be a multiple of 1024.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = data.shape[0]
    assert n % _TILE_ROWS == 0, n
    tiles = n // _TILE_ROWS
    shape2d = (tiles * 8, 128)
    d2 = data.reshape(shape2d)
    v2 = validity.reshape(shape2d)
    s2 = seeds.reshape(shape2d)
    # index map: `0` must be i32 — under x64 mode a literal 0 traces as
    # i64 and Mosaic rejects the mixed (i32, i64) return
    block = pl.BlockSpec((8, 128), lambda i: (i, i - i),
                         memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        _kernel,
        grid=(tiles,),
        out_shape=jax.ShapeDtypeStruct(shape2d, jnp.int32),
        in_specs=[block, block, block],
        out_specs=block,
        interpret=interpret,
    )(d2, v2, s2)
    return out.reshape(n)


def pallas_available() -> bool:
    """True when the default backend can run compiled Pallas TPU kernels."""
    try:
        return jax.default_backend() not in ("cpu",)
    except Exception:
        return False
