"""Pallas substring-search kernel (cudf string-search role; reference:
sql-plugin/.../sql/rapids/stringFunctions.scala GpuContains/GpuStringLocate).

The XLA formulation of window matching (expressions/strings._window_match)
rolls the whole [n, max_len] byte matrix once per pattern byte — k full
HBM passes for a k-byte pattern. This kernel loads each tile into VMEM
ONCE and runs all k shifted compares in-register: one read pass + one
write pass, ~k/2 x less HBM traffic for long patterns.

Layout trick: 8-bit Mosaic tiles want 128-wide rows, but string columns
are [n, max_len] with max_len typically 32/64. When max_len divides 128,
pack 128//max_len strings per VMEM row — shifted compares never produce
FALSE matches across string boundaries for match starts the caller keeps
(start <= max_len - k), because s + j < max_len stays inside the packed
string's byte range.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_ROW_TILE = 256          # packed 128-byte rows per grid step


def _mk_kernel(pat: bytes, ml: int):
    k = len(pat)

    def kernel(data_ref, out_ref):
        # widen to i32 in-register: v5e Mosaic has no 8-bit vector compare
        d = data_ref[:].astype(jnp.int32)     # [T, 128]
        m = jnp.ones(d.shape, jnp.int32)
        for j in range(k):
            if j == 0:
                shifted = d
            else:
                # static shift left by j within the packed row; the tail
                # bytes compare garbage but fall outside kept starts
                pad = jnp.zeros((d.shape[0], j), jnp.int32)
                shifted = jnp.concatenate([d[:, j:], pad], axis=1)
            m = m & (shifted == jnp.int32(pat[j])).astype(jnp.int32)
        out_ref[:] = m.astype(jnp.uint8)

    return kernel


@functools.partial(jax.jit, static_argnames=("pat", "ml", "interpret"))
def _pallas_match_packed(packed: jax.Array, pat: bytes, ml: int,
                         interpret: bool = False) -> jax.Array:
    rows = packed.shape[0]
    grid = (rows // _ROW_TILE,)
    # Mosaic rejects the i64 scalars the global x64 mode would put in the
    # grid index maps ("failed to legalize func.return"); the kernel is
    # all-32-bit, so trace it in an x64-disabled scope
    with jax.enable_x64(False):
        return pl.pallas_call(
            _mk_kernel(pat, ml),
            out_shape=jax.ShapeDtypeStruct(packed.shape, jnp.uint8),
            grid=grid,
            in_specs=[pl.BlockSpec((_ROW_TILE, 128), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((_ROW_TILE, 128), lambda i: (i, 0)),
            interpret=interpret,
        )(packed)


def supports(n: int, ml: int, pat: bytes) -> bool:
    """Kernel applicability: packable row widths, pattern fits, enough
    rows to amortize the launch."""
    if not (0 < len(pat) <= ml):
        return False
    if ml > 128 or 128 % ml != 0:
        return False
    return n >= (128 // ml) * _ROW_TILE


def pallas_window_match(data: jax.Array, lengths: jax.Array, pat: bytes,
                        interpret: bool = False) -> jax.Array:
    """match[row, s] = pat equals data[row, s:s+k]; same contract as
    expressions/strings._window_match."""
    n, ml = data.shape
    k = len(pat)
    per = 128 // ml
    pack_rows = -(-n // per)
    # pad row count so the packed matrix tiles evenly; when the row count
    # already aligns (power-of-two capacities do), packing is a FREE
    # reshape — no copy pass
    row_align = _ROW_TILE
    padded_rows = -(-pack_rows // row_align) * row_align
    if padded_rows * per == n:
        packed = data.reshape(padded_rows, 128)
    else:
        flat = jnp.zeros((padded_rows * per, ml), jnp.uint8)
        flat = flat.at[:n].set(data)
        packed = flat.reshape(padded_rows, 128)
    m = _pallas_match_packed(packed, pat, ml, interpret)
    m = m.reshape(padded_rows * per, ml)[:n]
    valid_start = jnp.arange(ml)[None, :] + k <= lengths[:, None]
    return (m != 0) & valid_start
