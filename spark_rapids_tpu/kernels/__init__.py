"""Pallas TPU kernels for hot operations.

Where XLA's fusion already saturates the VPU/MXU (elementwise chains,
matmuls, sorts) the engine stays on plain jnp; Pallas enters where manual
tiling or memory placement beats the compiler (SURVEY.md §7: joins,
string/regex scanning). First resident: the murmur3 row-hash kernel —
every shuffle route and join build hashes every row, and the Pallas version
keeps the whole multi-column hash chain in VMEM registers instead of
round-tripping intermediate columns (guide: /opt/skills/guides/
pallas_guide.md).
"""

from .murmur3 import pallas_available, pallas_murmur3_int32

__all__ = ["pallas_murmur3_int32", "pallas_available"]
