from .daemon import (PythonWorkerError, WorkerPool, shared_pool,
                     worker_apply)

__all__ = ["PythonWorkerError", "WorkerPool", "shared_pool", "worker_apply"]
