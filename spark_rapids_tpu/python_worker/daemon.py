"""Forked Python UDF worker pool — process isolation for pandas UDFs.

Reference: python/rapids/daemon.py + worker.py (the GPU-aware PySpark
daemon fork) and PythonWorkerSemaphore.scala:41. Round 2 ran UDFs
in-process: a crashing UDF killed the executor and the GIL serialized
workers (VERDICT r2 Missing #6). Here each worker is a FORKED subprocess;
tables cross as Arrow IPC stream bytes over a pipe (the same wire format
the reference speaks over its daemon socket), and a worker death surfaces
as ``PythonWorkerError`` failing the QUERY — the executor lives on and the
pool respawns the seat.

UDFs must be picklable to ride to a worker (module-level functions,
functools.partial of them, ...). Closures/lambdas are not; callers detect
that with ``picklable()`` and run those in-process — explicit downgrade,
not a crash.
"""

from __future__ import annotations

import io
import multiprocessing as mp
import pickle
import threading
import traceback
from typing import Callable, List, Optional

import pyarrow as pa


class PythonWorkerError(RuntimeError):
    """A UDF failed or its worker process died; the query fails, the
    executor survives (reference: task failure, not executor exit)."""


def _table_to_ipc(table: pa.Table) -> bytes:
    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, table.schema) as w:
        w.write_table(table)
    return sink.getvalue()


def _table_from_ipc(buf: bytes) -> pa.Table:
    with pa.ipc.open_stream(pa.BufferReader(buf)) as r:
        return r.read_all()


def _worker_main(conn) -> None:
    """Child loop: (pickled fn+extras, Arrow IPC in) -> (Arrow IPC out)."""
    while True:
        try:
            msg = conn.recv_bytes()
        except (EOFError, OSError):
            return
        if msg == b"__stop__":
            return
        try:
            fn_blob_len = int.from_bytes(msg[:8], "little")
            fn, extras = pickle.loads(msg[8:8 + fn_blob_len])
            table = _table_from_ipc(msg[8 + fn_blob_len:])
            out = fn(table, *extras)
            conn.send_bytes(b"ok" + _table_to_ipc(out))
        except BaseException:                       # noqa: BLE001
            try:
                conn.send_bytes(b"er" + traceback.format_exc()
                                .encode("utf-8", "replace"))
            except (BrokenPipeError, OSError):
                return


def picklable(obj) -> bool:
    try:
        pickle.dumps(obj)
        return True
    except Exception:                               # noqa: BLE001
        return False


class _Seat:
    def __init__(self, ctx):
        self.ctx = ctx
        self.spawn()

    def spawn(self) -> None:
        self.parent, child = mp.Pipe()
        self.proc = self.ctx.Process(target=_worker_main, args=(child,),
                                     daemon=True)
        self.proc.start()
        child.close()

    def close(self) -> None:
        try:
            self.parent.send_bytes(b"__stop__")
        except (BrokenPipeError, OSError):
            pass
        self.proc.join(timeout=1)
        if self.proc.is_alive():
            self.proc.terminate()


class WorkerPool:
    """N forked seats; a call checks out a seat, ships (fn, table), and
    awaits the Arrow reply. A dead seat raises and is respawned."""

    def __init__(self, size: int = 4, method: str = "spawn"):
        # spawn by default: forking a multithreaded JAX process can
        # deadlock on held locks (the CPython fork warning); spawn pays a
        # one-time import cost per seat instead
        self.ctx = mp.get_context(method)
        self._seats: List[_Seat] = []
        self._free: List[_Seat] = []
        self._cv = threading.Condition()
        self.size = size

    def _ensure(self) -> None:
        if not self._seats:
            self._seats = [_Seat(self.ctx) for _ in range(self.size)]
            self._free = list(self._seats)

    def apply(self, fn: Callable, table: pa.Table,
              extras: tuple = (), blob: Optional[bytes] = None) -> pa.Table:
        with self._cv:
            self._ensure()
            while not self._free:
                self._cv.wait()
            seat = self._free.pop()
        try:
            if blob is None:
                blob = pickle.dumps((fn, extras))
            msg = len(blob).to_bytes(8, "little") + blob \
                + _table_to_ipc(table)
            try:
                seat.parent.send_bytes(msg)
                reply = seat.parent.recv_bytes()
            except (EOFError, BrokenPipeError, OSError):
                exit_code = seat.proc.exitcode
                seat.close()
                seat.spawn()        # executor survives; seat respawns
                raise PythonWorkerError(
                    f"python worker died (exit {exit_code}) while running "
                    f"{getattr(fn, '__name__', fn)!r}")
            if reply[:2] == b"er":
                raise PythonWorkerError(
                    "python UDF raised in worker:\n"
                    + reply[2:].decode("utf-8", "replace"))
            return _table_from_ipc(reply[2:])
        finally:
            with self._cv:
                self._free.append(seat)
                self._cv.notify()

    def close(self) -> None:
        with self._cv:
            for s in self._seats:
                s.close()
            self._seats = []
            self._free = []


_POOL: Optional[WorkerPool] = None
_POOL_LOCK = threading.Lock()


def shared_pool(size: Optional[int] = None) -> WorkerPool:
    global _POOL
    with _POOL_LOCK:
        if _POOL is None:
            if size is None:
                from ..config import PYTHON_WORKER_PROCESSES, _REGISTRY
                size = int(_REGISTRY[PYTHON_WORKER_PROCESSES.key].default)
            _POOL = WorkerPool(size)
        return _POOL


def worker_apply(fn: Callable, table: pa.Table, extras: tuple = (),
                 use_daemon: bool = True,
                 pool_size: Optional[int] = None) -> pa.Table:
    """Run ``fn(table, *extras) -> table`` in a worker when the payload
    pickles (ONE dumps serves both the check and the wire message);
    otherwise in-process (lambdas/closures). ``pool_size`` sizes the
    shared pool on FIRST use (spark.rapids.tpu.python.worker.processes)."""
    if use_daemon:
        try:
            blob = pickle.dumps((fn, extras))
        except Exception:                           # noqa: BLE001
            blob = None
        if blob is not None:
            return shared_pool(pool_size).apply(fn, table, extras,
                                                blob=blob)
    return fn(table, *extras)
