"""Dictionary-encoded string columns: the compressed data plane.

The reference keeps string data in wire form until the device needs it —
nvcomp-compressed buffers and cudf dictionary columns flow through shuffle
and spill, and operators like join/group-by compare dictionary keys
(reference: GpuColumnVector dictionary support + TableCompressionCodec;
"GPU Acceleration of SQL Analytics on Compressed Data" in PAPERS.md shows
the same win of operating directly on the encoded form). Here the encoded
representation is::

    data         int32[cap]        per-row code into the dictionary
    dict_data    uint8[card, ml]   distinct padded UTF-8 strings
    dict_lengths int32[card]       byte length per dictionary entry

riding in the existing ``DeviceColumn`` (lengths lane unused — per-row
lengths rematerialize as ``dict_lengths[codes]`` at decode).

INVARIANTS (everything downstream relies on these):
  1. Only STRING columns are ever dict-encoded.
  2. Dictionary entries are DISTINCT ``(bytes, length)`` pairs in ascending
     byte-lexicographic order with the length as tiebreak — exactly
     ``sort_operands``' string order. Hence, within one column,
     *code equality == string equality* and *code order == string order*,
     so group-by keys sort/compare on one int32 lane instead of
     ``max_len/8 + 1`` word lanes.
  3. Null rows carry code 0 with validity False (payload-zeroing parity
     with the plain path).
  4. ``card`` is bucketed to a power of two (>= 8); padding entries are
     all-zero, never referenced by a live code, and exist purely to bound
     XLA recompiles (same policy as row-capacity bucketing).

Cross-batch ops (exchange read coalesce) unify per-batch dictionaries with
a device code-remap (``unify_dict_batches``); any site that cannot prove a
shared dictionary decodes instead — decode is one gather, and bit-for-bit
identical to the padded-matrix path.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import types as T
from .batch import ColumnarBatch, DeviceColumn, Schema
from .types import TypeKind

MIN_DICT_CAPACITY = 8


def bucket_card(card: int) -> int:
    """Dictionary capacity bucket (power of two, >= MIN_DICT_CAPACITY)."""
    if card <= MIN_DICT_CAPACITY:
        return MIN_DICT_CAPACITY
    return 1 << (card - 1).bit_length()


# ---------------------------------------------------------------------------
# fallback reason tags (the willNotWork-style record the window
# over-capacity fallback established in PR 4 — overrides.py tags at plan
# time; cardinality is runtime information, so the tag records here and
# Session.fell_back surfaces it next to the plan-time reasons)
# ---------------------------------------------------------------------------

_FALLBACKS: dict = {}        # reason -> sequence number of its LAST record
_FALLBACK_SEQ = 0
_FALLBACK_CAP = 256          # reason strings embed per-batch numbers, so
#                              distinct strings can keep arriving in a
#                              long-lived process: evict oldest-recorded
_FALLBACK_LOCK = threading.Lock()


def record_fallback(reason: str) -> None:
    global _FALLBACK_SEQ
    with _FALLBACK_LOCK:
        _FALLBACK_SEQ += 1
        _FALLBACKS[reason] = _FALLBACK_SEQ
        if len(_FALLBACKS) > _FALLBACK_CAP:
            del _FALLBACKS[min(_FALLBACKS, key=_FALLBACKS.get)]


def fallback_mark() -> int:
    """Sequence watermark for per-session attribution: reasons recorded
    AFTER the mark show up in fallback_reasons(since=mark). A repeat of
    an already-seen reason bumps its sequence, so a session always sees
    fallbacks that happened on its own watch (storage stays one entry
    per distinct reason)."""
    with _FALLBACK_LOCK:
        return _FALLBACK_SEQ


def fallback_reasons(since: int = 0) -> List[str]:
    with _FALLBACK_LOCK:
        return [r for r, s in _FALLBACKS.items() if s > since]


def clear_fallbacks() -> None:
    with _FALLBACK_LOCK:
        _FALLBACKS.clear()


def dict_conf(conf=None) -> Tuple[bool, int, float]:
    """(enabled, max_cardinality, max_cardinality_fraction) — from the
    given RapidsTpuConf or the registry defaults."""
    from .config import (DICT_ENCODING_ENABLED, DICT_MAX_CARDINALITY,
                         DICT_MAX_CARD_FRACTION, RapidsTpuConf)
    c = conf or RapidsTpuConf()
    return (bool(c.get(DICT_ENCODING_ENABLED.key)),
            int(c.get(DICT_MAX_CARDINALITY.key)),
            float(c.get(DICT_MAX_CARD_FRACTION.key)))


# ---------------------------------------------------------------------------
# host-side encode (np.unique gives the sorted-distinct invariant for free)
# ---------------------------------------------------------------------------

def _sort_keys(mat: np.ndarray, lengths: np.ndarray) -> np.ndarray:
    """Void-typed memcmp keys whose order is (bytes, length) — the string
    sort order of sort_operands (padding 0x00 sorts below content, and the
    big-endian length word breaks ties for embedded-NUL strings)."""
    ml = mat.shape[1]
    be_len = np.ascontiguousarray(
        lengths.astype(">i4")).view(np.uint8).reshape(-1, 4)
    keyed = np.ascontiguousarray(
        np.concatenate([mat, be_len], axis=1))
    return keyed.view(np.dtype((np.void, ml + 4))).reshape(-1)


def encode_strings_np(mat: np.ndarray, lengths: np.ndarray,
                      validity: np.ndarray
                      ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(dict_mat[card, ml], dict_lens[card], codes[n]) from a padded byte
    matrix. Dictionary is sorted-distinct over VALID rows; null rows get
    code 0. ``card`` here is the true cardinality (bucket separately)."""
    n, ml = mat.shape
    lengths = np.where(validity, lengths, 0).astype(np.int32)
    mat = np.where(validity[:, None], mat, 0).astype(np.uint8)
    if not validity.any():
        return (np.zeros((0, ml), np.uint8), np.zeros(0, np.int32),
                np.zeros(n, np.int32))
    keys = _sort_keys(mat, lengths)
    vkeys = keys[validity]
    uniq, inv = np.unique(vkeys, return_inverse=True)
    # representative row per unique key (first occurrence)
    first = np.full(len(uniq), -1, np.int64)
    vidx = np.nonzero(validity)[0]
    # reversed so the FIRST occurrence wins the final write
    first[inv[::-1]] = vidx[::-1]
    dict_mat = mat[first]
    dict_lens = lengths[first]
    codes = np.zeros(n, np.int32)
    codes[validity] = inv.astype(np.int32)
    return dict_mat, dict_lens, codes


def _pad_dict(dict_mat: np.ndarray, dict_lens: np.ndarray
              ) -> Tuple[np.ndarray, np.ndarray]:
    card = dict_mat.shape[0]
    cap = bucket_card(card)
    if cap == card:
        return dict_mat, dict_lens
    pm = np.zeros((cap, dict_mat.shape[1]), np.uint8)
    pm[:card] = dict_mat
    pl = np.zeros(cap, np.int32)
    pl[:card] = dict_lens
    return pm, pl


def encode_column(col: DeviceColumn,
                  max_card: Optional[int] = None) -> Optional[DeviceColumn]:
    """Host-round-trip encode of a PLAIN device string column (test/bench
    utility — the scan boundary encodes straight from arrow instead).
    Returns None when the TRUE cardinality (pre-bucketing) exceeds
    ``max_card`` — the same threshold the scan boundary applies."""
    assert col.dtype.kind is TypeKind.STRING and col.dict_data is None
    mat = np.asarray(jax.device_get(col.data))
    lengths = np.asarray(jax.device_get(col.lengths))
    validity = np.asarray(jax.device_get(col.validity))
    dm, dl, codes = encode_strings_np(mat, lengths, validity)
    if max_card is not None and dm.shape[0] > max_card:
        return None
    dm, dl = _pad_dict(dm, dl)
    return DeviceColumn(jnp.asarray(codes), jnp.asarray(validity), None,
                        col.dtype, None, jnp.asarray(dm), jnp.asarray(dl))


def encode_batch(batch: ColumnarBatch, schema: Schema,
                 max_card: Optional[int] = None) -> ColumnarBatch:
    """Encode every eligible string column (test/bench utility)."""
    cols = []
    for c, f in zip(batch.columns, schema):
        if (f.dtype.kind is TypeKind.STRING and not c.is_struct
                and c.dict_data is None):
            enc = encode_column(c, max_card)
            if enc is not None:
                c = enc
        cols.append(c)
    return ColumnarBatch(tuple(cols), batch.num_rows)


# ---------------------------------------------------------------------------
# device-side decode (one gather; bit-for-bit the padded-matrix layout)
# ---------------------------------------------------------------------------

def decode_column(col: DeviceColumn) -> DeviceColumn:
    """Dict column -> plain padded-matrix column. Traced-safe (pure jnp),
    so lazy decode fuses into whatever kernel needed the bytes."""
    if col.dict_data is None:
        return col
    card = col.dict_data.shape[0]
    idx = jnp.clip(col.data, 0, card - 1)
    data = jnp.take(col.dict_data, idx, axis=0)
    lengths = jnp.take(col.dict_lengths, idx)
    # payload-zero invalid rows: parity with make_column/_strings_to_matrix
    data = jnp.where(col.validity[:, None], data, 0)
    lengths = jnp.where(col.validity, lengths, 0)
    return DeviceColumn(data, col.validity, lengths, col.dtype)


def decode_batch(batch: ColumnarBatch) -> ColumnarBatch:
    if not any(c.dict_data is not None for c in batch.columns
               if not c.is_struct):
        return batch
    cols = tuple(decode_column(c) if not c.is_struct else c
                 for c in batch.columns)
    return ColumnarBatch(cols, batch.num_rows)


def dict_entries_column(col: DeviceColumn) -> DeviceColumn:
    """The dictionary itself as a (card-capacity) plain string column —
    the evaluation domain for predicate pushdown: evaluate once per
    DISTINCT value, then gather the [card] result through the codes."""
    assert col.dict_data is not None
    card = col.dict_data.shape[0]
    return DeviceColumn(col.dict_data,
                        jnp.ones(card, bool), col.dict_lengths, col.dtype)


# ---------------------------------------------------------------------------
# cross-batch dictionary unification (device code-remap)
# ---------------------------------------------------------------------------

def unify_dict_columns(cols: Sequence[DeviceColumn]
                       ) -> Optional[List[DeviceColumn]]:
    """Re-express dict columns (same logical column, different per-batch
    dictionaries) over ONE merged sorted dictionary via a device
    code-remap. Host-side merge over the small dictionaries, one
    ``jnp.take`` per piece for the codes. Returns None when any piece is
    not dict-encoded, or when the merged cardinality would exceed the
    dictEncoding.maxCardinality registry default (caller decodes instead;
    the session conf is not threaded to this eager boundary). EAGER only
    — dictionary contents must be concrete, so never call under jit
    tracing.

    Bucket-padding rows (all-zero, length 0) are indistinguishable from a
    real empty-string entry once padded, so the merged union may carry one
    phantom "" entry no live code references — correctness-neutral, at
    most one entry of wire overhead."""
    if not cols or any(c.dict_data is None for c in cols):
        return None
    first = cols[0].dict_data
    if all(c.dict_data is first for c in cols[1:]) or len(cols) == 1:
        return list(cols)
    mats = [np.asarray(jax.device_get(c.dict_data)) for c in cols]
    lens = [np.asarray(jax.device_get(c.dict_lengths)) for c in cols]
    if all(m.shape == mats[0].shape and np.array_equal(m, mats[0])
           and np.array_equal(l, lens[0])
           for m, l in zip(mats[1:], lens[1:])):
        # byte-identical dictionaries (the common exchange-read case:
        # every piece deserialized from one upstream batch carries its
        # own copy): codes already agree — share ONE device object so
        # concat_columns keeps the encoding, skip the merge+remap
        return [c.replace(dict_data=cols[0].dict_data,
                          dict_lengths=cols[0].dict_lengths) for c in cols]
    ml = max(m.shape[1] for m in mats)
    mats = [np.pad(m, ((0, 0), (0, ml - m.shape[1]))) if m.shape[1] < ml
            else m for m in mats]
    all_keys = np.concatenate([_sort_keys(m, l)
                               for m, l in zip(mats, lens)])
    merged_keys = np.unique(all_keys)          # sorted union
    _, merge_max_card, _ = dict_conf()
    if len(merged_keys) > merge_max_card:
        record_fallback(
            f"merged dictionary cardinality {len(merged_keys)} across "
            f"{len(cols)} batches exceeds "
            f"spark.rapids.tpu.dictEncoding.maxCardinality="
            f"{merge_max_card}; decoding at the concat boundary instead")
        return None
    merged = merged_keys.view(np.uint8).reshape(len(merged_keys), ml + 4)
    merged_mat = np.ascontiguousarray(merged[:, :ml])
    merged_lens = np.ascontiguousarray(
        merged[:, ml:]).view(">i4").astype(np.int32).reshape(-1)
    pm, pl = _pad_dict(merged_mat, merged_lens)
    dev_mat = jnp.asarray(pm)
    dev_lens = jnp.asarray(pl)
    out = []
    for c, m, l in zip(cols, mats, lens):
        remap = np.searchsorted(merged_keys, _sort_keys(m, l))
        remap = np.clip(remap, 0, max(len(merged_keys) - 1, 0))
        codes = jnp.take(jnp.asarray(remap.astype(np.int32)),
                         jnp.clip(c.data, 0, m.shape[0] - 1))
        out.append(DeviceColumn(codes, c.validity, None, c.dtype, None,
                                dev_mat, dev_lens))
    return out


def unify_dict_batches(batches: Sequence[ColumnarBatch],
                       ) -> List[ColumnarBatch]:
    """Per column position: unify when every piece is dict-encoded, decode
    when encodings are mixed, pass through otherwise. Called EAGERLY at
    concat boundaries (exchange read coalesce, CoalesceBatchesExec) so
    ``concat_columns`` sees one shared dictionary object and keeps the
    encoded form across the concat."""
    if len(batches) <= 1:
        return list(batches)
    ncols = batches[0].num_columns
    new_cols: List[List[DeviceColumn]] = [list(b.columns) for b in batches]
    for i in range(ncols):
        cols = [b.columns[i] for b in batches]
        if any(not c.is_struct and c.dict_data is not None for c in cols):
            unified = unify_dict_columns(cols)
            if unified is None:
                unified = [decode_column(c) if not c.is_struct else c
                           for c in cols]
            for bi, c in enumerate(unified):
                new_cols[bi][i] = c
    return [ColumnarBatch(tuple(cs), b.num_rows)
            for cs, b in zip(new_cols, batches)]


# ---------------------------------------------------------------------------
# arrow boundary (the scan hand-off: RLE_DICTIONARY page codes -> HBM)
# ---------------------------------------------------------------------------

def column_from_arrow_dictionary(arr, dtype, capacity: int,
                                 truncate_strings: bool = False,
                                 name: str = "",
                                 conf3: Optional[tuple] = None
                                 ) -> Optional[DeviceColumn]:
    """Build a dict-encoded device column from a pa.DictionaryArray
    WITHOUT materializing per-row bytes — the byte matrix is built once
    per DISTINCT value (the scanner hands page codes straight to HBM).
    Returns None when the column must take the padded-matrix fallback
    (conf off / over the cardinality threshold / null dictionary entries),
    recording the reason tag."""
    import pyarrow as pa
    from .batch import _strings_to_matrix
    enabled, max_card, max_frac = conf3 or dict_conf()
    n = len(arr)
    card = len(arr.dictionary)
    colname = f"column {name!r}: " if name else ""
    if not enabled:
        record_fallback(f"{colname}dictionary-encoded scan column decoded "
                        f"to padded bytes: "
                        f"spark.rapids.tpu.dictEncoding.enabled is false")
        return None
    if card > max_card:
        record_fallback(
            f"{colname}dictionary cardinality {card} exceeds "
            f"spark.rapids.tpu.dictEncoding.maxCardinality={max_card}; "
            f"falling back to the padded byte-matrix path")
        return None
    if n > 0 and card > max_frac * n:
        record_fallback(
            f"{colname}dictionary cardinality {card} exceeds "
            f"{max_frac:g} of {n} rows "
            f"(spark.rapids.tpu.dictEncoding.maxCardinalityFraction); "
            f"encoding would not shrink the column")
        return None
    if arr.dictionary.null_count:
        record_fallback(f"{colname}dictionary contains null entries; "
                        f"falling back to the padded byte-matrix path")
        return None
    dmat, dlens = _strings_to_matrix(arr.dictionary.cast(pa.string()),
                                     dtype.max_len, truncate_strings)
    # canonicalize: SORTED-DISTINCT dictionary (invariant 2), codes
    # remapped through the inverse. np.unique also DEDUPLICATES — arrow
    # dictionaries may legally repeat values, and max_len truncation can
    # collapse distinct entries; duplicate entries would silently break
    # "code equality == string equality" downstream.
    _, first_idx, inv = np.unique(_sort_keys(dmat, dlens),
                                  return_index=True, return_inverse=True)
    inv = inv.astype(np.int32)
    dmat = dmat[first_idx]
    dlens = dlens[first_idx]
    if arr.null_count:
        validity = np.asarray(arr.is_valid())
    else:
        validity = np.ones(n, dtype=bool)
    idx_arr = arr.indices
    if idx_arr.null_count:
        idx_arr = idx_arr.fill_null(0)
    raw_codes = np.asarray(idx_arr.to_numpy(zero_copy_only=False),
                           dtype=np.int64)
    codes = np.zeros(n, np.int32)
    if card:
        codes = inv[np.clip(raw_codes, 0, card - 1)]
    codes = np.where(validity, codes, 0).astype(np.int32)
    pm, pl = _pad_dict(dmat, dlens)
    pad_codes = np.zeros(capacity, np.int32)
    pad_codes[:n] = codes
    pad_valid = np.zeros(capacity, bool)
    pad_valid[:n] = validity
    return DeviceColumn(jnp.asarray(pad_codes), jnp.asarray(pad_valid),
                        None, dtype, None, jnp.asarray(pm),
                        jnp.asarray(pl))


def dictionary_encode_arrow(table):
    """dictionary_encode every string column of an arrow table — the
    form the RLE_DICTIONARY scan hand-off produces. Shared by
    ``bench.py --wire``, the exchange microbench dict mode, and the
    differential tests."""
    import pyarrow as pa
    return pa.table(
        {c: (table[c].combine_chunks().dictionary_encode()
             if pa.types.is_string(table[c].type)
             or pa.types.is_large_string(table[c].type) else table[c])
         for c in table.column_names})


def dict_wire_bytes(batch: ColumnarBatch) -> Tuple[int, int]:
    """(encoded_bytes, raw_bytes) the batch's string lanes occupy on the
    wire, from the layout alone (no serialization): ``raw`` is what the
    padded-matrix form would ship; ``encoded`` is what the current
    representation ships (identical when nothing is dict-encoded). The
    BENCH sidecar measures real serialized frames instead — this is the
    cheap accounting twin, pinned against it by tests."""
    enc = raw = 0
    for c in batch.columns:
        if c.is_struct or c.dtype.kind is not TypeKind.STRING:
            continue
        cap = c.capacity
        ml = c.dtype.max_len
        raw += cap * ml + 4 * cap            # byte matrix + lengths
        if c.dict_data is not None:
            card = c.dict_data.shape[0]
            enc += 4 * cap + card * ml + 4 * card   # codes + dict
        else:
            enc += cap * ml + 4 * cap
    return enc, raw
